#!/bin/bash
# Single-node minikube rig for CPU-only development of the stack
# (counterpart of reference utils/install-minikube-cluster.sh, which
# installs minikube + the NVIDIA GPU operator; a TPU stack needs no
# device operator — engines run tiny models on CPU XLA in this rig,
# matching the values-01 minimal example).
set -euo pipefail

if ! command -v minikube >/dev/null; then
    echo "==> Installing minikube"
    curl -LO https://storage.googleapis.com/minikube/releases/latest/minikube-linux-amd64
    sudo install minikube-linux-amd64 /usr/local/bin/minikube
    rm minikube-linux-amd64
fi

if ! command -v kubectl >/dev/null; then
    echo "==> Installing kubectl"
    curl -LO "https://dl.k8s.io/release/$(curl -Ls https://dl.k8s.io/release/stable.txt)/bin/linux/amd64/kubectl"
    sudo install -o root -g root -m 0755 kubectl /usr/local/bin/kubectl
    rm kubectl
fi

if ! command -v helm >/dev/null; then
    echo "==> Installing helm"
    curl -fsSL https://raw.githubusercontent.com/helm/helm/main/scripts/get-helm-3 | bash
fi

echo "==> Starting minikube"
minikube start --cpus 4 --memory 8g

echo "==> Installing tpu-stack (CPU-only tiny model)"
helm install tpu-stack "$(dirname "$0")/../helm" \
    -f "$(dirname "$0")/../tutorials/assets/values-01-minimal-example.yaml"

kubectl get pods -w
