#!/usr/bin/env bash
# Install kube-prometheus-stack + prometheus-adapter wired for the
# tpu-stack metrics (parity: reference observability/install.sh).
set -euo pipefail
cd "$(dirname "$0")"

helm repo add prometheus-community \
  https://prometheus-community.github.io/helm-charts || true
helm repo update

helm upgrade --install kube-prom-stack \
  prometheus-community/kube-prometheus-stack \
  --namespace monitoring --create-namespace \
  -f kube-prom-stack.yaml

helm upgrade --install prometheus-adapter \
  prometheus-community/prometheus-adapter \
  --namespace monitoring \
  -f prom-adapter.yaml

echo "Grafana dashboard: import tpu-stack-dashboard.json"
