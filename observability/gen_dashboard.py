"""Generates the Grafana dashboard JSON (tpu-stack-dashboard.json).

Panel set matches the reference's vllm-dashboard.json (21 panels in 4
rows: overview, QoS, serving-engine load, node resources — reference
observability/vllm-dashboard.json) with TPU naming (HBM KV instead of
"GPU KV", TPU duty cycle instead of GPU usage), plus the fork's KV
block-accounting panels and per-engine router views the reference
doesn't have.

Latency/TTFT/ITL distributions use the engine's vLLM-name histograms
(engine/metrics.py); queueing delay and prefill length use the router
gauges (router/services/metrics_service.py). Node panels use standard
node-exporter series (the reference ships placeholder exprs there).

Run: python observability/gen_dashboard.py > observability/tpu-stack-dashboard.json
"""

import json

_next_id = [0]


def _nid() -> int:
    _next_id[0] += 1
    return _next_id[0]


def target(expr, legend="{{server}}"):
    return {"expr": expr, "legendFormat": legend}


def panel(title, targets, x, y, w=8, h=7, unit=None, kind="timeseries"):
    p = {
        "id": _nid(),
        "title": title,
        "type": kind,
        "datasource": {"type": "prometheus", "uid": "prometheus"},
        "gridPos": {"x": x, "y": y, "w": w, "h": h},
        "targets": targets,
        "fieldConfig": {"defaults": {}, "overrides": []},
    }
    if unit:
        p["fieldConfig"]["defaults"]["unit"] = unit
    return p


def row(title, y):
    return {
        "id": _nid(),
        "title": title,
        "type": "row",
        "gridPos": {"x": 0, "y": y, "w": 24, "h": 1},
        "collapsed": False,
        "panels": [],
    }


def build():
    panels = [
        # ---- Overview System Performance (reference row 1) ----------------
        row("Overview System Performance", 0),
        panel("Available TPU Engine Instances",
              [target('sum(vllm:healthy_pods_total)', "engines")],
              0, 1, w=6, kind="stat"),
        panel("Average Latency",
              [target('avg(vllm:e2e_request_latency_seconds_sum) / '
                      'avg(vllm:e2e_request_latency_seconds_count)',
                      "avg e2e latency")],
              6, 1, w=6, unit="s", kind="stat"),
        panel("Request latency distribution",
              [target('sum by(le) (vllm:e2e_request_latency_seconds_bucket)',
                      "{{le}}")],
              12, 1, w=12, kind="bargauge"),
        # ---- QoS Information (reference row 2) -----------------------------
        row("QoS Information", 8),
        panel("Current QPS",
              [target('sum(vllm:current_qps)', "qps")],
              0, 9, w=4, unit="reqps", kind="stat"),
        panel("Router-side Queueing Delay",
              [target('avg(vllm:router_queueing_delay_seconds)',
                      "queueing delay")],
              4, 9, w=4, unit="s", kind="stat"),
        panel("Average Prefill Length",
              [target('avg(vllm:avg_prefill_length)', "prompt tokens")],
              8, 9, w=4, kind="stat"),
        panel("Average ITL",
              [target('avg(vllm:time_per_output_token_seconds_sum) / '
                      'avg(vllm:time_per_output_token_seconds_count)',
                      "avg itl")],
              12, 9, w=4, unit="s", kind="stat"),
        panel("Request TTFT distribution",
              [target('sum by(le) '
                      '(vllm:time_to_first_token_seconds_bucket)',
                      "{{le}}")],
              16, 9, w=8, kind="bargauge"),
        panel("TTFT decomposition (queue vs prefill, p50)",
              [target('histogram_quantile(0.5, sum by(le) (rate('
                      'vllm:request_queue_time_seconds_bucket[5m])))',
                      "queue p50"),
               target('histogram_quantile(0.5, sum by(le) (rate('
                      'vllm:request_prefill_time_seconds_bucket[5m]'
                      ')))', "prefill p50")],
              0, 9, w=8, unit="s"),
        # ---- Serving Engine Load (reference row 3) -------------------------
        row("Serving Engine Load", 16),
        panel("Number of Running Requests",
              [target('vllm:num_requests_running')], 0, 17),
        panel("Number of Pending Requests",
              [target('vllm:num_requests_waiting')], 8, 17),
        panel("HBM KV Usage Percentage",
              [target('vllm:gpu_cache_usage_perc')], 16, 17,
              unit="percentunit"),
        panel("HBM KV Cache Hit Rate",
              [target('vllm:gpu_prefix_cache_hit_rate')], 0, 24,
              unit="percentunit"),
        panel("Number of Swapped Requests",
              [target('sum(vllm:num_requests_swapped)', "swapped")],
              8, 24, w=4, kind="stat"),
        panel("Preemptions / min",
              [target('sum(rate(vllm:num_preemptions_total[1m])) '
                      '* 60', "preempted")],
              12, 24, w=4, kind="stat"),
        panel("KV Blocks (allocated / reserved / free)",
              [target('vllm:allocated_blocks', "alloc {{server}}"),
               target('vllm:pending_reserved_blocks',
                      "reserved {{server}}"),
               target('vllm:num_free_blocks', "free {{server}}")],
              16, 24),
        # ---- Router per-engine views (fork extras) -------------------------
        row("Router Per-Engine View", 31),
        panel("Router QPS per Engine",
              [target('vllm:current_qps')], 0, 32, unit="reqps"),
        panel("Average Request Latency",
              [target('vllm:avg_latency')], 8, 32, unit="s"),
        panel("Prefill Requests (router view)",
              [target('vllm:num_prefill_requests')], 16, 32),
        panel("Decoding Requests (router view)",
              [target('vllm:num_decoding_requests')], 0, 39),
        panel("Average Decoding Length",
              [target('vllm:avg_decoding_length')], 8, 39, unit="s"),
        panel("Inter-Token Latency",
              [target('vllm:avg_itl')], 16, 39, unit="s"),
        # ---- Current Resource Usage (reference row 4) ----------------------
        row("Current Resource Usage", 46),
        panel("TPU Usage",
              [target('avg by (node) '
                      '(kubernetes_io:node_accelerator_duty_cycle)',
                      "{{node}}")],
              0, 47, w=6, unit="percent"),
        panel("CPU Usage",
              [target('1 - avg by (instance) '
                      '(rate(node_cpu_seconds_total{mode="idle"}[2m]))',
                      "{{instance}}")],
              6, 47, w=6, unit="percentunit"),
        panel("Memory Usage",
              [target('1 - node_memory_MemAvailable_bytes / '
                      'node_memory_MemTotal_bytes',
                      "{{instance}}")],
              12, 47, w=6, unit="percentunit"),
        panel("Disk Usage",
              [target('1 - node_filesystem_avail_bytes'
                      '{mountpoint="/"} / node_filesystem_size_bytes'
                      '{mountpoint="/"}',
                      "{{instance}}")],
              18, 47, w=6, unit="percentunit"),
        # ---- Disaggregated serving (docs/disaggregation.md) ----------------
        row("Disaggregated Serving", 54),
        panel("Prefill / Decode Requests per Engine",
              [target('vllm:engine_disagg_prefill_requests',
                      "prefill {{server}}"),
               target('vllm:engine_disagg_decode_requests',
                      "decode {{server}}")],
              0, 55),
        panel("Handoff KV Bytes Shipped",
              [target('vllm:engine_disagg_kv_bytes_shipped')],
              8, 55, unit="bytes"),
        panel("AWAITING_KV Queue Depth",
              [target('vllm:engine_disagg_awaiting_kv_requests')],
              16, 55),
        panel("Handoff Admission Latency (mean)",
              [target('vllm:engine_disagg_handoff_latency_mean_seconds')],
              0, 62, unit="s"),
        panel("Router Two-Hop Handoffs",
              [target('vllm:router_disagg_handoffs_total', "handoffs")],
              8, 62, w=4, kind="stat"),
        panel("Router Monolithic Fallbacks",
              [target('vllm:router_disagg_fallbacks_total', "fallbacks")],
              12, 62, w=4, kind="stat"),
        # Per-phase request latency means (docs/observability.md): the
        # router re-exports each engine phase histogram's mean; full
        # distributions come from cluster Prometheus on the engines.
        panel("Request Phase Latency (means)",
              [target('vllm:engine_request_queue_time_mean_seconds',
                      "queue {{server}}"),
               target('vllm:engine_request_prefill_time_mean_seconds',
                      "prefill {{server}}"),
               target(
                   'vllm:engine_request_awaiting_kv_time_mean_seconds',
                   "awaiting-kv {{server}}"),
               target('vllm:engine_request_decode_time_mean_seconds',
                      "decode {{server}}")],
              16, 62, unit="s"),
        # ---- Unified ragged step (docs/unified_step.md) --------------------
        row("Unified Ragged Step", 69),
        panel("Step Row Split (prefill / decode / pad)",
              [target('vllm:engine_step_prefill_rows',
                      "prefill {{server}}"),
               target('vllm:engine_step_decode_rows',
                      "decode {{server}}"),
               target('vllm:engine_step_pad_rows', "pad {{server}}")],
              0, 70),
        panel("Cumulative Pad-Row Ratio",
              [target('vllm:engine_ragged_pad_rows / '
                      'clamp_min(vllm:engine_ragged_rows, 1)')],
              8, 70, unit="percentunit"),
        panel("Async Pipeline (ahead-step share)",
              [target('vllm:engine_pipeline_ahead_steps / '
                      'clamp_min(vllm:engine_pipeline_steps, 1)')],
              16, 70, unit="percentunit"),
        # ---- Fleet & drain (docs/fleet.md) ---------------------------------
        row("Fleet & Drain", 77),
        panel("Fleet Replicas (desired vs live)",
              [target('vllm:fleet_desired_replicas',
                      "desired {{server}}"),
               target('vllm:fleet_live_replicas', "live {{server}}")],
              0, 78),
        panel("Draining Engines",
              [target('vllm:engine_draining')], 8, 78),
        panel("Fleet Scale Events",
              [target('vllm:fleet_scale_events_total')],
              16, 78, w=4, kind="stat"),
        panel("Request Retries / Failovers",
              [target('vllm:request_retries_total', "retries"),
               target('vllm:request_failovers_total', "failovers")],
              20, 78, w=4, kind="stat"),
        # ---- QoS & overload (docs/qos.md) -----------------------------------
        row("QoS & Overload", 85),
        panel("Preempt-to-Offload Outcomes",
              [target('sum by(outcome) (rate('
                      'vllm:preempt_offload_total[5m]))',
                      "{{outcome}}")],
              0, 86),
        panel("Shed Requests by Class",
              [target('sum by(class) (rate(vllm:qos_shed_total[5m]))',
                      "{{class}}")],
              8, 86),
        panel("Tenants Throttled (degraded)",
              [target('sum(rate(vllm:tenant_throttled_total[5m])) * 60',
                      "degraded / min")],
              16, 86, w=4, kind="stat"),
        panel("Preempt Restore Latency (p50 / p99)",
              [target('histogram_quantile(0.5, sum by(le) (rate('
                      'vllm:preempt_restore_latency_seconds_bucket'
                      '[5m])))', "p50"),
               target('histogram_quantile(0.99, sum by(le) (rate('
                      'vllm:preempt_restore_latency_seconds_bucket'
                      '[5m])))', "p99")],
              20, 86, w=4, unit="s"),
        # ---- Device performance observatory (docs/observability.md) --------
        row("Device Performance", 92),
        panel("Compile Events by Kind (rate)",
              [target('sum by(kind) (rate('
                      'vllm:engine_compile_events[5m]))',
                      "{{kind}}")],
              0, 93),
        panel("Compile Wall Time by Kind (rate)",
              [target('sum by(kind) (rate('
                      'vllm:engine_compile_seconds[5m]))',
                      "{{kind}}")],
              8, 93, unit="s"),
        panel("Executable Cache Size by Kind",
              [target('vllm:engine_executable_cache_size',
                      "{{kind}} {{server}}")],
              16, 93),
        panel("HBM Bytes by Category",
              [target('sum by(category) (vllm:engine_hbm_bytes)',
                      "{{category}}")],
              0, 100, unit="bytes"),
        panel("Model FLOPs Utilization (useful tokens)",
              [target('vllm:engine_mfu')],
              8, 100, w=4, unit="percentunit"),
        panel("Step Device Seconds by Kind (rate)",
              [target('sum by(kind) (rate('
                      'vllm:engine_step_device_seconds[5m]))',
                      "{{kind}}")],
              12, 100, w=6, unit="s"),
        panel("Attention Impl (one-hot)",
              [target('vllm:engine_attention_impl',
                      "{{phase}}={{impl}} {{server}}")],
              18, 100, w=6, kind="stat"),
        # ---- Cluster KV economy (docs/kv_economy.md) -----------------------
        row("KV Economy", 107),
        panel("Hot Prefix Chains Advertised",
              [target('vllm:engine_kv_summary_hot_chains')],
              0, 108),
        panel("KV Headroom Fraction",
              [target('vllm:engine_kv_headroom_frac')],
              8, 108, unit="percentunit"),
        panel("KV Summary Staleness",
              [target('vllm:engine_kv_summary_age_seconds')],
              16, 108, unit="s"),
        panel("Shared Cache Ops (rate)",
              [target('sum(rate(vllm:engine_kv_cluster_hits[5m]))',
                      "hits"),
               target('sum(rate(vllm:engine_kv_cluster_misses[5m]))',
                      "misses"),
               target('sum(rate('
                      'vllm:engine_kv_cluster_admissions[5m]))',
                      "admissions"),
               target('sum(rate('
                      'vllm:engine_kv_cluster_rejections[5m]))',
                      "rejections")],
              0, 115),
        panel("Free KV Pages",
              [target('vllm:engine_kv_free_page_headroom')],
              8, 115),
        panel("Expected Prefix-Hit Tokens (last placement)",
              [target('vllm:kv_route_expected_hit_tokens')],
              16, 115),
        # ---- SLO ledger & goodput (docs/observability.md) -------------------
        row("SLO & Goodput", 122),
        panel("SLO Attainment by Class",
              [target('vllm:slo_attainment',
                      "{{class}} {{model}}")],
              0, 123, unit="percentunit"),
        panel("SLO Burn Rate (multi-window)",
              [target('vllm:slo_burn_rate', "{{window}}")],
              8, 123),
        panel("Good vs Bad Requests (rate)",
              [target('sum(rate(vllm:slo_good_requests_total[5m]))',
                      "good"),
               target('sum(rate(vllm:slo_bad_requests_total[5m]))',
                      "bad")],
              16, 123),
        panel("Slow-Request Archive Depth",
              [target('vllm:slow_archive_depth', "exemplars")],
              0, 130, w=4, kind="stat"),
        panel("Perf Drift Flags by Phase",
              [target('vllm:perf_drift', "{{phase}}")],
              4, 130, w=8, kind="stat"),
        panel("Engine Step-Time Median by Kind",
              [target('vllm:engine_step_time_median_seconds',
                      "{{kind}} {{server}}")],
              12, 130, w=12, unit="s"),
        # ---- Rolling upgrades (docs/fleet.md) -------------------------------
        row("Rollouts", 137),
        panel("Rollout Phase by Pool",
              [target('vllm:rollout_phase', "{{pool}} {{phase}}")],
              0, 138),
        panel("Replicas by Revision",
              [target('vllm:rollout_replicas',
                      "{{pool}} {{revision}}")],
              8, 138),
        panel("Rollbacks / Alarm",
              [target('vllm:rollout_rollbacks_total',
                      "rollbacks {{pool}}"),
               target('vllm:rollout_alarm', "ALARM {{pool}}")],
              16, 138),
        panel("Server Revision Labels",
              [target('vllm:server_revision',
                      "{{server}} {{revision}}")],
              0, 145),
        panel("Stream Resumes by Outcome (rate)",
              [target('sum by(outcome) '
                      '(rate(vllm:stream_resumes_total[5m]))',
                      "{{outcome}}")],
              8, 145),
        panel("Server Errors (rate)",
              [target('rate(vllm:server_errors_total[5m])')],
              16, 145),
        # ---- Self-tuning controllers (docs/autotuning.md) -------------------
        row("Self-Tuning", 152),
        panel("Autotune Decision Rate by Controller",
              [target('sum by(controller) (rate('
                      'vllm:autotune_decisions_total[5m]))',
                      "{{controller}}")],
              0, 153),
        panel("Frozen Controllers (guardrail latched)",
              [target('vllm:engine_autotune_frozen',
                      "{{controller}} {{server}}")],
              8, 153, w=4, kind="stat"),
        panel("Active Controllers per Engine",
              [target('vllm:engine_autotune_active_controllers')],
              12, 153, w=4, kind="stat"),
        panel("Knob Values by Controller",
              [target('vllm:engine_autotune_knob_value',
                      "{{controller}} {{server}}")],
              16, 153),
    ]
    return {
        "title": "TPU Stack — Serving Overview",
        "uid": "tpu-stack-overview",
        "schemaVersion": 39,
        "version": 2,
        "refresh": "15s",
        "time": {"from": "now-30m", "to": "now"},
        "tags": ["tpu-stack", "llm"],
        "panels": panels,
        "templating": {"list": []},
    }


if __name__ == "__main__":
    print(json.dumps(build(), indent=2))
