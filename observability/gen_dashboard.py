"""Generates the Grafana dashboard JSON (tpu-stack-dashboard.json).

Panel set mirrors the reference's vllm-dashboard.json capability
(available instances, latency/TTFT, QPS, prefill/decode counts,
running/waiting, KV usage + prefix hit rate, block accounting) with
TPU naming (HBM KV instead of "GPU KV").

Run: python observability/gen_dashboard.py > observability/tpu-stack-dashboard.json
"""

import json


def target(expr, legend="{{server}}"):
    return {"expr": expr, "legendFormat": legend}


def panel(panel_id, title, targets, x, y, w=8, h=7, unit=None,
          kind="timeseries"):
    p = {
        "id": panel_id,
        "title": title,
        "type": kind,
        "datasource": {"type": "prometheus", "uid": "prometheus"},
        "gridPos": {"x": x, "y": y, "w": w, "h": h},
        "targets": targets,
        "fieldConfig": {"defaults": {}, "overrides": []},
    }
    if unit:
        p["fieldConfig"]["defaults"]["unit"] = unit
    return p


def build():
    panels = [
        panel(1, "Healthy Serving Engines",
              [target('sum(vllm:healthy_pods_total)', "engines")],
              0, 0, w=6, kind="stat"),
        panel(2, "Router QPS per Engine",
              [target('vllm:current_qps')], 6, 0, w=9, unit="reqps"),
        panel(3, "Average Request Latency",
              [target('vllm:avg_latency')], 15, 0, w=9, unit="s"),
        panel(4, "Prefill Requests (router view)",
              [target('vllm:num_prefill_requests')], 0, 7),
        panel(5, "Decoding Requests (router view)",
              [target('vllm:num_decoding_requests')], 8, 7),
        panel(6, "Average Decoding Length",
              [target('vllm:avg_decoding_length')], 16, 7, unit="s"),
        panel(7, "Engine Running Requests",
              [target('vllm:num_requests_running')], 0, 14),
        panel(8, "Engine Waiting Requests",
              [target('vllm:num_requests_waiting')], 8, 14),
        panel(9, "HBM KV Cache Usage",
              [target('vllm:gpu_cache_usage_perc')], 16, 14,
              unit="percentunit"),
        panel(10, "Prefix Cache Hit Rate",
              [target('vllm:gpu_prefix_cache_hit_rate')], 0, 21,
              unit="percentunit"),
        panel(11, "KV Blocks (allocated / reserved / free)",
              [target('vllm:allocated_blocks', "alloc {{server}}"),
               target('vllm:pending_reserved_blocks',
                      "reserved {{server}}"),
               target('vllm:num_free_blocks', "free {{server}}")],
              8, 21),
        panel(12, "Swapped Requests",
              [target('vllm:num_requests_swapped')], 16, 21),
        panel(13, "Inter-Token Latency",
              [target('vllm:avg_itl')], 0, 28, unit="s"),
    ]
    return {
        "title": "TPU Stack — Serving Overview",
        "uid": "tpu-stack-overview",
        "schemaVersion": 39,
        "version": 1,
        "refresh": "15s",
        "time": {"from": "now-30m", "to": "now"},
        "tags": ["tpu-stack", "llm"],
        "panels": panels,
        "templating": {"list": []},
    }


if __name__ == "__main__":
    print(json.dumps(build(), indent=2))
