"""OpenAI Batch API walkthrough against the router (counterpart of
reference examples/openai_api_client_batch.py): upload a JSONL batch
file, create a batch, poll it, download results.

Run a stack first (e.g. run_production_stack/ runbook or the helm
minimal example with --enable-batch-api on the router), then:

    python examples/openai_api_client_batch.py --base-url http://localhost:8001
"""

import argparse
import os
import time

from openai import OpenAI


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--base-url", default="http://localhost:8001")
    parser.add_argument("--file", default=os.path.join(
        os.path.dirname(__file__), "batch.jsonl"))
    args = parser.parse_args()

    client = OpenAI(base_url=f"{args.base_url}/v1", api_key="none")

    print("== uploading", args.file)
    with open(args.file, "rb") as f:
        uploaded = client.files.create(file=f, purpose="batch")
    print("file id:", uploaded.id)

    print("== creating batch")
    batch = client.batches.create(
        input_file_id=uploaded.id,
        endpoint="/v1/chat/completions",
        completion_window="24h",
    )
    print("batch id:", batch.id, "status:", batch.status)

    while batch.status not in ("completed", "failed", "cancelled",
                               "expired"):
        time.sleep(2)
        batch = client.batches.retrieve(batch.id)
        print("  status:", batch.status)

    if batch.status == "completed" and batch.output_file_id:
        content = client.files.content(batch.output_file_id)
        print("== results")
        print(content.text)
    else:
        print("batch ended with status", batch.status)


if __name__ == "__main__":
    main()
