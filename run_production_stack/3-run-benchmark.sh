#!/bin/bash
# Multi-round-QA load against the local router (fork benchmark step).
# Usage: ./3-run-benchmark.sh [model] [qps] [num_users]
set -euo pipefail
cd "$(dirname "$0")/.."
MODEL="${1:-meta-llama/Meta-Llama-3-8B-Instruct}"
QPS="${2:-1.0}"
USERS="${3:-10}"

python -m benchmarks.multi_round_qa \
    --base-url "http://127.0.0.1:8001/v1" \
    --model "$MODEL" \
    --qps "$QPS" \
    --num-users "$USERS" \
    --num-rounds 3 \
    --output-csv /tmp/tpu-stack/bench.csv
