#!/bin/bash
# Launch N tpu-engine processes from a config file (fork cluster-on
# analogue). Usage: ./1-start-engines.sh [config/llama3-1chip.env]
set -euo pipefail
cd "$(dirname "$0")"
CONFIG="${1:-config/llama3-1chip.env}"
# shellcheck disable=SC1090
source "$CONFIG"

mkdir -p /tmp/tpu-stack
ENGINE_CMD="tpu-engine"
if ! command -v tpu-engine >/dev/null; then
    ENGINE_CMD="python -m production_stack_tpu.engine.server"
    export PYTHONPATH="$(cd .. && pwd):${PYTHONPATH:-}"
fi
for i in $(seq 0 $((NUM_ENGINES - 1))); do
    port=$((ENGINE_BASE_PORT + i))
    log="/tmp/tpu-stack/engine-$port.log"
    echo "==> engine :$port ($MODEL, tp=$TENSOR_PARALLEL_SIZE)"
    # shellcheck disable=SC2086
    nohup $ENGINE_CMD \
        --model "$MODEL" \
        --served-model-name "$SERVED_MODEL_NAME" \
        --port "$port" \
        --tensor-parallel-size "$TENSOR_PARALLEL_SIZE" \
        --max-model-len "$MAX_MODEL_LEN" \
        --max-num-seqs "$MAX_NUM_SEQS" \
        --num-pages "$NUM_PAGES" \
        --prefill-chunk-size "$PREFILL_CHUNK_SIZE" \
        --dtype "$DTYPE" \
        $EXTRA_FLAGS >"$log" 2>&1 &
    echo $! > "/tmp/tpu-stack/engine-$port.pid"
done
echo "logs: /tmp/tpu-stack/engine-*.log"
