#!/bin/bash
# Launch the router with llq routing + the dynamic-config watcher
# (fork's router setup with config/dynamic.json). The watcher is the
# same contract the K8s control-plane agent drives (SURVEY.md §3.4).
# Usage: ./2-start-router.sh [port] [dynamic.json]
set -euo pipefail
cd "$(dirname "$0")"
PORT="${1:-8001}"
DYNAMIC="${2:-config/dynamic.json}"

mkdir -p /tmp/tpu-stack
cp "$DYNAMIC" /tmp/tpu-stack/dynamic_config.json
ROUTER_CMD="tpu-router"
if ! command -v tpu-router >/dev/null; then
    ROUTER_CMD="python -m production_stack_tpu.router.app"
    export PYTHONPATH="$(cd .. && pwd):${PYTHONPATH:-}"
fi
nohup $ROUTER_CMD \
    --port "$PORT" \
    --service-discovery static \
    --static-backends "$(python -c "import json;print(json.load(open('$DYNAMIC'))['static_backends'])")" \
    --static-models "$(python -c "import json;print(json.load(open('$DYNAMIC'))['static_models'])")" \
    --routing-logic llq \
    --dynamic-config-json /tmp/tpu-stack/dynamic_config.json \
    >/tmp/tpu-stack/router.log 2>&1 &
echo $! > /tmp/tpu-stack/router.pid
echo "router :$PORT (log /tmp/tpu-stack/router.log)"
echo "edit /tmp/tpu-stack/dynamic_config.json to re-point it live"
