#!/bin/bash
# Install the stack on a TPU VM (fork 0-*.sh analogue: environment
# prep; TPU VMs need only the Python package + jax[tpu]).
set -euo pipefail
cd "$(dirname "$0")/.."

pip install -e .
python -c "import jax; print('devices:', jax.devices())"
mkdir -p /tmp/tpu-stack
echo "OK"
