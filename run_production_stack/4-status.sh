#!/bin/bash
# Health/metrics snapshot of every stack process.
set -uo pipefail

for pidfile in /tmp/tpu-stack/*.pid; do
    [ -e "$pidfile" ] || continue
    name=$(basename "$pidfile" .pid)
    pid=$(cat "$pidfile")
    if kill -0 "$pid" 2>/dev/null; then
        echo "$name: running (pid $pid)"
    else
        echo "$name: DEAD"
    fi
done

echo "--- router health ---"
curl -s http://127.0.0.1:8001/health || echo "(router unreachable)"
echo
echo "--- router metrics (engine gauges) ---"
curl -s http://127.0.0.1:8001/metrics | grep -E "^vllm:" | head -20
