#!/bin/bash
# Stop every stack process (fork cluster-off analogue).
set -uo pipefail

for pidfile in /tmp/tpu-stack/*.pid; do
    [ -e "$pidfile" ] || continue
    pid=$(cat "$pidfile")
    name=$(basename "$pidfile" .pid)
    if kill "$pid" 2>/dev/null; then
        echo "stopped $name (pid $pid)"
    fi
    rm -f "$pidfile"
done
