"""Batch API end to end: upload JSONL -> create batch -> processor
executes every line against a discovered engine -> output file.

The reference's batch processor is a stub with broken imports
(reference local_processor.py:157-208 TODO, batch_service/__init__.py
stale paths); this test proves ours actually completes batches.
"""

import asyncio
import json

from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.router.service_discovery import (
    initialize_service_discovery,
)
from production_stack_tpu.router.services.batch import (
    LocalBatchProcessor,
)
from production_stack_tpu.router.services.files import (
    initialize_storage,
)
from production_stack_tpu.testing.fake_engine import build_fake_engine

BATCH_LINES = [
    {"custom_id": f"req-{i}", "method": "POST",
     "url": "/v1/chat/completions",
     "body": {"model": "m1",
              "messages": [{"role": "user", "content": f"q{i}"}],
              "max_tokens": 4}}
    for i in range(3)
]


def test_batch_executes_against_engine(tmp_path):
    async def run():
        fake = TestServer(build_fake_engine(model="m1", speed=1000,
                                            ttft=0.0))
        await fake.start_server()
        initialize_service_discovery(
            "static", urls=[f"http://127.0.0.1:{fake.port}"],
            models=["m1"],
        )
        storage = initialize_storage(
            "local_file", str(tmp_path / "files"))
        processor = LocalBatchProcessor(
            storage, db_path=str(tmp_path / "batch.db"),
            poll_interval_s=0.2,
        )
        await processor.initialize()
        try:
            payload = "\n".join(
                json.dumps(line) for line in BATCH_LINES).encode()
            f = await storage.save_file(
                "default", "batch.jsonl", payload, purpose="batch")
            info = await processor.create_batch(
                "default", input_file_id=f.metadata()["id"],
                endpoint="/v1/chat/completions",
                completion_window="24h", metadata=None,
            )
            for _ in range(100):
                info = await processor.retrieve_batch(
                    "default", info.id)
                if info.status.value in ("completed", "failed"):
                    break
                await asyncio.sleep(0.2)
            assert info.status.value == "completed", info.to_dict()
            assert info.output_file_id

            out = await storage.get_file_content(
                "default", info.output_file_id)
            lines = [json.loads(ln) for ln in
                     out.decode().strip().splitlines()]
            assert len(lines) == 3
            ids = {ln["custom_id"] for ln in lines}
            assert ids == {"req-0", "req-1", "req-2"}
            for ln in lines:
                assert ln["response"]["status_code"] == 200
                body = ln["response"]["body"]
                assert body["choices"][0]["message"]["content"]
        finally:
            await processor.close()
            await fake.close()

    asyncio.run(run())


def test_batch_cancellation(tmp_path):
    async def run():
        fake = TestServer(build_fake_engine(model="m1", speed=5,
                                            ttft=0.5))
        await fake.start_server()
        initialize_service_discovery(
            "static", urls=[f"http://127.0.0.1:{fake.port}"],
            models=["m1"],
        )
        storage = initialize_storage(
            "local_file", str(tmp_path / "files"))
        processor = LocalBatchProcessor(
            storage, db_path=str(tmp_path / "batch.db"),
            poll_interval_s=10.0,  # worker won't pick it up in time
        )
        await processor.initialize()
        try:
            payload = json.dumps(BATCH_LINES[0]).encode()
            f = await storage.save_file(
                "default", "batch.jsonl", payload, purpose="batch")
            info = await processor.create_batch(
                "default", input_file_id=f.metadata()["id"],
                endpoint="/v1/chat/completions",
                completion_window="24h", metadata=None,
            )
            info = await processor.cancel_batch("default",
                                                info.id)
            assert info.status.value in ("cancelling", "cancelled")
        finally:
            await processor.close()
            await fake.close()

    asyncio.run(run())
