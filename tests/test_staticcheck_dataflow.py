"""Tier-1 tests for the flow-sensitive staticcheck layer.

Three strata, matching how the machinery is built:

- CFG structure (staticcheck/cfg.py): loop back-edges, try/finally
  cleanup on both normal and exceptional paths, async-with
  enter/exit markers, EXC edges observing pre-statement state, and
  catch-all handlers stopping the escape to the exceptional exit.
- the four CFG-backed rules (page-lifecycle, state-machine,
  lock-discipline, endpoint-contract): one planted-violation fixture
  and one clean shape each, plus the real tree staying clean per
  rule (the aggregate gate lives in test_staticcheck.py).
- the CLI satellites: --diff line filtering, SARIF rendering, and
  baseline prune/stale detection.

Plus runtime regressions for the drift the new rules surfaced:
Sequence.transition() guarding untabled moves, and the fake engine's
/version and /debug/steps mirrors of the real server surface.
"""

import ast
import asyncio
import json
import pathlib
import textwrap

from production_stack_tpu.staticcheck import (
    Finding,
    Project,
    run_rules,
)
from production_stack_tpu.staticcheck import baseline as baseline_mod
from production_stack_tpu.staticcheck import dataflow
from production_stack_tpu.staticcheck import diff as diff_mod
from production_stack_tpu.staticcheck import sarif as sarif_mod
from production_stack_tpu.staticcheck.cfg import (
    BACK,
    CFG,
    EXC,
    WithEnter,
    WithExit,
    contains_call,
    default_raises,
)

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _fn(src):
    """First function definition parsed from dedented ``src``."""
    tree = ast.parse(textwrap.dedent(src))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node
    raise AssertionError("no function in fixture")


def _run(sources, rule):
    project = Project.from_sources(
        {path: textwrap.dedent(text) for path, text in sources.items()})
    return [f for f in run_rules(project, rules=[rule])
            if f.rule == rule]


# ---- CFG structure -----------------------------------------------------


def test_cfg_loop_has_one_back_edge_to_head():
    cfg = CFG(_fn("""\
        def f(n):
            total = 0
            while n > 0:
                total += n
                n -= 1
            return total
        """), raises=lambda _s, _t: False)
    back = cfg.back_edges()
    assert len(back) == 1
    _src, head = back[0]
    # The loop head carries the While statement itself so analyzers
    # can read its test.
    assert any(isinstance(el, ast.While) for el in head.elements)


def test_cfg_try_finally_cleanup_on_normal_and_exception_paths():
    # Lattice: {"held"} after acquire, cleared by release. The
    # finally must run on the fallthrough path AND on the path where
    # work() raises, so neither exit sees the lock held.
    # Only work() raises here — under default_raises the release()
    # call itself gets an EXC edge too (on which the lock is
    # legitimately still held), which is precision this test is not
    # about.
    def only_work_raises(stmt, _in_try):
        return any(isinstance(n, ast.Call)
                   and getattr(n.func, "id", "") == "work"
                   for n in ast.walk(stmt))

    cfg = CFG(_fn("""\
        def f(lock):
            lock.acquire()
            try:
                work()
            finally:
                lock.release()
            return 1
        """), raises=only_work_raises)

    def transfer(state, el, _kind):
        if not isinstance(el, ast.AST):
            return state
        for node in ast.walk(el):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute):
                if node.func.attr == "acquire":
                    return state | {"held"}
                if node.func.attr == "release":
                    return state - {"held"}
        return state

    exits = dataflow.facts_at_exit(cfg, frozenset(), transfer)
    assert exits["exit"] == frozenset()
    # work() raised -> exceptional copy of the finally still released.
    assert exits["raise_exit"] == frozenset()


def test_cfg_async_with_emits_enter_exit_markers_on_all_paths():
    cfg = CFG(_fn("""\
        async def f(self):
            async with self.lock:
                await work()
            return 1
        """), raises=default_raises)
    elements = [el for b in cfg.blocks for el in b.elements]
    enters = [el for el in elements if isinstance(el, WithEnter)]
    exits_ = [el for el in elements if isinstance(el, WithExit)]
    assert len(enters) == 1 and enters[0].is_async
    # One WithExit on the normal path, one cloned onto the
    # exceptional escape (await work() can raise).
    assert len(exits_) == 2

    def transfer(state, el, _kind):
        if isinstance(el, WithEnter):
            return state | {"held"}
        if isinstance(el, WithExit):
            return state - {"held"}
        return state

    exits = dataflow.facts_at_exit(cfg, frozenset(), transfer)
    assert exits["exit"] == frozenset()
    assert exits["raise_exit"] == frozenset()


def test_cfg_exc_edge_carries_pre_statement_state():
    # The allocation statement itself can raise; on that edge the
    # binding never happened, so only the normal exit holds the fact.
    cfg = CFG(_fn("""\
        def f(self):
            pages = self.cache.allocate_pages(1)
        """), raises=lambda s, _t: contains_call(s))

    def transfer(state, el, _kind):
        if (isinstance(el, ast.Assign)
                and isinstance(el.targets[0], ast.Name)):
            return state | {el.targets[0].id}
        return state

    exits = dataflow.facts_at_exit(cfg, frozenset(), transfer)
    assert exits["exit"] == frozenset({"pages"})
    assert exits["raise_exit"] == frozenset()


def test_cfg_catch_all_handler_stops_escape():
    # With `except Exception` the body's raise cannot reach the
    # exceptional exit; drop the handler and it must.
    caught = CFG(_fn("""\
        def f(self):
            try:
                raise ValueError("x")
            except Exception:
                return 0
        """), raises=default_raises)
    reachable = {b.id for b in caught.reachable()}
    assert caught.raise_exit.id not in reachable

    uncaught = CFG(_fn("""\
        def f(self):
            try:
                raise ValueError("x")
            except KeyError:
                return 0
        """), raises=default_raises)
    reachable = {b.id for b in uncaught.reachable()}
    assert uncaught.raise_exit.id in reachable


def test_cfg_break_and_continue_route_through_finally():
    # break inside try/finally inside a loop clones the finally onto
    # the exit path; the continue edge back to the head is BACK.
    cfg = CFG(_fn("""\
        def f(items, lock):
            for item in items:
                lock.acquire()
                try:
                    if item:
                        break
                    continue
                finally:
                    lock.release()
            return 1
        """), raises=lambda _s, _t: False)

    def transfer(state, el, _kind):
        # Loop heads carry the whole For statement (so analyzers can
        # read its iterable) — don't credit the head with effects
        # nested in the loop body.
        if not isinstance(el, ast.AST) or isinstance(
                el, (ast.For, ast.While)):
            return state
        for node in ast.walk(el):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute):
                if node.func.attr == "acquire":
                    return state | {"held"}
                if node.func.attr == "release":
                    return state - {"held"}
        return state

    exits = dataflow.facts_at_exit(cfg, frozenset(), transfer)
    assert exits["exit"] == frozenset()
    assert len(cfg.back_edges()) >= 1


# ---- page-lifecycle ----------------------------------------------------


def test_page_lifecycle_catches_alloc_leak_on_exception_path():
    findings = _run({
        "production_stack_tpu/engine/scheduler.py": """\
            def admit(self, seq):
                pages = list(self.cache.allocate_pages(4))
                self.queue.add_sequence(seq)
                seq.pages.extend(pages)
            """,
    }, "page-lifecycle")
    assert len(findings) == 1
    assert "KV pages allocated into 'pages'" in findings[0].message
    assert "exception path" in findings[0].message


def test_page_lifecycle_accepts_freed_on_failure_path():
    findings = _run({
        "production_stack_tpu/engine/scheduler.py": """\
            def admit(self, seq):
                pages = list(self.cache.allocate_pages(4))
                try:
                    self.queue.add_sequence(seq)
                except Exception:
                    self.cache.free_pages(pages)
                    raise
                seq.pages.extend(pages)
            """,
    }, "page-lifecycle")
    assert findings == []


def test_page_lifecycle_catches_stranded_awaiting_kv_park():
    findings = _run({
        "production_stack_tpu/engine/engine.py": """\
            def park(self, seq):
                seq.transition(SequenceState.AWAITING_KV)
                if not self.has_capacity:
                    return
                self.waiting_kv.append(seq)
            """,
    }, "page-lifecycle")
    assert len(findings) == 1
    assert "parked in AWAITING_KV" in findings[0].message


def test_page_lifecycle_accepts_park_with_sink_on_every_path():
    findings = _run({
        "production_stack_tpu/engine/engine.py": """\
            def park(self, seq):
                seq.transition(SequenceState.AWAITING_KV)
                if not self.has_capacity:
                    self.scheduler.abort_sequence(seq.seq_id)
                    return
                self.waiting_kv.append(seq)
            """,
    }, "page-lifecycle")
    assert findings == []


def test_page_lifecycle_waiver_suppresses():
    findings = _run({
        "production_stack_tpu/engine/engine.py": """\
            def park(self, seq):
                seq.transition(SequenceState.AWAITING_KV)  # lint: allow-page-lifecycle
                return
            """,
    }, "page-lifecycle")
    assert findings == []


# ---- state-machine -----------------------------------------------------

_SEQUENCE_FIXTURE = """\
    class SequenceState:
        WAITING = "waiting"
        RUNNING = "running"
        FINISHED = "finished"
        ABORTED = "aborted"

    SEQUENCE_TRANSITIONS = (
        ("new", "waiting", "arrival"),
        ("waiting", "running", "scheduled"),
        ("running", "finished", "done"),
    )

    class Sequence:
        def transition(self, new_state):
            self.state = new_state
    """

_DOCS_FIXTURE = """\
    <!-- sequence-states:begin -->
    | `new` | `waiting` | arrival |
    | `waiting` | `running` | scheduled |
    | `running` | `finished` | done |
    <!-- sequence-states:end -->
    """


def test_state_machine_catches_bypass_bad_ctor_and_untabled_dest():
    findings = _run({
        "production_stack_tpu/engine/sequence.py": _SEQUENCE_FIXTURE,
        "docs/sequence_states.md": _DOCS_FIXTURE,
        "production_stack_tpu/engine/scheduler.py": """\
            from production_stack_tpu.engine.sequence import (
                Sequence, SequenceState)

            def bad_write(seq):
                seq.state = SequenceState.RUNNING

            def bad_ctor():
                return Sequence(state=SequenceState.RUNNING)

            def bad_dest(seq):
                seq.transition(SequenceState.ABORTED)
            """,
    }, "state-machine")
    messages = "\n".join(f.message for f in findings)
    assert "direct .state write bypasses" in messages
    assert "no ('new', ...) row" in messages
    assert "never a destination" in messages
    assert len(findings) == 3


def test_state_machine_accepts_clean_usage_and_docs():
    findings = _run({
        "production_stack_tpu/engine/sequence.py": _SEQUENCE_FIXTURE,
        "docs/sequence_states.md": _DOCS_FIXTURE,
        "production_stack_tpu/engine/scheduler.py": """\
            from production_stack_tpu.engine.sequence import (
                Sequence, SequenceState)

            def ok(seq):
                seq.transition(SequenceState.RUNNING)
                return Sequence(state=SequenceState.WAITING)
            """,
    }, "state-machine")
    assert findings == []


def test_state_machine_keeps_docs_in_sync_both_directions():
    stale_docs = _DOCS_FIXTURE.replace(
        "| `running` | `finished` | done |",
        "| `running` | `aborted` | stale row |")
    findings = _run({
        "production_stack_tpu/engine/sequence.py": _SEQUENCE_FIXTURE,
        "docs/sequence_states.md": stale_docs,
    }, "state-machine")
    messages = "\n".join(f.message for f in findings)
    # Table row missing from the docs block...
    assert "but undocumented" in messages
    # ...and a documented row the table no longer has.
    assert "stale row or missing" in messages


# ---- lock-discipline ---------------------------------------------------


def test_lock_discipline_catches_await_under_sync_lock_and_bare_rmw():
    findings = _run({
        "production_stack_tpu/router/service.py": """\
            class Counter:
                async def bump(self):
                    with self._lock:
                        await self.flush()

                async def inc(self):
                    self.total += 1

                async def dec(self):
                    self.total -= 1
            """,
    }, "lock-discipline")
    messages = "\n".join(f.message for f in findings)
    assert "await in Counter.bump while" in messages
    assert "sync lock self._lock is held" in messages
    rmw = [f for f in findings
           if "self.total is read-modify-written" in f.message]
    assert len(rmw) == 2  # one per bare site


def test_lock_discipline_accepts_async_with_guarded_counters():
    findings = _run({
        "production_stack_tpu/router/service.py": """\
            class Counter:
                async def inc(self):
                    async with self._lock:
                        self.total += 1

                async def dec(self):
                    async with self._lock:
                        self.total -= 1
            """,
    }, "lock-discipline")
    assert findings == []


def test_lock_discipline_lock_released_before_await_is_clean():
    findings = _run({
        "production_stack_tpu/router/service.py": """\
            class Worker:
                async def step(self):
                    with self._lock:
                        payload = self.queue.pop()
                    await self.send(payload)
            """,
    }, "lock-discipline")
    assert findings == []


# ---- endpoint-contract -------------------------------------------------


def test_endpoint_contract_catches_every_drift_direction():
    findings = _run({
        "production_stack_tpu/engine/server.py": """\
            def build(app, h):
                app.router.add_get("/health", h)
                app.router.add_post("/v1/completions", h)
            """,
        "production_stack_tpu/engine/cache_server.py": """\
            def build(app, h):
                app.router.add_get("/stats", h)
            """,
        "production_stack_tpu/testing/fake_engine.py": """\
            FAKE_ENGINE_EXEMPT = {
                "GET /stats": "cache server runs in-process in tests",
                "GET /health": "redundant: the fake implements it",
                "POST /gone": "route no real server registers",
            }
            FAKE_ONLY_ROUTES = {
                "POST /fault": "fault injection hook",
            }

            def build(app, h):
                app.router.add_get("/health", h)
                app.router.add_post("/fault", h)
                app.router.add_post("/surprise", h)
            """,
    }, "endpoint-contract")
    messages = "\n".join(f.message for f in findings)
    assert "'POST /v1/completions' has no mirror" in messages
    assert ("FAKE_ENGINE_EXEMPT lists 'GET /health' but the fake "
            "implements it") in messages
    assert "stale exemption" in messages
    assert "fake-only route 'POST /surprise' is not declared" in messages
    # The correctly exempted and correctly declared routes are silent.
    assert "'GET /stats'" not in messages
    assert "'POST /fault'" not in messages


def test_endpoint_contract_accepts_mirrored_surface():
    findings = _run({
        "production_stack_tpu/engine/server.py": """\
            def build(app, h):
                app.router.add_get("/health", h)
            """,
        "production_stack_tpu/engine/cache_server.py": """\
            def build(app, h):
                pass
            """,
        "production_stack_tpu/testing/fake_engine.py": """\
            FAKE_ENGINE_EXEMPT = {}
            FAKE_ONLY_ROUTES = {}

            def build(app, h):
                app.router.add_get("/health", h)
            """,
    }, "endpoint-contract")
    assert findings == []


# ---- the real tree stays clean per new rule ----------------------------


def test_new_rules_are_clean_on_the_real_tree():
    project = Project.from_root(ROOT)
    for name in ("page-lifecycle", "state-machine", "lock-discipline",
                 "endpoint-contract"):
        findings = [f for f in run_rules(project, rules=[name])
                    if f.rule == name]
        assert findings == [], (
            f"{name} fired on the real tree:\n"
            + "\n".join(f.render() for f in findings))


# ---- CLI satellites: --diff, --sarif, baseline hygiene -----------------


def test_diff_parse_and_filter():
    text = textwrap.dedent("""\
        diff --git a/pkg/a.py b/pkg/a.py
        --- a/pkg/a.py
        +++ b/pkg/a.py
        @@ -10,0 +11,2 @@ def f():
        +    x = 1
        +    y = 2
        @@ -30 +33 @@ def g():
        +    z = 3
        diff --git a/pkg/b.py b/pkg/b.py
        --- a/pkg/b.py
        +++ b/pkg/b.py
        @@ -5,2 +0,0 @@ def h():
        """)
    changed = diff_mod.parse_unified_diff(text)
    assert changed["pkg/a.py"] == {11, 12, 33}
    assert changed["pkg/b.py"] == set()  # deletions: touched, no lines

    def f(path, line):
        return Finding(rule="r", path=path, line=line, message="m")

    kept = diff_mod.filter_findings(
        [f("pkg/a.py", 11), f("pkg/a.py", 20), f("pkg/a.py", 0),
         f("pkg/b.py", 7), f("pkg/b.py", 0), f("pkg/c.py", 1)],
        changed)
    assert [(x.path, x.line) for x in kept] == [
        ("pkg/a.py", 11),   # on a changed line
        ("pkg/a.py", 0),    # file-level contract finding, file touched
        ("pkg/b.py", 0),    # ditto (deletion-only touch)
    ]


def test_sarif_render_shape_and_fingerprints():
    from production_stack_tpu.staticcheck.core import REGISTRY
    import production_stack_tpu.staticcheck.analyzers  # noqa: F401
    finding = Finding(rule="state-machine", path="pkg/a.py", line=4,
                      message="planted")
    doc = sarif_mod.render([finding], REGISTRY)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "production-stack-tpu-staticcheck"
    assert {r["id"] for r in driver["rules"]} == set(REGISTRY)
    (result,) = run["results"]
    assert result["ruleId"] == "state-machine"
    assert driver["rules"][result["ruleIndex"]]["id"] == "state-machine"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "pkg/a.py"
    assert loc["region"]["startLine"] == 4
    assert (result["partialFingerprints"]["staticcheckFingerprint/v1"]
            == finding.fingerprint())


def test_baseline_prune_and_stale_detection(tmp_path):
    live = Finding(rule="r", path="a.py", line=1, message="still here")
    dead = Finding(rule="r", path="b.py", line=2, message="paid down")
    (tmp_path / "production_stack_tpu" / "staticcheck").mkdir(
        parents=True)
    baseline_mod.write(tmp_path, [live, dead])

    stale = baseline_mod.stale_entries(tmp_path, [live])
    assert [e["fingerprint"] for e in stale] == [dead.fingerprint()]

    dropped = baseline_mod.prune(tmp_path, [live])
    assert [e["fingerprint"] for e in dropped] == [dead.fingerprint()]
    kept = baseline_mod.load_fingerprints(tmp_path)
    assert kept == {live.fingerprint()}
    # Idempotent: nothing stale remains.
    assert baseline_mod.stale_entries(tmp_path, [live]) == []
    assert baseline_mod.prune(tmp_path, [live]) == []


# ---- runtime regressions for the drift the rules surfaced --------------


def test_sequence_transition_guards_untabled_moves():
    import pytest
    from production_stack_tpu.engine.sequence import (
        SamplingParams, Sequence, SequenceState,
    )
    seq = Sequence(seq_id="s1", prompt_token_ids=[1, 2],
                   sampling=SamplingParams())
    assert seq.state == SequenceState.WAITING
    seq.transition(SequenceState.RUNNING)
    assert seq.state == SequenceState.RUNNING
    seq.transition(SequenceState.RUNNING)  # same-state no-op
    assert seq.state == SequenceState.RUNNING
    seq.transition(SequenceState.FINISHED)
    with pytest.raises(ValueError, match="untabled sequence transition"):
        seq.transition(SequenceState.RUNNING)
    assert seq.state == SequenceState.FINISHED  # guard left state alone


def test_fake_engine_serves_version_like_the_real_server():
    from aiohttp.test_utils import TestClient, TestServer
    from production_stack_tpu.testing.fake_engine import (
        build_fake_engine,
    )
    from production_stack_tpu.version import __version__

    async def run():
        client = TestClient(TestServer(build_fake_engine()))
        await client.start_server()
        try:
            resp = await client.get("/version")
            assert resp.status == 200
            # Same shape as EngineServer.version: the build identity
            # rides along so rollouts can verify a canary's revision
            # (docs/fleet.md); empty when no --build-id was given.
            assert await resp.json() == {"version": __version__,
                                         "build_id": ""}
        finally:
            await client.close()

    asyncio.run(run())


def test_fake_engine_debug_steps_mirrors_real_contract():
    from aiohttp.test_utils import TestClient, TestServer
    from production_stack_tpu.testing.fake_engine import (
        build_fake_engine,
    )

    async def run():
        # Flight recorder on (the default): shape contract.
        client = TestClient(TestServer(build_fake_engine()))
        await client.start_server()
        try:
            resp = await client.get("/debug/steps")
            assert resp.status == 200
            data = await resp.json()
            assert isinstance(data["steps"], list)

            resp = await client.get("/debug/steps?limit=notanint")
            assert resp.status == 400
            data = await resp.json()
            assert "limit must be an integer" in data["error"]["message"]
        finally:
            await client.close()

        # Tracing disabled: same 404 contract as the real server.
        client = TestClient(TestServer(
            build_fake_engine(trace_ring=0)))
        await client.start_server()
        try:
            resp = await client.get("/debug/steps")
            assert resp.status == 404
            data = await resp.json()
            assert "tracing disabled" in data["error"]["message"]
        finally:
            await client.close()

    asyncio.run(run())
