"""Multi-step decode: K fused decode iterations must generate exactly
what single-step decoding generates (greedy), handle stop tokens
mid-window (tail discarded), and respect max_tokens budgets."""

import numpy as np

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams


def _engine(decode_steps, max_num_seqs=4):
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=max_num_seqs,
                                  max_model_len=256,
                                  prefill_chunk_size=32,
                                  decode_steps=decode_steps),
    )
    return LLMEngine(config)


def _gen(engine, prompts, **kw):
    sampling = dict(max_tokens=12, temperature=0.0, ignore_eos=True)
    sampling.update(kw)
    seqs = []
    for p in prompts:
        sid = engine.add_request(p, SamplingParams(**sampling))
        seqs.append(engine.sequences[sid])
    while engine.has_work():
        engine.step()
    return [s.output_token_ids for s in seqs]


def test_multistep_matches_single_step_greedy():
    rs = np.random.RandomState(1)
    prompts = [[int(x) for x in rs.randint(1, 500, size=n)]
               for n in (7, 20, 41)]
    expected = _gen(_engine(decode_steps=1), prompts)
    got = _gen(_engine(decode_steps=4), prompts)
    assert got == expected
    assert all(len(t) == 12 for t in got)


def test_window_respects_max_tokens():
    """max_tokens not divisible by K: the tail runs single-step and the
    budget is met exactly."""
    prompts = [[5, 6, 7, 8]]
    got = _gen(_engine(decode_steps=4), prompts, max_tokens=10)
    assert len(got[0]) == 10
    expected = _gen(_engine(decode_steps=1), prompts, max_tokens=10)
    assert got == expected


def test_stop_token_mid_window_discards_tail():
    """Pick the greedy continuation's 2nd token as a stop token: with
    K=4 it fires mid-window and the tail must be dropped."""
    prompts = [[9, 10, 11, 12, 13]]
    ref = _gen(_engine(decode_steps=1), prompts, max_tokens=8)[0]
    stop = ref[1]
    kw = dict(max_tokens=8, ignore_eos=False, stop_token_ids=[stop])
    got1 = _gen(_engine(decode_steps=1), prompts, **kw)[0]
    got4 = _gen(_engine(decode_steps=4), prompts, **kw)[0]
    assert got1 == got4
    assert got4[-1] == stop
    assert len(got4) == 2


def test_mixed_sampling_batch_keeps_greedy_rows_deterministic():
    """A stochastic row in the burst batch must not perturb greedy
    rows (per-row temperature; the sampler only randomizes rows with
    temperature > 0)."""
    rs = np.random.RandomState(3)
    greedy_prompt = [int(x) for x in rs.randint(1, 500, size=23)]
    stoch_prompt = [int(x) for x in rs.randint(1, 500, size=17)]

    solo = _gen(_engine(decode_steps=4), [greedy_prompt])[0]

    engine = _engine(decode_steps=4)
    sids = [
        engine.add_request(greedy_prompt, SamplingParams(
            max_tokens=12, temperature=0.0, ignore_eos=True)),
        engine.add_request(stoch_prompt, SamplingParams(
            max_tokens=12, temperature=0.9, top_p=0.9,
            ignore_eos=True)),
    ]
    seqs = [engine.sequences[s] for s in sids]
    while engine.has_work():
        engine.step()
    assert seqs[0].output_token_ids == solo
    assert len(seqs[1].output_token_ids) == 12


def test_penalized_burst_matches_single_step():
    """Greedy + penalties must produce identical tokens whether the
    decode runs as fused bursts (counts tracked on device) or single
    steps (counts rebuilt on host per dispatch)."""
    from production_stack_tpu.engine.sequence import SamplingParams

    prompt = list(range(1, 30))
    sp = dict(max_tokens=12, temperature=0.0, ignore_eos=True,
              presence_penalty=1.5, frequency_penalty=0.5,
              repetition_penalty=1.3)

    def gen(steps):
        engine = _engine(decode_steps=steps)
        seq = engine.generate(prompt, SamplingParams(**sp))
        return seq.output_token_ids

    burst, single = gen(6), gen(1)
    assert burst == single


def test_seeded_requests_reproduce():
    """Identical seeded stochastic requests produce identical tokens —
    across engine instances and regardless of burst width — and a
    different seed diverges."""
    from production_stack_tpu.engine.sequence import SamplingParams

    prompt = list(range(1, 30))

    def gen(steps, seed):
        engine = _engine(decode_steps=steps)
        seq = engine.generate(prompt, SamplingParams(
            max_tokens=10, temperature=0.9, ignore_eos=True,
            seed=seed))
        return seq.output_token_ids

    a = gen(6, 1234)
    b = gen(6, 1234)
    c = gen(1, 1234)
    d = gen(6, 999)
    assert a == b == c
    assert d != a
