"""KV offload tiers: host pool, remote cache server, engine restore.

Capability model: reference LMCache CPU-offload + remote shared cache
(tutorials 05/06), done with jax device_put/get on page granularity.
"""

import asyncio

import numpy as np
import pytest

from production_stack_tpu.engine.cache_server import build_cache_server
from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    OffloadConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.offload import (
    HostKVPool,
    KVOffloadManager,
    _stable_key,
)
from production_stack_tpu.engine.sequence import SamplingParams


def _payload(seed, shape=(2, 8, 2, 16)):
    rng = np.random.RandomState(seed)
    return (rng.randn(*shape).astype(np.float32),
            rng.randn(*shape).astype(np.float32))


def test_host_pool_lru_eviction():
    k, v = _payload(0)
    entry_bytes = k.nbytes + v.nbytes
    pool = HostKVPool(max_bytes=entry_bytes * 2)
    pool.put("a", _payload(1))
    pool.put("b", _payload(2))
    pool.put("c", _payload(3))  # evicts "a" (LRU)
    assert pool.get("a") is None
    assert pool.get("b") is not None
    assert pool.get("c") is not None


def test_host_pool_get_refreshes_lru():
    k, v = _payload(0)
    pool = HostKVPool(max_bytes=(k.nbytes + v.nbytes) * 2)
    pool.put("a", _payload(1))
    pool.put("b", _payload(2))
    pool.get("a")  # refresh
    pool.put("c", _payload(3))  # should evict "b" now
    assert pool.get("a") is not None
    assert pool.get("b") is None


def test_offload_manager_chain_lookup():
    mgr = KVOffloadManager(host_pool=HostKVPool())
    hashes = [(0, (1, 2)), (hash((0, (1, 2))), (3, 4)),
              (99, (5, 6))]
    mgr.offload_page(hashes[0], *_payload(1))
    mgr.offload_page(hashes[1], *_payload(2))
    # Chain breaks at the third hash.
    assert mgr.lookup_chain(hashes) == 2
    assert mgr.fetch(hashes[0]) is not None
    assert mgr.fetch(hashes[2]) is None


def test_cache_server_roundtrip():
    """PUT/GET/HEAD against the remote cache server over HTTP."""
    import msgpack
    from aiohttp.test_utils import TestClient, TestServer

    async def run():
        client = TestClient(TestServer(build_cache_server(1024 ** 2)))
        await client.start_server()
        try:
            k, v = _payload(5)
            body = msgpack.packb({
                "k": k.tobytes(), "v": v.tobytes(),
                "shape": list(k.shape), "dtype": str(k.dtype),
            })
            put = await client.put("/kv/abc", data=body)
            assert put.status == 200
            head = await client.head("/kv/abc")
            assert head.status == 200
            got = await client.get("/kv/abc")
            assert got.status == 200
            obj = msgpack.unpackb(await got.read())
            k2 = np.frombuffer(obj["k"], np.float32).reshape(k.shape)
            np.testing.assert_array_equal(k, k2)
            missing = await client.get("/kv/nope")
            assert missing.status == 404
            stats = await (await client.get("/stats")).json()
            assert stats["entries"] == 1
        finally:
            await client.close()
    asyncio.run(run())


def _make_engine(num_pages, offload=True):
    model = tiny_model_config("llama")
    return LLMEngine(EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_pages=num_pages),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=256,
                                  prefill_chunk_size=64),
        offload=OffloadConfig(enable=offload,
                              host_pool_bytes=256 * 1024 ** 2),
    ))


def test_engine_restores_evicted_prefix_from_host_pool():
    """Fill HBM, evict a cached prefix, and watch the offload tier
    restore it — with identical generation output."""
    sampling = lambda: SamplingParams(  # noqa: E731
        max_tokens=4, temperature=0.0, ignore_eos=True)
    shared = list(range(1, 65))  # 64 tokens = 4 full pages

    # Reference output from a clean engine.
    ref_engine = _make_engine(num_pages=64, offload=False)
    expected = ref_engine.generate(
        shared + [99, 98], sampling()).output_token_ids

    # Tiny cache: 15 usable pages.
    engine = _make_engine(num_pages=16)
    first = engine.generate(shared + [99, 98], sampling())
    assert first.output_token_ids == expected

    # Fill the cache with unrelated prompts to force eviction of the
    # shared prefix pages into the host pool.
    for i in range(4):
        engine.generate([200 + i] * 80, sampling())
    assert engine.offload.offloaded_pages > 0

    # Same shared prefix again: must restore from the host pool.
    restored_before = engine.offload.restored_pages
    again = engine.generate(shared + [99, 98], sampling())
    assert engine.offload.restored_pages > restored_before
    assert again.output_token_ids == expected
