"""KV offload tiers: host pool, remote cache server, engine restore.

Capability model: reference LMCache CPU-offload + remote shared cache
(tutorials 05/06), done with jax device_put/get on page granularity.
"""

import asyncio

import numpy as np
import pytest

from production_stack_tpu.engine.cache_server import build_cache_server
from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    OffloadConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.offload import (
    HostKVPool,
    KVOffloadManager,
    _stable_key,
)
from production_stack_tpu.engine.sequence import SamplingParams


def _payload(seed, shape=(2, 8, 2, 16)):
    rng = np.random.RandomState(seed)
    return (rng.randn(*shape).astype(np.float32),
            rng.randn(*shape).astype(np.float32))


def test_host_pool_lru_eviction():
    k, v = _payload(0)
    entry_bytes = k.nbytes + v.nbytes
    pool = HostKVPool(max_bytes=entry_bytes * 2)
    pool.put("a", _payload(1))
    pool.put("b", _payload(2))
    pool.put("c", _payload(3))  # evicts "a" (LRU)
    assert pool.get("a") is None
    assert pool.get("b") is not None
    assert pool.get("c") is not None


def test_host_pool_get_refreshes_lru():
    k, v = _payload(0)
    pool = HostKVPool(max_bytes=(k.nbytes + v.nbytes) * 2)
    pool.put("a", _payload(1))
    pool.put("b", _payload(2))
    pool.get("a")  # refresh
    pool.put("c", _payload(3))  # should evict "b" now
    assert pool.get("a") is not None
    assert pool.get("b") is None


def test_offload_manager_chain_lookup():
    mgr = KVOffloadManager(host_pool=HostKVPool())
    hashes = [(0, (1, 2)), (hash((0, (1, 2))), (3, 4)),
              (99, (5, 6))]
    mgr.offload_page(hashes[0], *_payload(1))
    mgr.offload_page(hashes[1], *_payload(2))
    # Chain breaks at the third hash.
    assert mgr.lookup_chain(hashes) == 2
    assert mgr.fetch(hashes[0]) is not None
    assert mgr.fetch(hashes[2]) is None


def _wire_body(payload):
    import msgpack

    from production_stack_tpu.engine.offload import KV_WIRE_VERSION
    return msgpack.packb({
        "version": KV_WIRE_VERSION,
        "arrays": [
            {"data": a.tobytes(), "shape": list(a.shape),
             "dtype": str(a.dtype)}
            for a in payload
        ],
    })


def test_cache_server_roundtrip():
    """PUT/GET/HEAD against the remote cache server over HTTP."""
    import msgpack
    from aiohttp.test_utils import TestClient, TestServer

    async def run():
        client = TestClient(TestServer(build_cache_server(1024 ** 2)))
        await client.start_server()
        try:
            k, v = _payload(5)
            put = await client.put("/kv/abc", data=_wire_body((k, v)))
            assert put.status == 200
            head = await client.head("/kv/abc")
            assert head.status == 200
            got = await client.get("/kv/abc")
            assert got.status == 200
            obj = msgpack.unpackb(await got.read())
            a = obj["arrays"][0]
            k2 = np.frombuffer(a["data"], np.float32).reshape(k.shape)
            np.testing.assert_array_equal(k, k2)
            missing = await client.get("/kv/nope")
            assert missing.status == 404
            stats = await (await client.get("/stats")).json()
            assert stats["entries"] == 1
        finally:
            await client.close()
    asyncio.run(run())


def test_cache_server_rejects_bad_payloads():
    """Decode-side allowlist: junk bytes, disallowed dtypes, and
    shape/byte-count mismatches all 400 instead of getting stored (or
    crashing the server)."""
    import msgpack
    from aiohttp.test_utils import TestClient, TestServer

    async def run():
        client = TestClient(TestServer(build_cache_server(1024 ** 2)))
        await client.start_server()
        try:
            bad = [
                b"\x00not msgpack at all",
                msgpack.packb({"no": "arrays"}),
                # float64 is not an allowed page dtype.
                _wire_body((np.zeros((2, 2), np.float64),)),
                # byte count disagrees with shape*itemsize.
                msgpack.packb({"arrays": [{
                    "data": b"\x00" * 7, "shape": [2, 2],
                    "dtype": "float32"}]}),
                # negative dim.
                msgpack.packb({"arrays": [{
                    "data": b"", "shape": [-1], "dtype": "int8"}]}),
            ]
            for i, body in enumerate(bad):
                resp = await client.put(f"/kv/bad{i}", data=body)
                assert resp.status == 400, f"payload {i} accepted"
                assert (await client.head(f"/kv/bad{i}")).status == 404
            # A valid payload still lands.
            ok = await client.put(
                "/kv/good", data=_wire_body(_payload(1)))
            assert ok.status == 200
        finally:
            await client.close()
    asyncio.run(run())


def _dtype_payloads():
    """One payload per page storage format the tiers must carry:
    float32 and bfloat16 full-precision (k, v) pairs, and the int8
    4-tuple with float32 scales."""
    import ml_dtypes
    rng = np.random.RandomState(7)
    f32 = tuple(rng.randn(2, 2, 32, 16).astype(np.float32)
                for _ in range(2))
    bf16 = tuple(rng.randn(2, 2, 32, 16).astype(ml_dtypes.bfloat16)
                 for _ in range(2))
    int8 = (
        rng.randint(-127, 128, (2, 2, 32, 16)).astype(np.int8),
        rng.randint(-127, 128, (2, 2, 32, 16)).astype(np.int8),
        rng.rand(2, 2, 16).astype(np.float32),
        rng.rand(2, 2, 16).astype(np.float32),
    )
    return {"float32": f32, "bfloat16": bf16, "int8": int8}


def test_host_pool_roundtrip_all_dtypes():
    pool = HostKVPool(max_bytes=64 * 1024 ** 2)
    payloads = _dtype_payloads()
    for name, payload in payloads.items():
        pool.put(name, payload)
    for name, payload in payloads.items():
        got = pool.get(name)
        assert len(got) == len(payload)
        for a, b in zip(payload, got):
            assert b.dtype == a.dtype
            np.testing.assert_array_equal(
                a.view(np.uint8), b.view(np.uint8))
    # Byte accounting covers every array in the tuple.
    assert pool.used_bytes == sum(
        a.nbytes for p in payloads.values() for a in p)


def test_remote_client_roundtrip_all_dtypes():
    """RemoteKVClient against a live cache server: every page dtype —
    including bfloat16, which np.dtype() alone cannot resolve — must
    round-trip byte-exact through the msgpack wire."""
    import threading

    from aiohttp import web

    from production_stack_tpu.engine.offload import RemoteKVClient

    loop = asyncio.new_event_loop()
    started = threading.Event()
    port_box = {}

    def serve():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(build_cache_server(64 * 1024 ** 2))
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)
        loop.run_until_complete(site.start())
        port_box["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())

    thread = threading.Thread(target=serve, daemon=True)
    thread.start()
    assert started.wait(10)
    try:
        client = RemoteKVClient(
            f"http://127.0.0.1:{port_box['port']}")
        for name, payload in _dtype_payloads().items():
            assert client.put(name, payload), name
            assert client.contains(name)
            got = client.get(name)
            assert got is not None and len(got) == len(payload)
            for a, b in zip(payload, got):
                assert b.dtype == a.dtype, name
                np.testing.assert_array_equal(
                    a.view(np.uint8), b.view(np.uint8))
        assert client.get("missing") is None
    finally:
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=10)


def test_stable_key_namespaced_by_dtype_and_manager_isolation():
    page_hash = (0, (1, 2, 3))
    keys = {_stable_key(page_hash, dt)
            for dt in ("", "float32", "bfloat16", "int8")}
    assert len(keys) == 4
    # Two managers sharing one host pool but with different kv_dtype
    # never see each other's pages.
    pool = HostKVPool()
    m_int8 = KVOffloadManager(host_pool=pool, kv_dtype="int8")
    m_bf16 = KVOffloadManager(host_pool=pool, kv_dtype="bfloat16")
    m_int8.offload_page(page_hash, *_payload(1))
    assert m_int8.fetch(page_hash) is not None
    assert m_bf16.fetch(page_hash) is None


def _make_engine(num_pages, offload=True, kv_dtype="auto"):
    model = tiny_model_config("llama")
    return LLMEngine(EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_pages=num_pages,
                          kv_cache_dtype=kv_dtype),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=256,
                                  prefill_chunk_size=64),
        offload=OffloadConfig(enable=offload,
                              host_pool_bytes=256 * 1024 ** 2),
    ))


def test_engine_restores_evicted_prefix_from_host_pool():
    """Fill HBM, evict a cached prefix, and watch the offload tier
    restore it — with identical generation output."""
    sampling = lambda: SamplingParams(  # noqa: E731
        max_tokens=4, temperature=0.0, ignore_eos=True)
    shared = list(range(1, 65))  # 64 tokens = 4 full pages

    # Reference output from a clean engine.
    ref_engine = _make_engine(num_pages=64, offload=False)
    expected = ref_engine.generate(
        shared + [99, 98], sampling()).output_token_ids

    # Tiny cache: 15 usable pages.
    engine = _make_engine(num_pages=16)
    first = engine.generate(shared + [99, 98], sampling())
    assert first.output_token_ids == expected

    # Fill the cache with unrelated prompts to force eviction of the
    # shared prefix pages into the host pool.
    for i in range(4):
        engine.generate([200 + i] * 80, sampling())
    assert engine.offload.offloaded_pages > 0

    # Same shared prefix again: must restore from the host pool.
    restored_before = engine.offload.restored_pages
    again = engine.generate(shared + [99, 98], sampling())
    assert engine.offload.restored_pages > restored_before
    assert again.output_token_ids == expected


def test_engine_restores_quantized_pages_from_host_pool():
    """The eviction/restore cycle with --kv-cache-dtype int8: 4-array
    payloads (data + scales) move through the host pool and land back
    in HBM with identical generation output."""
    sampling = lambda: SamplingParams(  # noqa: E731
        max_tokens=4, temperature=0.0, ignore_eos=True)
    shared = list(range(1, 65))  # 64 tokens = 4 full pages

    ref_engine = _make_engine(num_pages=64, offload=False,
                              kv_dtype="int8")
    expected = ref_engine.generate(
        shared + [99, 98], sampling()).output_token_ids

    # num_pages input 5 expands to ~17 int8 pages — small enough that
    # the filler prompts below force the shared prefix out to the
    # host pool.
    engine = _make_engine(num_pages=5, kv_dtype="int8")
    assert engine.runner.kv_quantized
    assert 10 < engine.config.cache.num_pages < 32
    first = engine.generate(shared + [99, 98], sampling())
    assert first.output_token_ids == expected

    for i in range(4):
        engine.generate([200 + i] * 80, sampling())
    assert engine.offload.offloaded_pages > 0
    # The offloaded payloads are the quantized 4-tuples.
    some = next(iter(engine.offload.host._pool.values()))
    assert len(some) == 4
    assert some[0].dtype == np.int8
    assert some[2].dtype == np.float32

    restored_before = engine.offload.restored_pages
    again = engine.generate(shared + [99, 98], sampling())
    assert engine.offload.restored_pages > restored_before
    assert again.output_token_ids == expected
