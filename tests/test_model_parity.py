"""Numerical parity vs HuggingFace transformers (torch CPU).

A tiny randomly-initialized HF Llama / OPT checkpoint is saved to disk,
loaded through our weights loader, and greedy generation + prompt logits
are compared. This is the engine's ground-truth correctness gate: if the
paged-attention path, RoPE, scanned layers and the weights mapping are
all right, logits match to float32 tolerance.
"""

import numpy as np
import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams
from production_stack_tpu.engine.weights import (
    load_model_config,
    load_weights,
)


def _save_tiny_llama(tmp_path):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(0)
    config = LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    model = LlamaForCausalLM(config)
    model.eval()
    path = str(tmp_path / "tiny_llama")
    model.save_pretrained(path)
    return path, model


def _save_tiny_opt(tmp_path):
    import torch
    from transformers import OPTConfig, OPTForCausalLM
    torch.manual_seed(0)
    config = OPTConfig(
        vocab_size=128,
        hidden_size=64,
        ffn_dim=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        max_position_embeddings=256,
        do_layer_norm_before=True,
        word_embed_proj_dim=64,
    )
    model = OPTForCausalLM(config)
    model.eval()
    path = str(tmp_path / "tiny_opt")
    model.save_pretrained(path)
    return path, model


def _save_tiny_gpt2(tmp_path):
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel
    torch.manual_seed(0)
    config = GPT2Config(
        vocab_size=128,
        n_embd=64,
        n_layer=2,
        n_head=4,
        n_positions=256,
        n_inner=128,
    )
    model = GPT2LMHeadModel(config)
    model.eval()
    path = str(tmp_path / "tiny_gpt2")
    model.save_pretrained(path)
    return path, model


def _save_tiny_qwen2(tmp_path):
    import torch
    from transformers import Qwen2Config, Qwen2ForCausalLM
    torch.manual_seed(0)
    config = Qwen2Config(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    model = Qwen2ForCausalLM(config)
    model.eval()
    path = str(tmp_path / "tiny_qwen2")
    model.save_pretrained(path)
    return path, model


def _save_tiny_mixtral(tmp_path):
    import torch
    from transformers import MixtralConfig, MixtralForCausalLM
    torch.manual_seed(0)
    config = MixtralConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        num_local_experts=4,
        num_experts_per_tok=2,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    model = MixtralForCausalLM(config)
    model.eval()
    path = str(tmp_path / "tiny_mixtral")
    model.save_pretrained(path)
    return path, model


def _engine_from(path, dtype="float32", page_size=8, chunk=16):
    config = load_model_config(path)
    config.dtype = dtype
    engine_config = EngineConfig(
        model=config,
        cache=CacheConfig(page_size=page_size, num_pages=128),
        scheduler=SchedulerConfig(
            max_num_seqs=4, max_model_len=256, prefill_chunk_size=chunk
        ),
    )
    params = load_weights(path, config)
    return LLMEngine(engine_config, params=params)


def _hf_greedy(model, prompt, n):
    import torch
    ids = torch.tensor([prompt])
    with torch.no_grad():
        out = model.generate(
            ids, max_new_tokens=n, do_sample=False,
            pad_token_id=0,
        )
    return out[0, len(prompt):].tolist()


@pytest.mark.parametrize(
    "saver",
    [_save_tiny_llama, _save_tiny_opt, _save_tiny_gpt2,
     _save_tiny_qwen2, _save_tiny_mixtral],
    ids=["llama", "opt", "gpt2", "qwen2", "mixtral"])
def test_greedy_generation_matches_hf(tmp_path, saver):
    path, hf_model = saver(tmp_path)
    engine = _engine_from(path)
    prompt = [3, 11, 25, 99, 7, 42, 58, 13, 77, 21, 5, 64]
    expected = _hf_greedy(hf_model, prompt, 12)
    seq = engine.generate(prompt, SamplingParams(
        max_tokens=12, temperature=0.0, ignore_eos=True
    ))
    assert seq.output_token_ids == expected


def test_mixtral_expert_parallel_matches_single_device(tmp_path):
    """Expert-parallel sharding (expert axis over 'tp') must not
    change generation."""
    import jax
    from production_stack_tpu.parallel.mesh import build_mesh
    path, hf_model = _save_tiny_mixtral(tmp_path)
    prompt = [3, 11, 25, 99, 7, 42, 58, 13]
    expected = _hf_greedy(hf_model, prompt, 8)

    config = load_model_config(path)
    config.dtype = "float32"
    engine_config = EngineConfig(
        model=config,
        cache=CacheConfig(page_size=8, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256,
                                  prefill_chunk_size=16),
    )
    mesh = build_mesh(tensor_parallel_size=2)  # shards E=4 experts 2-way
    params = load_weights(path, config)
    engine = LLMEngine(engine_config, mesh=mesh, params=params)
    seq = engine.generate(prompt, SamplingParams(
        max_tokens=8, temperature=0.0, ignore_eos=True
    ))
    assert seq.output_token_ids == expected


def test_chunked_prefill_matches_single_shot(tmp_path):
    """A prompt longer than the chunk size must produce the same tokens."""
    path, hf_model = _save_tiny_llama(tmp_path)
    prompt = list(np.random.RandomState(7).randint(1, 128, size=50))
    prompt = [int(x) for x in prompt]
    expected = _hf_greedy(hf_model, prompt, 8)
    engine = _engine_from(path, chunk=16)  # forces 4 prefill chunks
    seq = engine.generate(prompt, SamplingParams(
        max_tokens=8, temperature=0.0, ignore_eos=True
    ))
    assert seq.output_token_ids == expected


def test_prefix_cache_reuse_is_exact(tmp_path):
    """Second request sharing a long prefix must generate identically
    while hitting the prefix cache."""
    path, hf_model = _save_tiny_llama(tmp_path)
    engine = _engine_from(path, page_size=8)
    shared = [int(x) for x in
              np.random.RandomState(3).randint(1, 128, size=40)]
    p1 = shared + [9, 9]
    p2 = shared + [17, 23]

    s1 = engine.generate(p1, SamplingParams(
        max_tokens=6, temperature=0.0, ignore_eos=True))
    hits_before = engine.cache_manager.prefix_hit_tokens
    s2 = engine.generate(p2, SamplingParams(
        max_tokens=6, temperature=0.0, ignore_eos=True))
    assert engine.cache_manager.prefix_hit_tokens > hits_before

    assert s1.output_token_ids == _hf_greedy(hf_model, p1, 6)
    assert s2.output_token_ids == _hf_greedy(hf_model, p2, 6)
