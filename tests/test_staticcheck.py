"""Self-tests for the staticcheck analyzer suite (tier 1).

Each analyzer gets a negative fixture: a synthetic tree (built with
``Project.from_sources``, never touching disk) with a planted
violation the rule must catch, plus the corresponding clean shape it
must NOT flag. On top of that: waiver semantics (a valid waiver
suppresses, a typoed waiver is itself a finding), fingerprint
stability (baseline survives line drift), parse-error surfacing, the
real tree staying clean modulo the checked-in baseline, and the CLI
exit-code/JSON contract. Rule catalog: docs/static_analysis.md.
"""

import json
import pathlib
import textwrap

from production_stack_tpu.staticcheck import (
    Finding,
    Project,
    REGISTRY,
    run_rules,
)
from production_stack_tpu.staticcheck import baseline as baseline_mod
from production_stack_tpu.staticcheck.cli import main as cli_main

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _run(sources, rule):
    """Findings for ``rule`` on an in-memory tree (waiver/parse
    findings from run_rules filtered out unless asked for)."""
    project = Project.from_sources(
        {path: textwrap.dedent(text) for path, text in sources.items()})
    return [f for f in run_rules(project, rules=[rule])
            if f.rule == rule]


# ---- registry sanity ---------------------------------------------------


def test_all_advertised_rules_are_registered():
    import production_stack_tpu.staticcheck.analyzers  # noqa: F401
    expected = {"tracer-hygiene", "async-blocking", "metrics-contract",
                "config-contract", "no-timeout", "host-read",
                "kv-parity", "span-contract", "slo-contract",
                "page-lifecycle", "state-machine", "lock-discipline",
                "endpoint-contract"}
    assert expected <= set(REGISTRY)


# ---- tracer-hygiene ----------------------------------------------------


def test_tracer_hygiene_catches_planted_hazards():
    findings = _run({
        "production_stack_tpu/ops/bad_kernel.py": """\
            import jax
            import jax.numpy as jnp

            EAGER = jnp.zeros((4,))

            @jax.jit
            def step(x):
                if float(x[0]) > 0:
                    x = x + 1
                while x[0] > 0:
                    x = x - 1
                if x.shape[0] == 1:
                    x = x * 2
                return x.sum().item()
            """,
    }, "tracer-hygiene")
    messages = "\n".join(f.message for f in findings)
    assert "eager jnp.zeros" in messages
    assert "float()-driven branch" in messages
    assert "Python while-loop" in messages
    assert "shape-dependent branch" in messages
    assert ".item() in traced function step" in messages


def test_tracer_hygiene_finds_jit_by_call_and_pallas_kernels():
    # Traced-ness must follow jax.jit(fn) references and kernels
    # handed to pl.pallas_call, not just decorators.
    findings = _run({
        "production_stack_tpu/ops/indirect.py": """\
            import jax
            from jax.experimental import pallas as pl

            def _impl(x):
                return x.sum().item()

            run = jax.jit(_impl)

            def _kernel(ref, out):
                if bool(ref[0]):
                    out[0] = ref[0]

            def launch(x):
                return pl.pallas_call(_kernel, out_shape=None)(x)
            """,
    }, "tracer-hygiene")
    messages = "\n".join(f.message for f in findings)
    assert ".item() in traced function _impl" in messages
    assert "bool()-driven branch in traced function _kernel" in messages


def test_tracer_hygiene_ignores_clean_and_untraced_code():
    findings = _run({
        "production_stack_tpu/ops/clean_kernel.py": """\
            import jax
            import jax.numpy as jnp
            from jax import lax

            @jax.jit
            def step(x):
                return lax.cond(x[0] > 0, lambda v: v + 1,
                                lambda v: v - 1, x)

            def host_helper(arr):
                # Not traced: host-side coercion is fine here.
                if float(arr[0]) > 0:
                    return int(arr.sum())
                return 0
            """,
    }, "tracer-hygiene")
    assert findings == []


# ---- async-blocking ----------------------------------------------------


def test_async_blocking_catches_planted_calls():
    findings = _run({
        "production_stack_tpu/router/bad_async.py": """\
            import time
            import requests

            async def handler():
                time.sleep(1)
                requests.get("http://x", timeout=5)
                with open("/tmp/f") as f:
                    return f.read()
            """,
    }, "async-blocking")
    messages = "\n".join(f.message for f in findings)
    assert len(findings) == 3
    assert "time.sleep blocks the event loop" in messages
    assert "synchronous requests." in messages
    assert "synchronous open() on the event loop" in messages
    assert all("in async def handler" in f.message for f in findings)


def test_async_blocking_skips_nested_sync_defs():
    # The file_storage.py pattern: blocking IO wrapped in a sync def
    # handed to asyncio.to_thread runs off-loop and must not flag.
    findings = _run({
        "production_stack_tpu/router/offloop.py": """\
            import asyncio
            import time

            async def handler():
                def _work():
                    time.sleep(1)
                    with open("/tmp/f") as f:
                        return f.read()
                return await asyncio.to_thread(_work)

            def sync_helper():
                time.sleep(1)  # not a coroutine: out of scope
            """,
    }, "async-blocking")
    assert findings == []


# ---- no-timeout (migrated PR1 lint) ------------------------------------


def test_no_timeout_flags_only_unbounded_calls():
    findings = _run({
        "production_stack_tpu/router/client.py": """\
            import requests

            def bad():
                return requests.get("http://x")

            def good():
                return requests.get("http://x", timeout=5)
            """,
    }, "no-timeout")
    assert len(findings) == 1
    assert findings[0].line == 4


# ---- host-read (migrated PR3 lint) -------------------------------------


def test_host_read_catches_planted_dispatch_read():
    findings = _run({
        "production_stack_tpu/engine/model_runner.py": """\
            import numpy as np

            def dispatch_decode(self, batch):
                tokens = np.asarray(batch.tokens)
                probed = batch.logits.item()
                batch.state.block_until_ready()
                return tokens, probed
            """,
    }, "host-read")
    blocking = [f for f in findings
                if "blocking host read in dispatch_decode" in f.message]
    assert len(blocking) == 3
    # The synthetic runner defines only one DISPATCH_PATH function;
    # the tracks-reality check reports the rest as out of coverage.
    assert any("DISPATCH_PATH names not found" in f.message
               for f in findings)


# ---- metrics-contract --------------------------------------------------

_METRICS_FIXTURE = {
    "production_stack_tpu/engine/metrics.py": """\
        def render():
            return [
                "vllm:num_requests_running 1",
                "vllm:ghost_total 2",
            ]
        """,
    "production_stack_tpu/engine/server.py": """\
        PORT = 8000
        """,
    "production_stack_tpu/router/stats/engine_stats.py": """\
        _METRIC_MAP = {
            "vllm:num_requests_running": "num_running_requests",
            "vllm:stale_metric": "missing_attr",
        }
        _ROUTER_UNSCRAPED = frozenset()

        class EngineStats:
            num_running_requests: int = 0
            orphan_field: int = 0
        """,
    "production_stack_tpu/router/services/metrics_service.py": """\
        def refresh_gauges(es):
            return es.num_running_requests
        """,
}


def test_metrics_contract_catches_planted_drift():
    findings = _run(_METRICS_FIXTURE, "metrics-contract")
    messages = "\n".join(f.message for f in findings)
    # Engine emits a name the scraper never reads.
    assert "engine emits vllm:ghost_total" in messages
    # Scraper maps a name no engine file emits.
    assert "references vllm:stale_metric" in messages
    # Map target is not a declared EngineStats field.
    assert "not a declared field" in messages
    # Scraped field never re-exported by the metrics service.
    assert "EngineStats.orphan_field is scraped but never" in messages


def test_metrics_contract_accepts_explicit_drop_marker():
    fixture = dict(_METRICS_FIXTURE)
    fixture["production_stack_tpu/router/stats/engine_stats.py"] = """\
        _METRIC_MAP = {
            "vllm:num_requests_running": "num_running_requests",
        }
        _ROUTER_UNSCRAPED = frozenset({
            "vllm:ghost_total",
        })

        class EngineStats:
            num_running_requests: int = 0
        """
    assert _run(fixture, "metrics-contract") == []


# ---- span-contract -----------------------------------------------------

# An agreeing router-span surface rides along in every span fixture so
# the event-vocabulary tests exercise only the drift they plant.
_ROUTER_TRACING_SRC = """\
    import json

    class RequestSpan:
        def to_json(self):
            return json.dumps({
                "span": "request",
                "request_id": self.request_id,
            })
    """
_ROUTER_FIELDS_DOC = """\
    <!-- router-span-fields:begin -->
    | Field | Meaning |
    |---|---|
    | `span` | record marker |
    | `request_id` | stitch key |
    <!-- router-span-fields:end -->
    """

_SPAN_FIXTURE = {
    "production_stack_tpu/engine/tracing.py": """\
        SPAN_EVENTS = (
            "enqueue",
            "finish",
        )
        """,
    "production_stack_tpu/engine/engine.py": """\
        def step(tracer, seq_id):
            tracer.event(seq_id, "enqueue")
            tracer.event(seq_id, "fist_token")
        """,
    "production_stack_tpu/router/tracing.py": _ROUTER_TRACING_SRC,
    "docs/observability.md": textwrap.dedent("""\
        <!-- span-events:begin -->
        | Event | When |
        |---|---|
        | `enqueue` | admitted |
        | `ghost_event` | never |
        <!-- span-events:end -->
        """) + textwrap.dedent(_ROUTER_FIELDS_DOC),
}


def test_span_contract_catches_planted_drift():
    findings = _run(_SPAN_FIXTURE, "span-contract")
    messages = "\n".join(f.message for f in findings)
    # Emitted literal outside the vocabulary (the classic typo).
    assert "span event 'fist_token' is not in SPAN_EVENTS" in messages
    # Vocabulary entry with no docs row.
    assert "'finish' is in SPAN_EVENTS but undocumented" in messages
    # Documented name not in the vocabulary.
    assert "'ghost_event'" in messages and "stale row" in messages


def test_span_contract_accepts_agreeing_surfaces():
    fixture = dict(_SPAN_FIXTURE)
    fixture["production_stack_tpu/engine/engine.py"] = """\
        def step(tracer, seq_id):
            tracer.event(seq_id, "enqueue")
            tracer.event(seq_id, "finish")
        """
    fixture["docs/observability.md"] = textwrap.dedent("""\
        <!-- span-events:begin -->
        | Event | When |
        |---|---|
        | `enqueue` | admitted |
        | `finish` | closed |
        <!-- span-events:end -->
        """) + textwrap.dedent(_ROUTER_FIELDS_DOC)
    assert _run(fixture, "span-contract") == []


def test_span_contract_requires_marker_block():
    fixture = dict(_SPAN_FIXTURE)
    fixture["docs/observability.md"] = "no markers here\n"
    findings = _run(fixture, "span-contract")
    assert any("marker block" in f.message for f in findings)


def test_span_contract_router_fields_two_way_drift():
    """An emitted-but-undocumented router span field and a
    documented-but-gone field are both findings."""
    fixture = dict(_SPAN_FIXTURE)
    fixture["production_stack_tpu/router/tracing.py"] = """\
        import json

        class RequestSpan:
            def to_json(self):
                return json.dumps({
                    "span": "request",
                    "request_id": self.request_id,
                    "tenant": self.tenant,
                })
        """
    findings = _run(fixture, "span-contract")
    messages = "\n".join(f.message for f in findings)
    assert ("router span field 'tenant' is emitted" in messages)
    # Now plant the reverse: docs advertise a field to_json dropped.
    fixture["production_stack_tpu/router/tracing.py"] = """\
        import json

        class RequestSpan:
            def to_json(self):
                return json.dumps({"span": "request"})
        """
    findings = _run(fixture, "span-contract")
    messages = "\n".join(f.message for f in findings)
    assert ("router span field 'request_id'" in messages
            and "does not emit" in messages)


# ---- slo-contract ------------------------------------------------------

_SLO_FIXTURE = {
    "production_stack_tpu/obs/slo.py": """\
        from dataclasses import dataclass, field

        @dataclass
        class SLOTarget:
            ttft_s: float = None
            objective: float = None

        @dataclass
        class SLOSpec:
            objective: float = 0.99
            classes: dict = field(default_factory=dict)
        """,
    "docs/observability.md": """\
        ## SLO ledger

        Fields: `objective`, `classes` and per-target `ttft_s`.
        """,
}


def test_slo_contract_catches_undocumented_field():
    findings = _run(_SLO_FIXTURE, "slo-contract")
    assert findings == []
    fixture = dict(_SLO_FIXTURE)
    fixture["production_stack_tpu/obs/slo.py"] = (
        _SLO_FIXTURE["production_stack_tpu/obs/slo.py"]
        .replace("objective: float = 0.99",
                 "objective: float = 0.99\n"
                 "            ghost_knob: int = 0"))
    findings = _run(fixture, "slo-contract")
    assert any("SLOSpec.ghost_knob is not documented" in f.message
               for f in findings)


def test_slo_contract_requires_spec_classes():
    fixture = dict(_SLO_FIXTURE)
    fixture["production_stack_tpu/obs/slo.py"] = "X = 1\n"
    findings = _run(fixture, "slo-contract")
    messages = "\n".join(f.message for f in findings)
    assert ("SLOTarget not found" in messages
            or "dataclass SLOTarget not found" in messages)


# ---- config-contract ---------------------------------------------------

_CONFIG_FIXTURE = {
    "production_stack_tpu/engine/config.py": """\
        class CacheConfig:
            page_size: int = 16
            secret_knob: int = 0

        class EngineConfig:
            cache: CacheConfig = None

            def validate(self):
                if self.cache.page_size and self.cache.secret_knob:
                    raise ValueError(
                        "page_size conflicts with secret_knob")

        EXCLUSIVITY_RULES = (
            ("cache.page_size", "cache.secret_knob",
             "conflicts with secret_knob"),
        )
        """,
    "production_stack_tpu/engine/server.py": """\
        def parse_args(parser):
            parser.add_argument("--page-size", type=int)
        """,
    "production_stack_tpu/fleet/spec.py": """\
        FLEET_INTERNAL_FIELDS = ()

        class AutoscalerSpec:
            tolerance: float = 0.1

        class PoolSpec:
            name: str = ""

        class FleetSpec:
            pools: list = None

        def from_dict(raw):
            return (raw.get("pools"), raw.get("name"),
                    raw.get("tolerance"))
        """,
    "production_stack_tpu/fleet/__main__.py": """\
        def parse_args(parser):
            parser.add_argument("--fleet-spec-file")
        """,
    "production_stack_tpu/parallel/topology.py": """\
        class MeshPlan:
            tp: int = 1
            ghost_axis: int = 1
        """,
    "production_stack_tpu/parallel/mesh.py": """\
        def build_mesh(tensor_parallel_size=1):
            return MeshPlan(tp=tensor_parallel_size)
        """,
}


def test_config_contract_catches_planted_drift():
    findings = _run(_CONFIG_FIXTURE, "config-contract")
    messages = "\n".join(f.message for f in findings)
    # Field with no flag, alias or internal marker.
    assert "config field cache.secret_knob has no CLI flag" in messages
    # Exclusivity pair with a raise but no pytest.raises test.
    assert "rejection is untested" in messages
    # Flag missing from every markdown doc.
    assert "--page-size appears in no markdown doc" in messages
    # Fleet CLI flags are held to the same docs bar.
    assert "--fleet-spec-file appears in no markdown doc" in messages
    # MeshPlan field build_mesh never threads (negative fixture).
    assert ("MeshPlan field ghost_axis is not threaded" in messages)
    assert ("MeshPlan field ghost_axis is not documented"
            in messages or "docs/parallelism.md missing" in messages)


def test_config_contract_accepts_markers_docs_and_tests():
    fixture = dict(_CONFIG_FIXTURE)
    fixture["production_stack_tpu/engine/config.py"] += (
        'INTERNAL_FIELDS = {"cache.secret_knob"}\n')
    fixture["docs/engine_flags.md"] = (
        "| `--page-size` | 16 | Tokens per KV page |\n"
        "| `--fleet-spec-file` | required | Fleet spec path |\n")
    fixture["docs/fleet.md"] = "pools name tolerance\n"
    fixture["production_stack_tpu/parallel/topology.py"] = (
        "class MeshPlan:\n    tp: int = 1\n")
    fixture["docs/parallelism.md"] = "MeshPlan `tp` axis placement\n"
    fixture["tests/test_exclusivity.py"] = textwrap.dedent("""\
        import pytest

        def test_page_size_conflict(make_config):
            with pytest.raises(ValueError,
                               match="conflicts with secret_knob"):
                make_config(secret_knob=1)
        """)
    assert _run(fixture, "config-contract") == []


def test_config_contract_covers_autotune_section():
    """The autotune section (docs/autotuning.md) is operator surface:
    an AutotuneConfig field with no flag, alias or internal marker must
    be flagged under its autotune. path like any other section."""
    fixture = dict(_CONFIG_FIXTURE)
    fixture["production_stack_tpu/engine/config.py"] = textwrap.dedent("""\
        class CacheConfig:
            page_size: int = 16

        class AutotuneConfig:
            mode: str = "off"
            ghost_gain: float = 0.5

        class EngineConfig:
            cache: CacheConfig = None
            autotune: AutotuneConfig = None

        CLI_FLAG_ALIASES = {"autotune.mode": "--autotune"}
        """)
    fixture["production_stack_tpu/engine/server.py"] = textwrap.dedent("""\
        def parse_args(parser):
            parser.add_argument("--page-size", type=int)
            parser.add_argument("--autotune")
        """)
    findings = _run(fixture, "config-contract")
    messages = "\n".join(f.message for f in findings)
    assert ("config field autotune.ghost_gain has no CLI flag"
            in messages)
    # The aliased mode field is reachable, so only the ghost drifts.
    assert "config field autotune.mode" not in messages


def test_config_contract_catches_fleet_spec_drift():
    fixture = dict(_CONFIG_FIXTURE)
    fixture["production_stack_tpu/fleet/spec.py"] = textwrap.dedent("""\
        FLEET_INTERNAL_FIELDS = ("ghost_field",)

        class PoolSpec:
            name: str = ""
            secret_pool_knob: int = 0

        class FleetSpec:
            pools: list = None

        def from_dict(raw):
            return (raw.get("pools"), raw.get("name"))
        """)
    fixture["docs/fleet.md"] = "pools name\n"
    findings = _run(fixture, "config-contract")
    messages = "\n".join(f.message for f in findings)
    # Spec field that no JSON key reaches.
    assert ("fleet spec field pools[].secret_pool_knob is never parsed"
            in messages)
    # The same field is also absent from docs/fleet.md.
    assert ("fleet spec field pools[].secret_pool_knob is not "
            "documented" in messages)
    # Marker naming a field that does not exist.
    assert "unknown fleet spec field ghost_field" in messages


def test_config_contract_catches_rollout_spec_drift():
    """The rollout/revision sub-specs (docs/fleet.md) are contract
    surface too: an undocumented or unparsed RolloutSpec/RevisionSpec
    field must be flagged under its pools[].rollout. / pools[].revision.
    spec path."""
    fixture = dict(_CONFIG_FIXTURE)
    fixture["production_stack_tpu/fleet/spec.py"] = textwrap.dedent("""\
        FLEET_INTERNAL_FIELDS = ()

        class RevisionSpec:
            build_id: str = ""

        class RolloutSpec:
            canary_weight: float = 0.1
            secret_rollout_knob: float = 0.0

        class PoolSpec:
            name: str = ""
            revision: RevisionSpec = None
            rollout: RolloutSpec = None

        class FleetSpec:
            pools: list = None

        def from_dict(raw):
            return (raw.get("pools"), raw.get("name"),
                    raw.get("revision"), raw.get("rollout"),
                    raw.get("build_id"), raw.get("canary_weight"))
        """)
    fixture["docs/fleet.md"] = (
        "pools name revision rollout build_id canary_weight\n")
    findings = _run(fixture, "config-contract")
    messages = "\n".join(f.message for f in findings)
    # The planted knob is neither parseable from a spec file...
    assert ("fleet spec field pools[].rollout.secret_rollout_knob is "
            "never parsed" in messages)
    # ...nor documented in docs/fleet.md.
    assert ("fleet spec field pools[].rollout.secret_rollout_knob is "
            "not documented" in messages)
    # The documented, parsed fields stay clean.
    assert "pools[].rollout.canary_weight" not in messages
    assert "pools[].revision.build_id" not in messages


# ---- kv-parity ---------------------------------------------------------


def test_kv_parity_catches_uncovered_and_unregistered_impls():
    findings = _run({
        "production_stack_tpu/ops/attention.py": """\
            ATTENTION_IMPLS = {
                "xla": ("production_stack_tpu.ops.attention",
                        "paged_real"),
                "phantom": ("production_stack_tpu.ops.gone",
                            "paged_phantom"),
            }

            def paged_real(q):
                return q
            """,
        "production_stack_tpu/ops/new_attention.py": """\
            def paged_new(q):
                return q
            """,
        "tests/test_int8_parity.py": """\
            def test_int8_real_impl():
                assert paged_real
            """,
    }, "kv-parity")
    messages = "\n".join(f.message for f in findings)
    # Registered impl with no int8-named test referencing it.
    assert "paged_phantom" in messages
    # paged_* module that never registered itself.
    assert ("ops/new_attention.py defines a paged_* entry point"
            in messages)
    # The covered impl is NOT among the findings.
    assert "references paged_real" not in messages


# ---- waivers -----------------------------------------------------------


def test_valid_waiver_suppresses_the_finding():
    project = Project.from_sources({
        "production_stack_tpu/router/w.py":
            "import requests\n"
            "requests.get('http://x')  # lint: allow-no-timeout\n",
    })
    assert run_rules(project, rules=["no-timeout"]) == []


def test_typoed_waiver_fails_loudly():
    # allow-no-timeoutS: must NOT suppress, and must surface as its
    # own unknown-waiver finding naming the bad token.
    project = Project.from_sources({
        "production_stack_tpu/router/w.py":
            "import requests\n"
            "requests.get('http://x')  # lint: allow-no-timeouts\n",
    })
    findings = run_rules(project, rules=["no-timeout"])
    by_rule = {f.rule for f in findings}
    assert "no-timeout" in by_rule
    assert "unknown-waiver" in by_rule
    unknown = [f for f in findings if f.rule == "unknown-waiver"]
    assert "no-timeouts" in unknown[0].message


# ---- framework mechanics -----------------------------------------------


def test_parse_error_is_a_finding_not_a_pass():
    project = Project.from_sources({
        "production_stack_tpu/router/broken.py": "def oops(:\n",
    })
    findings = run_rules(project, rules=["no-timeout"])
    assert any(f.rule == "parse-error" for f in findings)


def test_fingerprint_ignores_line_number_but_not_content():
    a = Finding(rule="r", path="p.py", line=10, message="m",
                snippet="requests.get('http://x')")
    b = Finding(rule="r", path="p.py", line=99, message="m",
                snippet="  requests.get('http://x')  ")
    c = Finding(rule="r", path="p.py", line=10, message="m",
                snippet="requests.post('http://x')")
    assert a.fingerprint() == b.fingerprint()
    assert a.fingerprint() != c.fingerprint()


# ---- the real tree -----------------------------------------------------


def test_repo_tree_is_clean_modulo_baseline():
    project = Project.from_root(ROOT)
    findings = run_rules(project)
    fingerprints = baseline_mod.load_fingerprints(ROOT)
    new, _ = baseline_mod.split_new(findings, fingerprints)
    assert not new, (
        "new staticcheck findings (fix, waive with a justified "
        "# lint: allow-<rule>, or --update-baseline and review the "
        "diff):\n" + "\n".join(f.render() for f in new))


def test_cli_json_contract(capsys):
    code = cli_main(["--json", "--root", str(ROOT)])
    payload = json.loads(capsys.readouterr().out)
    assert code == 0
    assert payload["findings"] == []
    assert set(payload["rules"]) == set(REGISTRY)


def test_cli_rejects_unknown_rule(capsys):
    code = cli_main(["--rule", "not-a-rule", "--root", str(ROOT)])
    assert code == 2
    assert "unknown rule" in capsys.readouterr().err
