"""Subprocess body for tests/test_multihost.py.

Runs one process of a 2-process jax.distributed CPU rig (4 virtual
devices each -> 8 global). Process 0 drives a tiny engine generation
through the MultihostStepBridge; process 1 mirrors the steps. Process 0
prints the generated token ids as JSON on the last line.

Usage: python multihost_helper.py <coordinator> <num_procs> <proc_id>
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    coordinator, num_procs, proc_id = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    )
    from production_stack_tpu.parallel.distributed import (
        MultihostStepBridge,
        init_distributed,
    )
    init_distributed(coordinator, num_procs, proc_id)
    assert jax.device_count() == 4 * num_procs

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        SchedulerConfig,
        tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams
    from production_stack_tpu.parallel.mesh import build_mesh

    # tp=2 spans processes (device order interleaves? either way the
    # mesh is global); dp covers the rest.
    mesh = build_mesh(tensor_parallel_size=2, data_parallel_size=4)
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64),
        # decode_steps > 1 exercises the decode-BURST payload over the
        # bridge (active/budgets/stop_tokens keys must be derivable
        # from the (kind, t) header — a template drift here deadlocks
        # the slice).
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32,
                                  decode_steps=4),
    )
    engine = LLMEngine(config, mesh=mesh)
    bridge = MultihostStepBridge(engine.runner)

    # Every host builds the embedder (as server.py main does) so
    # KIND_EMBED dispatches mirror slice-wide.
    from production_stack_tpu.engine.embeddings import Embedder
    embedder = Embedder(config.model, engine.runner.params,
                        max_len=config.scheduler.max_model_len)
    engine.runner.embedder = embedder

    if proc_id == 0:
        engine.runner.bridge = bridge
        embedder.bridge = bridge
        seq = engine.generate(
            list(range(1, 20)),
            SamplingParams(max_tokens=6, temperature=0.0,
                           ignore_eos=True),
        )
        vecs = embedder.embed_batch([[1, 2, 3], [4, 5, 6, 7]])
        bridge.shutdown()
        print("TOKENS=" + json.dumps(seq.output_token_ids))
        print("EMBED=" + json.dumps(
            [round(float(x), 6) for x in vecs[:, 0]]))
    else:
        bridge.worker_loop()
        print("WORKER_DONE")


if __name__ == "__main__":
    main()
