"""Per-layer vs stacked KV cache layout parity.

CacheConfig.cache_layout='per_layer' is the round-3 decode-roofline
experiment (benchmarks/results/round3_onchip_notes.md §0.6): a tuple of
L per-layer buffers instead of one stacked [L, ...] array. Numerics
must be identical — the layout changes buffer granularity (scatter
operands, donation aliasing), not math.
"""

import numpy as np
import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams


def _run_engine(layout: str, family: str = "llama",
                decode_steps: int = 1):
    config = EngineConfig(
        model=tiny_model_config(family),
        cache=CacheConfig(page_size=16, num_pages=64,
                          cache_layout=layout),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32,
                                  prefill_batch_size=2,
                                  decode_steps=decode_steps),
    )
    engine = LLMEngine(config)
    prompts = [list(range(3, 23)), list(range(40, 50))]
    seqs = []
    for p in prompts:
        sid = engine.add_request(
            p, SamplingParams(max_tokens=8, temperature=0.0,
                              ignore_eos=True))
        seqs.append(engine.sequences[sid])
    while engine.has_work():
        engine.step()
    return [s.output_token_ids for s in seqs]


@pytest.mark.parametrize("family", ["llama", "gpt2"])
def test_per_layer_matches_stacked_greedy(family):
    a = _run_engine("stacked", family)
    b = _run_engine("per_layer", family)
    assert a == b


def test_per_layer_matches_stacked_burst_decode():
    a = _run_engine("stacked", decode_steps=4)
    b = _run_engine("per_layer", decode_steps=4)
    assert a == b


def test_per_layer_offload_page_roundtrip():
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64,
                          cache_layout="per_layer"),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32,
                                  prefill_batch_size=2),
    )
    engine = LLMEngine(config)
    engine.add_request(list(range(3, 35)),
                       SamplingParams(max_tokens=4, temperature=0.0,
                                      ignore_eos=True))
    while engine.has_work():
        engine.step()
    runner = engine.runner
    k, v = runner.read_page(1)
    L = config.model.num_hidden_layers
    assert k.shape[0] == L and v.shape[0] == L
    # Round-trip: write back what was read, read again, identical.
    runner.write_page(1, k, v)
    k2, v2 = runner.read_page(1)
    np.testing.assert_array_equal(k, k2)
    np.testing.assert_array_equal(v, v2)

    # The serde page format matches the stacked layout's.
    config_s = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32,
                                  prefill_batch_size=2),
    )
    engine_s = LLMEngine(config_s)
    ks, _ = engine_s.runner.read_page(1)
    assert ks.shape == k.shape

def test_auto_layout_resolves_per_layer():
    """The 'auto' default resolves to per_layer (the on-chip measured
    winner, benchmarks/results/decode_probe.json 2026-07-31) for
    plain configs."""
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32,
                                  prefill_batch_size=2),
    )
    assert config.cache.cache_layout == "auto"
    engine = LLMEngine(config)
    assert engine.runner.cache_layout == "per_layer"
    assert isinstance(engine.runner.k_cache, tuple)


def test_auto_layout_resolves_stacked_under_pp():
    """pp shards the stacked L axis, so 'auto' resolves to stacked
    there (explicit per_layer+pp stays a loud error)."""
    import jax

    from production_stack_tpu.engine.config import ParallelConfig
    from production_stack_tpu.parallel.mesh import build_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices for a pp mesh")
    parallel = ParallelConfig(pipeline_parallel_size=2)
    mesh = build_mesh(pipeline_parallel_size=2)
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32,
                                  prefill_batch_size=2),
        parallel=parallel,
    )
    engine = LLMEngine(config, mesh=mesh)
    assert engine.runner.cache_layout == "stacked"


def test_rejects_unknown_layout():
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64,
                          cache_layout="bogus"),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128),
    )
    with pytest.raises(ValueError, match="cache_layout"):
        LLMEngine(config)
