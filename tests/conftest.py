"""Test harness configuration.

- Forces JAX onto a virtual 8-device CPU mesh so every sharding/parallel
  test runs without TPU hardware (the driver dry-runs the real multi-chip
  path separately via __graft_entry__.dryrun_multichip).
- Runs ``async def`` tests via asyncio.run (no pytest-asyncio in env).
- Resets all process-wide singletons between tests.
"""

import asyncio
import inspect
import os
import sys

# Must happen before any jax backend init anywhere in the test session.
# Hard override (not setdefault): the environment ships JAX_PLATFORMS=axon
# (the tunneled TPU); tests must run hermetically on the virtual CPU mesh
# regardless of TPU/relay health.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Undo the TPU-tunnel plugin's jax_platforms config override so no test
# can accidentally dial the tunnel (sitecustomize runs register(), which
# does jax.config.update("jax_platforms", "axon,cpu") — config beats env).
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Execute coroutine test functions with asyncio.run."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(func(**kwargs))
        return True
    return None


@pytest.fixture(autouse=True)
def reset_singletons():
    """Each test gets fresh router singletons."""
    from production_stack_tpu.utils import SingletonMeta
    SingletonMeta._instances.clear()
    yield
    SingletonMeta._instances.clear()
