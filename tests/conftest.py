"""Test harness configuration.

- Forces JAX onto a virtual 8-device CPU mesh so every sharding/parallel
  test runs without TPU hardware (the driver dry-runs the real multi-chip
  path separately via __graft_entry__.dryrun_multichip).
- Runs ``async def`` tests via asyncio.run (no pytest-asyncio in env).
- Resets all process-wide singletons between tests.
"""

import asyncio
import inspect
import os
import sys

# Must happen before any jax backend init anywhere in the test session.
# Hard override (not setdefault): the environment ships JAX_PLATFORMS=axon
# (the tunneled TPU); tests must run hermetically on the virtual CPU mesh
# regardless of TPU/relay health.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Undo the TPU-tunnel plugin's jax_platforms config override so no test
# can accidentally dial the tunnel (sitecustomize runs register(), which
# does jax.config.update("jax_platforms", "axon,cpu") — config beats env).
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
    # Persistent XLA compilation cache: the slow lane is dominated by
    # recompiles of the same engine programs every run (26m at round
    # 4). Lower the min-compile-time floor so the many ~1s engine
    # programs are cached too. Override the location with
    # JAX_TEST_CACHE_DIR; wiped by `rm -rf ~/.cache/psx_jax_tests`.
    _cache_dir = os.environ.get(
        "JAX_TEST_CACHE_DIR",
        os.path.expanduser("~/.cache/psx_jax_tests"))
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

# Compile-heavy modules (engine builds, shard_map parity, multi-process
# rigs) form the SLOW lane; everything else is the fast lane the common
# dev loop runs (round-3 verdict: 206 tests / 24 min had no split).
#   fast lane:  pytest -m "not slow"   (target <= 8 min)
#   full suite: pytest                 (CI nightly / pre-merge)
# Files can still mark themselves explicitly; this list saves each
# slow module from repeating the boilerplate.
_SLOW_MODULES = {
    "test_70b_lowering",
    "test_abort",
    "test_batch_e2e",
    "test_deferred_kv",
    "test_batched_prefill",
    "test_cache_layout",
    "test_context_parallel_serving",
    "test_e2e_router_engine",
    "test_embeddings",
    "test_engine_server",
    "test_guided_json",
    "test_kv_offload",
    "test_logit_bias",
    "test_lora",
    "test_min_tokens",
    "test_model_parity",
    "test_multihost",
    "test_multistep_decode",
    "test_pallas_attention",
    "test_pallas_lowering",
    "test_pipeline_parallel",
    "test_quantization",
    "test_real_checkpoint_sharded",
    "test_ring_attention",
    "test_score_rerank",
    "test_spec_decode",
    "test_tracing",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.module.__name__ in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)


def pytest_pyfunc_call(pyfuncitem):
    """Execute coroutine test functions with asyncio.run."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(func(**kwargs))
        return True
    return None


@pytest.fixture(autouse=True)
def reset_singletons():
    """Each test gets fresh router singletons."""
    from production_stack_tpu.utils import SingletonMeta
    SingletonMeta._instances.clear()
    yield
    SingletonMeta._instances.clear()
