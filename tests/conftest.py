"""Test harness configuration.

- Forces JAX onto a virtual 8-device CPU mesh so every sharding/parallel
  test runs without TPU hardware (the driver dry-runs the real multi-chip
  path separately via __graft_entry__.dryrun_multichip).
- Runs ``async def`` tests via asyncio.run (no pytest-asyncio in env).
- Resets all process-wide singletons between tests.
"""

import asyncio
import inspect
import os
import sys

# Must happen before any jax import anywhere in the test session.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Execute coroutine test functions with asyncio.run."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(func(**kwargs))
        return True
    return None


@pytest.fixture(autouse=True)
def reset_singletons():
    """Each test gets fresh router singletons."""
    from production_stack_tpu.utils import SingletonMeta
    SingletonMeta._instances.clear()
    yield
    SingletonMeta._instances.clear()
