"""Self-tuning controllers (docs/autotuning.md).

Framework semantics (mode gate, cadence, dead-band, clamps, span
emission), the drift-sentinel guardrail's freeze/latch/reset contract,
each engine-side controller's closed loop against fake engine state,
the fleet pool-split controller, config validation, and the fake
engine's autotune surface. All host-side — fake clocks, fake engines,
no device programs.
"""

from types import SimpleNamespace

import pytest
from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.autotune import (
    Autotuner,
    CheckpointIntervalController,
    Controller,
    DriftGuardrail,
    PoolSplitController,
    PrefillBudgetController,
    QoSShedController,
    SpecKController,
)
from production_stack_tpu.engine.config import AutotuneConfig
from production_stack_tpu.testing.fake_engine import build_fake_engine


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


class FakeTracer:
    """Records (span_id, event_name, fields) like engine tracing."""

    def __init__(self):
        self.events = []

    def start(self, sid, **kw):
        pass

    def event(self, sid, name, **fields):
        self.events.append((sid, name, fields))

    def finish(self, sid, **kw):
        pass


class ScriptedController(Controller):
    """Observes a scripted signal; proposes signal as the target."""

    name = "scripted"

    def __init__(self, lo=0.0, hi=100.0, value=10.0):
        super().__init__(lo=lo, hi=hi)
        self.value = value
        self.signal = None
        self.applied = []

    def observe(self):
        return self.signal

    def current(self):
        return self.value

    def propose(self, signal):
        return signal

    def apply(self, target):
        self.applied.append(target)
        self.value = target


def _cfg(**kw):
    defaults = dict(mode="on", interval_s=1.0, dead_band=0.05)
    defaults.update(kw)
    return AutotuneConfig(**defaults)


def _tuner(ctrl, clock, drift_flags=None, burn_rate=None,
           tracer=None, **cfg_kw):
    return Autotuner(_cfg(**cfg_kw), [ctrl], tracer=tracer,
                     clock=clock, drift_flags=drift_flags,
                     burn_rate=burn_rate)


# ---------------------------------------------------------------------------
# Guardrail: freeze on drift flip, latch, never re-apply until reset.
# ---------------------------------------------------------------------------


def test_guardrail_freezes_latches_and_resets():
    """The satellite contract: a controller whose applied decisions
    precede an injected perf-drift flip must freeze, latch the
    frozen gauge, and never apply again until an operator reset."""
    clock = FakeClock()
    flags = {"decode": 0.0}
    ctrl = ScriptedController(value=10.0)
    tuner = _tuner(ctrl, clock, drift_flags=lambda: dict(flags))

    # Healthy tick: decision applies.
    ctrl.signal = 20.0
    tuner.tick()
    assert ctrl.applied == [20.0]
    assert tuner.frozen_flags() == {"scripted": False}

    # Drift flips 0 -> 1 within the freeze window of that decision.
    clock.advance(5.0)
    flags["decode"] = 1.0
    ctrl.signal = 30.0
    tuner.tick()
    assert tuner.frozen_flags() == {"scripted": True}
    # The tick that froze it must not have applied.
    assert ctrl.applied == [20.0]

    # Latched: the flag staying high (no new flip) keeps it frozen,
    # and decisions keep being computed (shadow) but never applied.
    for _ in range(5):
        clock.advance(60.0)  # far outside the blame window
        ctrl.signal = 40.0
        tuner.tick()
    assert tuner.frozen_flags() == {"scripted": True}
    assert ctrl.applied == [20.0]
    assert tuner.decisions_total["scripted"] > 1
    assert tuner.applied_total["scripted"] == 1
    assert tuner.active_count() == 0

    # Operator reset unlatches; the next decision applies again and
    # the old decisions carry no blame (no instant re-freeze).
    assert tuner.reset() == ["scripted"]
    ctrl.signal = 50.0
    tuner.tick()
    assert ctrl.applied == [20.0, 50.0]
    assert tuner.frozen_flags() == {"scripted": False}


def test_guardrail_burn_rise_freezes_only_recent_deciders():
    clock = FakeClock()
    burn = {"v": 0.2}
    rail = DriftGuardrail(freeze_window_s=30.0, burn_threshold=1.0,
                          burn_rate=lambda: burn["v"], clock=clock)
    rail.note_applied("old")
    clock.advance(100.0)
    rail.note_applied("recent")
    clock.advance(1.0)
    burn["v"] = 0.5  # rise below threshold: no trip
    assert rail.scan() == []
    burn["v"] = 1.5  # rise to/above threshold: trip
    assert rail.scan() == ["recent"]
    assert rail.is_frozen("recent") and not rail.is_frozen("old")
    # A falling burn never trips.
    burn["v"] = 0.1
    rail.note_applied("old")
    assert rail.scan() == []


def test_guardrail_reset_single_controller():
    clock = FakeClock()
    rail = DriftGuardrail(clock=clock)
    rail._frozen = {"a": 1.0, "b": 2.0}
    assert rail.reset("a") == ["a"]
    assert not rail.is_frozen("a") and rail.is_frozen("b")
    assert rail.reset("missing") == []
    assert rail.reset() == ["b"]
    assert rail.frozen() == {}


# ---------------------------------------------------------------------------
# Autotuner framework: modes, cadence, dead-band, clamps, spans.
# ---------------------------------------------------------------------------


def test_off_mode_never_ticks():
    clock = FakeClock()
    ctrl = ScriptedController()
    tuner = _tuner(ctrl, clock, mode="off")
    ctrl.signal = 99.0
    for _ in range(5):
        clock.advance(10.0)
        assert tuner.maybe_tick() is False
    assert ctrl.applied == []
    assert tuner.active_count() == 0


def test_shadow_computes_and_logs_but_never_applies():
    clock = FakeClock()
    tracer = FakeTracer()
    ctrl = ScriptedController(value=10.0)
    tuner = _tuner(ctrl, clock, tracer=tracer, mode="shadow")
    ctrl.signal = 20.0
    tuner.tick()
    assert ctrl.applied == []
    assert tuner.decisions_total["scripted"] == 1
    assert tuner.applied_total["scripted"] == 0
    assert tuner.active_count() == 0  # nothing is being applied
    [(_, name, fields)] = tracer.events
    assert name == "autotune_decision"
    assert fields["mode"] == "shadow"
    assert fields["applied"] is False
    assert fields["target"] == 20.0


def test_on_mode_span_marks_applied():
    clock = FakeClock()
    tracer = FakeTracer()
    ctrl = ScriptedController(value=10.0)
    tuner = _tuner(ctrl, clock, tracer=tracer)
    ctrl.signal = 20.0
    tuner.tick()
    [(_, name, fields)] = tracer.events
    assert fields["applied"] is True
    assert ctrl.applied == [20.0]
    assert tuner.active_count() == 1


def test_cadence_is_bounded_by_interval():
    clock = FakeClock()
    ctrl = ScriptedController(value=10.0)
    tuner = _tuner(ctrl, clock, interval_s=2.0)
    ctrl.signal = 20.0
    assert tuner.maybe_tick() is True
    ctrl.signal = 30.0
    clock.advance(1.0)
    assert tuner.maybe_tick() is False  # inside the interval
    clock.advance(1.0)
    assert tuner.maybe_tick() is True
    assert ctrl.applied == [20.0, 30.0]


def test_dead_band_drops_small_moves():
    clock = FakeClock()
    ctrl = ScriptedController(hi=200.0, value=100.0)
    tuner = _tuner(ctrl, clock, dead_band=0.1)
    ctrl.signal = 105.0  # within 10% of 100
    tuner.tick()
    assert ctrl.applied == []
    ctrl.signal = 120.0
    tuner.tick()
    assert ctrl.applied == [120.0]


def test_targets_are_clamped_to_controller_band():
    clock = FakeClock()
    ctrl = ScriptedController(lo=5.0, hi=15.0, value=10.0)
    tuner = _tuner(ctrl, clock)
    ctrl.signal = 1000.0
    tuner.tick()
    assert ctrl.applied == [15.0]
    ctrl.signal = -1000.0
    tuner.tick()
    assert ctrl.applied == [15.0, 5.0]


def test_no_signal_and_hold_proposals_are_skipped():
    clock = FakeClock()
    ctrl = ScriptedController(value=10.0)
    ctrl.propose = lambda s: None  # hold
    tuner = _tuner(ctrl, clock)
    ctrl.signal = None
    tuner.tick()
    ctrl.signal = 50.0
    tuner.tick()
    assert ctrl.applied == []
    assert tuner.decisions_total["scripted"] == 0


def test_broken_controller_is_contained():
    clock = FakeClock()
    ctrl = ScriptedController(value=10.0)
    boom = ScriptedController(value=1.0)
    boom.name = "boom"

    def explode():
        raise RuntimeError("tick bomb")

    boom.observe = explode
    tuner = Autotuner(_cfg(), [boom, ctrl], clock=clock)
    ctrl.signal = 20.0
    tuner.tick()  # must not raise, and the healthy controller runs
    assert ctrl.applied == [20.0]


def test_controller_selection_allowlist():
    clock = FakeClock()
    a = ScriptedController()
    b = ScriptedController()
    b.name = "other"
    tuner = Autotuner(_cfg(controllers="other"), [a, b], clock=clock)
    assert [c.name for c in tuner.controllers] == ["other"]


def test_status_payload_shape():
    clock = FakeClock()
    ctrl = ScriptedController(lo=0.0, hi=100.0, value=10.0)
    tuner = _tuner(ctrl, clock)
    status = tuner.status()
    assert status["mode"] == "on"
    assert status["active_controllers"] == 1
    [entry] = status["controllers"]
    assert entry["name"] == "scripted"
    assert entry["knob"] == 10.0
    assert entry["frozen"] is False


# ---------------------------------------------------------------------------
# AutotuneConfig validation.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kw", [
    dict(mode="auto"),
    dict(interval_s=0.0),
    dict(dead_band=1.0),
    dict(dead_band=-0.1),
    dict(freeze_window_s=-1.0),
    dict(min_spec_k=0),
    dict(min_checkpoint_interval_tokens=0),
    dict(min_checkpoint_interval_tokens=8192,
         max_checkpoint_interval_tokens=4096),
    dict(min_shed_threshold=0.0),
    dict(min_shed_threshold=1.5),
])
def test_autotune_config_rejects_bad_values(kw):
    with pytest.raises(ValueError):
        AutotuneConfig(**kw)


def test_autotune_config_defaults_are_off():
    cfg = AutotuneConfig()
    assert cfg.mode == "off"


# ---------------------------------------------------------------------------
# Engine-side controllers against fake engine state.
# ---------------------------------------------------------------------------


def _fake_seq(seq_id, drafted=0, accepted=0, cap=None):
    return SimpleNamespace(seq_id=seq_id, spec_drafted_total=drafted,
                           spec_accepted_total=accepted,
                           spec_k_cap=cap)


def test_spec_k_controller_cuts_on_collapse_and_regrows():
    seqs = [_fake_seq("a"), _fake_seq("b")]
    engine = SimpleNamespace(
        config=SimpleNamespace(
            scheduler=SimpleNamespace(speculative_k=6)),
        scheduler=SimpleNamespace(running=seqs))
    ctrl = SpecKController(engine, _cfg(min_spec_k=1))
    assert ctrl.enabled()
    assert ctrl.observe() is None  # no drafts yet: no signal

    # Acceptance collapse: lots drafted, almost nothing accepted.
    for s in seqs:
        s.spec_drafted_total = 40
        s.spec_accepted_total = 2
    signal = ctrl.observe()
    assert signal == pytest.approx(4 / 80)
    target = ctrl.propose(signal)
    assert target < ctrl.current()
    ctrl.apply(ctrl.clamp(target))
    assert all(s.spec_k_cap == 5 for s in seqs)

    # Sustained collapse walks the caps to the floor, never below.
    for _ in range(10):
        for s in seqs:
            s.spec_drafted_total += 40
            s.spec_accepted_total += 2
        ctrl.apply(ctrl.clamp(ctrl.propose(ctrl.observe())))
    assert all(s.spec_k_cap == 1 for s in seqs)

    # Recovery: high acceptance grows the caps back toward k.
    for _ in range(10):
        for s in seqs:
            s.spec_drafted_total += 40
            s.spec_accepted_total += 38
        ctrl.apply(ctrl.clamp(ctrl.propose(ctrl.observe())))
    assert all(s.spec_k_cap == 6 for s in seqs)


def test_spec_k_controller_disabled_without_speculation():
    engine = SimpleNamespace(
        config=SimpleNamespace(
            scheduler=SimpleNamespace(speculative_k=0)),
        scheduler=SimpleNamespace(running=[]))
    assert not SpecKController(engine, _cfg()).enabled()


def _prefill_engine():
    from production_stack_tpu.engine.metrics import EngineMetrics
    metrics = EngineMetrics()
    return SimpleNamespace(
        config=SimpleNamespace(scheduler=SimpleNamespace(
            unified_step=True, prefill_chunk_size=64,
            prefill_batch_size=4)),
        scheduler=SimpleNamespace(mixed_prefill_budget=256),
        metrics=metrics)


def test_prefill_budget_controller_shrinks_over_target():
    engine = _prefill_engine()
    ctrl = PrefillBudgetController(
        engine, _cfg(target_itl_ms=50.0))
    assert ctrl.enabled()
    for _ in range(32):
        engine.metrics.itl.observe(0.2)  # way over 50ms
    p99 = ctrl.observe()
    assert p99 is not None and p99 > 0.05
    ctrl.apply(ctrl.clamp(ctrl.propose(p99)))
    assert engine.scheduler.mixed_prefill_budget == 192
    # Sustained pressure bottoms out at one chunk.
    for _ in range(5):
        for _ in range(32):
            engine.metrics.itl.observe(0.2)
        target = ctrl.propose(ctrl.observe())
        if target is not None:
            ctrl.apply(ctrl.clamp(target))
    assert engine.scheduler.mixed_prefill_budget == 64


def test_prefill_budget_controller_grows_with_headroom():
    engine = _prefill_engine()
    engine.scheduler.mixed_prefill_budget = 64
    ctrl = PrefillBudgetController(
        engine, _cfg(target_itl_ms=50.0))
    for _ in range(32):
        engine.metrics.itl.observe(0.002)  # far under target
    ctrl.apply(ctrl.clamp(ctrl.propose(ctrl.observe())))
    assert engine.scheduler.mixed_prefill_budget == 128


def test_prefill_budget_needs_window_volume():
    engine = _prefill_engine()
    ctrl = PrefillBudgetController(engine, _cfg())
    engine.metrics.itl.observe(0.2)  # below MIN_WINDOW_TOKENS
    assert ctrl.observe() is None


def test_checkpoint_interval_halves_on_resume_and_relaxes():
    engine = SimpleNamespace(
        config=SimpleNamespace(checkpoint_interval_tokens=1024),
        stream_resumes=0)
    ctrl = CheckpointIntervalController(
        engine, _cfg(min_checkpoint_interval_tokens=64,
                     max_checkpoint_interval_tokens=4096))
    assert ctrl.enabled()
    assert ctrl.observe() is None  # first tick primes the window
    engine.stream_resumes = 2  # a crash replayed somewhere
    ctrl.apply(ctrl.clamp(ctrl.propose(ctrl.observe())))
    assert engine.config.checkpoint_interval_tokens == 512
    # Quiet ticks relax it back up (doubling after the quiet run).
    for _ in range(ctrl.QUIET_TICKS_TO_RELAX - 1):
        assert ctrl.propose(ctrl.observe()) is None
    ctrl.apply(ctrl.clamp(ctrl.propose(ctrl.observe())))
    assert engine.config.checkpoint_interval_tokens == 1024


def _qos_engine(waiting=0):
    return SimpleNamespace(
        config=SimpleNamespace(
            qos=SimpleNamespace(shed_threshold=0.95),
            scheduler=SimpleNamespace(max_queue_len=100)),
        scheduler=SimpleNamespace(num_waiting=waiting,
                                  spec_degrade_clamp=False))


def test_qos_shed_tightens_on_queue_growth_and_relaxes():
    engine = _qos_engine(waiting=10)
    ctrl = QoSShedController(engine, _cfg(min_shed_threshold=0.5))
    assert ctrl.observe() is None  # primes the window
    engine.scheduler.num_waiting = 40  # growing and deep
    ctrl.apply(ctrl.clamp(ctrl.propose(ctrl.observe())))
    assert engine.config.qos.shed_threshold == pytest.approx(0.90)
    assert engine.scheduler.spec_degrade_clamp is True
    # Drained queue relaxes back to the static and lifts the clamp.
    engine.scheduler.num_waiting = 2
    ctrl.apply(ctrl.clamp(ctrl.propose(ctrl.observe())))
    assert engine.config.qos.shed_threshold == pytest.approx(0.95)
    assert engine.scheduler.spec_degrade_clamp is False


# ---------------------------------------------------------------------------
# Fleet-side pool split controller.
# ---------------------------------------------------------------------------


def _pools():
    from production_stack_tpu.fleet.spec import PoolSpec
    return [
        PoolSpec(name="prefill", role="prefill", min_replicas=1,
                 max_replicas=4),
        PoolSpec(name="decode", role="decode", min_replicas=1,
                 max_replicas=4),
    ]


def _signals(pmean, dmean, burn=-1.0):
    return {"prefill": SimpleNamespace(prefill_time_mean_s=pmean,
                                       decode_time_mean_s=dmean,
                                       slo_burn_rate=burn)}


def test_pool_split_moves_replica_on_phase_drift():
    clock = FakeClock()
    ctrl = PoolSplitController(ratio_band=0.5, cooldown_s=60.0,
                               clock=clock)
    pools = _pools()
    desired = {"prefill": 2, "decode": 2}
    # First complete observation sets the baseline; no move.
    out = ctrl.rebalance(pools, _signals(1.0, 1.0), desired)
    assert out == desired
    # Prefill phase slows past the band: decode lends a replica.
    clock.advance(61.0)
    out = ctrl.rebalance(pools, _signals(2.0, 1.0), desired)
    assert out == {"prefill": 3, "decode": 1}
    assert ctrl.moves_total == 1
    # Cooldown blocks an immediate second move.
    clock.advance(1.0)
    assert ctrl.rebalance(pools, _signals(2.0, 1.0),
                          desired) == desired
    # Drift the other way (after cooldown) moves it back.
    clock.advance(61.0)
    out = ctrl.rebalance(pools, _signals(0.4, 1.0), desired)
    assert out == {"prefill": 1, "decode": 3}


def test_pool_split_respects_replica_bands():
    clock = FakeClock()
    ctrl = PoolSplitController(ratio_band=0.5, cooldown_s=0.0,
                               clock=clock)
    pools = _pools()
    ctrl.rebalance(pools, _signals(1.0, 1.0), {"prefill": 2,
                                               "decode": 2})
    clock.advance(1.0)
    # Source already at min: no move.
    out = ctrl.rebalance(pools, _signals(2.0, 1.0),
                         {"prefill": 2, "decode": 1})
    assert out == {"prefill": 2, "decode": 1}


def test_pool_split_freezes_on_burn_rise_until_reset():
    clock = FakeClock()
    ctrl = PoolSplitController(ratio_band=0.5, cooldown_s=0.0,
                               burn_threshold=1.0, clock=clock)
    pools = _pools()
    desired = {"prefill": 2, "decode": 2}
    ctrl.rebalance(pools, _signals(1.0, 1.0, burn=0.1), desired)
    clock.advance(1.0)
    out = ctrl.rebalance(pools, _signals(2.0, 1.0, burn=0.1), desired)
    assert out == {"prefill": 3, "decode": 1}
    # Burn rises past threshold within the freeze window of the move.
    clock.advance(1.0)
    out = ctrl.rebalance(pools, _signals(2.0, 1.0, burn=2.0), desired)
    assert out == desired
    assert ctrl.frozen
    # Latched: even with the drift persisting, no more moves.
    clock.advance(120.0)
    assert ctrl.rebalance(pools, _signals(3.0, 1.0, burn=2.0),
                          desired) == desired
    ctrl.reset()
    assert not ctrl.frozen
    clock.advance(1.0)
    out = ctrl.rebalance(pools, _signals(3.0, 1.0, burn=2.0), desired)
    assert out == {"prefill": 3, "decode": 1}


# ---------------------------------------------------------------------------
# Fake engine autotune surface (knob echo + metrics + status).
# ---------------------------------------------------------------------------


async def test_fake_engine_autotune_knob_echo_roundtrip():
    app = build_fake_engine()
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        # Default: off, nothing frozen, no knobs.
        resp = await client.get("/autotune/status")
        status = await resp.json()
        assert status["mode"] == "off"
        assert status["active_controllers"] == 0

        # Seed knobs via the echo endpoint.
        resp = await client.post("/autotune/knobs", json={
            "mode": "on",
            "knobs": {"spec_k": 4.0, "qos_shed": 0.9},
            "frozen": {"spec_k": True},
            "decisions": {"spec_k": 7},
        })
        status = await resp.json()
        assert status["mode"] == "on"
        assert status["active_controllers"] == 1  # qos_shed only
        by_name = {c["name"]: c for c in status["controllers"]}
        assert by_name["spec_k"]["frozen"] is True
        assert by_name["spec_k"]["knob"] == 4.0
        assert by_name["spec_k"]["decisions"] == 7

        # The gauges show up in /metrics with the controller label.
        resp = await client.get("/metrics")
        text = await resp.text()
        assert 'vllm:autotune_frozen{controller="spec_k"} 1.0' in text
        assert ('vllm:autotune_knob_value{controller="qos_shed"} 0.9'
                in text)
        assert "vllm:autotune_active_controllers 1" in text

        # Reset unfreezes; clear empties the echo state.
        resp = await client.post("/autotune/reset", json={})
        assert (await resp.json())["reset"] == ["spec_k"]
        resp = await client.get("/autotune/status")
        status = await resp.json()
        assert status["active_controllers"] == 2
        await client.post("/autotune/knobs", json={"clear": True})
        resp = await client.get("/autotune/status")
        assert (await resp.json())["mode"] == "off"
    finally:
        await client.close()


def test_autotune_decision_span_event_is_registered():
    from production_stack_tpu.engine.tracing import SPAN_EVENTS
    assert "autotune_decision" in SPAN_EVENTS


def test_drift_bench_extra_keys_have_directions():
    """The drift A/B keys bench.py merges must classify, so
    benchcompare can hold goodput/freeze/parity as directions."""
    from production_stack_tpu.benchcompare import classify
    assert classify("autotune_on_goodput_tok_s") == "higher"
    assert classify("autotune_off_itl_p99_s") == "lower"
    assert classify("autotune_on_frozen_controllers") == "lower"
    assert classify("autotune_on_extra_compile_events") == "lower"
    assert classify("autotune_shadow_byte_identical") == "higher"
    assert classify("autotune_on_compile_events_delta") == "lower"
