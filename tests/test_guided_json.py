"""Guided JSON decoding (OpenAI ``response_format: json_object``):
the byte-level automaton (engine/guided.py) masks inadmissible tokens
inside the sampling step — on device, in the decode-burst scan carry —
so even a RANDOM-weight model emits structurally valid JSON."""

import json

import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams


def _engine(decode_steps=1, deferred=False):
    return LLMEngine(EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256,
                                  prefill_chunk_size=32,
                                  decode_steps=decode_steps,
                                  deferred_kv_writes=deferred),
    ))


PROMPT = list(range(5, 25))


def _json_of(seq, engine) -> str:
    # Keep only byte-range ids (the automaton forbids everything
    # else anyway except EOS, which the stop set consumes).
    return bytes(t for t in seq.output_token_ids if t < 256).decode(
        "utf-8", "replace")


def _gen(engine, **kw):
    sampling = dict(max_tokens=120, temperature=0.8, seed=7,
                    guided="json")
    sampling.update(kw)
    return engine.generate(PROMPT, SamplingParams(**sampling))


def test_random_weights_emit_valid_json():
    engine = _engine()
    seq = _gen(engine)
    text = _json_of(seq, engine)
    if seq.finish_reason is not None and seq.finish_reason.value == "stop":
        parsed = json.loads(text)  # structurally valid, starts as object
        assert isinstance(parsed, dict)
    else:
        # Budget ran out mid-document: every prefix must still be a
        # valid JSON prefix — re-walk it through the automaton.
        fsm = engine.guided_fsm
        s = 0
        for t in seq.output_token_ids:
            s = fsm.advance(s, t)
            assert s >= 0


def test_guided_parity_across_decode_paths():
    ref = _gen(_engine()).output_token_ids
    burst = _gen(_engine(decode_steps=4)).output_token_ids
    deferred = _gen(_engine(decode_steps=4,
                            deferred=True)).output_token_ids
    assert burst == ref
    assert deferred == ref


def test_guided_and_free_rows_coexist():
    """A guided row must not constrain (or be corrupted by) a free
    row in the same batch."""
    engine = _engine(decode_steps=4)
    free_ref = engine.generate(PROMPT, SamplingParams(
        max_tokens=12, temperature=0.0,
        ignore_eos=True)).output_token_ids

    engine2 = _engine(decode_steps=4)
    seqs = []
    for kw in (dict(max_tokens=12, temperature=0.0, ignore_eos=True),
               dict(max_tokens=120, temperature=0.8, seed=7,
                    guided="json")):
        sid = engine2.add_request(PROMPT, SamplingParams(**kw))
        seqs.append(engine2.sequences[sid])
    while engine2.has_work():
        engine2.step()
    free, guided = seqs
    assert free.output_token_ids == free_ref
    fsm = engine2.guided_fsm
    s = 0
    for t in guided.output_token_ids:
        s = fsm.advance(s, t)
        assert s >= 0


def test_greedy_guided_deterministic_and_valid():
    a = _gen(_engine(decode_steps=4), temperature=0.0, seed=None)
    b = _gen(_engine(decode_steps=4), temperature=0.0, seed=None)
    assert a.output_token_ids == b.output_token_ids
    fsm_state = 0
    fsm = _engine().guided_fsm
    for t in a.output_token_ids:
        fsm_state = fsm.advance(fsm_state, t)
        assert fsm_state >= 0


def test_server_response_format_parsing():
    from production_stack_tpu.engine.server import _sampling_from_body

    assert _sampling_from_body(
        {"response_format": {"type": "json_object"}}, 256
    ).guided == "json"
    assert _sampling_from_body(
        {"response_format": {"type": "text"}}, 256).guided is None
    assert _sampling_from_body({}, 256).guided is None
    with pytest.raises(ValueError, match="unsupported response_format"):
        _sampling_from_body(
            {"response_format": {"type": "json_schema"}}, 256)
    with pytest.raises(ValueError, match="must be an object"):
        _sampling_from_body({"response_format": "json_object"}, 256)


def test_guided_rejected_without_byte_tokenizer():
    engine = _engine()
    engine.guided_fsm = None  # simulate an HF-tokenizer engine
    with pytest.raises(ValueError, match="byte-range tokenizer"):
        engine.add_request(PROMPT, SamplingParams(guided="json"))
