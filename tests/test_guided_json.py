"""Guided JSON decoding (OpenAI ``response_format: json_object``):
the byte-level automaton (engine/guided.py) masks inadmissible tokens
inside the sampling step — on device, in the decode-burst scan carry —
so even a RANDOM-weight model emits structurally valid JSON."""

import json

import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams


def _engine(decode_steps=1, deferred=False):
    return LLMEngine(EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256,
                                  prefill_chunk_size=32,
                                  decode_steps=decode_steps,
                                  deferred_kv_writes=deferred),
    ))


PROMPT = list(range(5, 25))


def _json_of(seq, engine) -> str:
    # Keep only byte-range ids (the automaton forbids everything
    # else anyway except EOS, which the stop set consumes).
    return bytes(t for t in seq.output_token_ids if t < 256).decode(
        "utf-8", "replace")


def _gen(engine, **kw):
    sampling = dict(max_tokens=120, temperature=0.8, seed=7,
                    guided="json")
    sampling.update(kw)
    return engine.generate(PROMPT, SamplingParams(**sampling))


def test_random_weights_emit_valid_json():
    engine = _engine()
    seq = _gen(engine)
    text = _json_of(seq, engine)
    if seq.finish_reason is not None and seq.finish_reason.value == "stop":
        parsed = json.loads(text)  # structurally valid, starts as object
        assert isinstance(parsed, dict)
    else:
        # Budget ran out mid-document: every prefix must still be a
        # valid JSON prefix — re-walk it through the automaton.
        fsm = engine.guided_fsm
        s = 0
        for t in seq.output_token_ids:
            s = fsm.advance(s, t)
            assert s >= 0


def test_guided_parity_across_decode_paths():
    ref = _gen(_engine()).output_token_ids
    burst = _gen(_engine(decode_steps=4)).output_token_ids
    deferred = _gen(_engine(decode_steps=4,
                            deferred=True)).output_token_ids
    assert burst == ref
    assert deferred == ref


def test_guided_and_free_rows_coexist():
    """A guided row must not constrain (or be corrupted by) a free
    row in the same batch."""
    engine = _engine(decode_steps=4)
    free_ref = engine.generate(PROMPT, SamplingParams(
        max_tokens=12, temperature=0.0,
        ignore_eos=True)).output_token_ids

    engine2 = _engine(decode_steps=4)
    seqs = []
    for kw in (dict(max_tokens=12, temperature=0.0, ignore_eos=True),
               dict(max_tokens=120, temperature=0.8, seed=7,
                    guided="json")):
        sid = engine2.add_request(PROMPT, SamplingParams(**kw))
        seqs.append(engine2.sequences[sid])
    while engine2.has_work():
        engine2.step()
    free, guided = seqs
    assert free.output_token_ids == free_ref
    fsm = engine2.guided_fsm
    s = 0
    for t in guided.output_token_ids:
        s = fsm.advance(s, t)
        assert s >= 0


def test_greedy_guided_deterministic_and_valid():
    a = _gen(_engine(decode_steps=4), temperature=0.0, seed=None)
    b = _gen(_engine(decode_steps=4), temperature=0.0, seed=None)
    assert a.output_token_ids == b.output_token_ids
    fsm_state = 0
    fsm = _engine().guided_fsm
    for t in a.output_token_ids:
        fsm_state = fsm.advance(fsm_state, t)
        assert fsm_state >= 0


def test_automaton_accepts_all_json_dumps_output():
    """Round-trip fuzz: every document the stdlib can produce (random
    nested structures up to the automaton's depth cap, ASCII and raw
    unicode) must walk the automaton byte-for-byte to DONE; one level
    PAST the cap must be rejected (depth-limiting is mask-enforced,
    not a crash)."""
    import numpy as np

    fsm = _engine().guided_fsm
    max_depth = fsm.max_depth
    rng = np.random.RandomState(0)

    def rand_value(depth):
        # Containers allowed right up to the cap: the top-level object
        # is stack depth 1, so depth < max_depth exercises stacks of
        # every legal size including max_depth itself.
        kind = rng.randint(0, 7 if depth < max_depth else 5)
        if kind == 0:
            return rng.randint(-10**9, 10**9)
        if kind == 1:
            return float(rng.randn()) * 10.0 ** rng.randint(-8, 8)
        if kind == 2:
            return bool(rng.randint(2))
        if kind == 3:
            return None
        if kind == 4:
            chars = [chr(rng.randint(32, 127)) for _ in range(
                rng.randint(0, 12))]
            if rng.randint(2):
                chars.append("é€\n\t\"\\")
            return "".join(chars)
        if kind == 5:
            return [rand_value(depth + 1)
                    for _ in range(rng.randint(0, 4))]
        return {f"k{i}": rand_value(depth + 1)
                for i in range(rng.randint(0, 4))}

    deepest_seen = 0
    for ensure_ascii in (True, False):
        for trial in range(60):
            doc = {f"k{i}": rand_value(1)
                   for i in range(rng.randint(0, 5))}
            text = json.dumps(doc, ensure_ascii=ensure_ascii)
            depth = d = 0
            in_str = esc = False
            for ch in text:
                if esc:
                    esc = False
                elif in_str:
                    if ch == "\\":
                        esc = True
                    elif ch == '"':
                        in_str = False
                elif ch == '"':
                    in_str = True
                elif ch in "{[":
                    d += 1
                    depth = max(depth, d)
                elif ch in "}]":
                    d -= 1
            deepest_seen = max(deepest_seen, depth)
            s = 0
            for b in text.encode("utf-8"):
                ns = fsm.advance(s, b)
                assert ns >= 0, (text, chr(b) if b < 128 else b, s)
                s = ns
            assert fsm.mask[s, fsm.eos_token_id], text
    assert deepest_seen == max_depth, (
        f"fuzz never reached the cap (deepest {deepest_seen})")

    # One level PAST the cap: rejected at the opening bracket.
    over = '{"a": ' + "[" * max_depth
    s = 0
    for i, b in enumerate(over.encode()):
        ns = fsm.advance(s, b)
        if ns < 0:
            assert chr(b) == "[" and i == len(over) - 1
            break
        s = ns
    else:
        raise AssertionError("over-depth document was accepted")


def test_server_response_format_parsing():
    from production_stack_tpu.engine.server import _sampling_from_body

    assert _sampling_from_body(
        {"response_format": {"type": "json_object"}}, 256
    ).guided == "json"
    assert _sampling_from_body(
        {"response_format": {"type": "text"}}, 256).guided is None
    assert _sampling_from_body({}, 256).guided is None
    with pytest.raises(ValueError, match="unsupported response_format"):
        _sampling_from_body(
            {"response_format": {"type": "json_schema"}}, 256)
    with pytest.raises(ValueError, match="must be an object"):
        _sampling_from_body({"response_format": "json_object"}, 256)


def test_guided_rejected_without_byte_tokenizer():
    engine = _engine()
    engine.guided_fsm = None  # simulate an HF-tokenizer engine
    with pytest.raises(ValueError, match="byte-range tokenizer"):
        engine.add_request(PROMPT, SamplingParams(guided="json"))
