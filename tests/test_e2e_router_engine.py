"""End-to-end: client -> router -> real TPU engine (tiny model, CPU).

The minimum end-to-end slice of SURVEY.md §7 step 3, as a test: static
discovery, round-robin routing, streaming proxy, engine metrics scrape
path — no Kubernetes, no TPU.
"""

import asyncio
import json

from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.router.app import build_app
from production_stack_tpu.router.routing.logic import (
    initialize_routing_logic,
)
from production_stack_tpu.router.service_discovery import (
    initialize_service_discovery,
)
from production_stack_tpu.router.services.rewriter import (
    initialize_request_rewriter,
)
from production_stack_tpu.router.stats.engine_stats import (
    initialize_engine_stats_scraper,
)
from production_stack_tpu.router.stats.request_stats import (
    initialize_request_stats_monitor,
)
from tests.test_engine_server import make_server


async def _stack(fn):
    engine_server = make_server()
    engine_client = TestClient(TestServer(engine_server.build_app()))
    await engine_client.start_server()
    engine_url = str(engine_client.make_url("")).rstrip("/")

    initialize_service_discovery(
        "static", urls=[engine_url], models=["tiny-llama"]
    )
    initialize_request_stats_monitor(60.0)
    initialize_engine_stats_scraper(3600.0)
    initialize_routing_logic("roundrobin")
    initialize_request_rewriter("noop")

    router_app = build_app()
    router_app["enable_batch_api"] = False
    from production_stack_tpu.router.services.files import (
        initialize_storage,
    )
    import tempfile
    router_app["file_storage"] = initialize_storage(
        "local_file", tempfile.mkdtemp()
    )
    router_client = TestClient(TestServer(router_app))
    await router_client.start_server()
    try:
        await fn(router_client)
    finally:
        await router_client.close()
        await engine_client.close()


def test_chat_completion_through_router():
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 6, "temperature": 0, "ignore_eos": True,
        })
        assert resp.status == 200
        data = await resp.json()
        assert data["object"] == "chat.completion"
        assert data["usage"]["completion_tokens"] == 6
    asyncio.run(_stack(run))


def test_streaming_through_router():
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 4, "temperature": 0, "ignore_eos": True,
            "stream": True,
        })
        assert resp.status == 200
        body = await resp.text()
        assert body.strip().endswith("data: [DONE]")
    asyncio.run(_stack(run))


def test_guided_json_through_router():
    """response_format json_object rides the router's pass-through
    proxy to the engine: a random-weight model answers with
    structurally valid JSON through the full stack (or a valid prefix
    when max_tokens truncates)."""
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "json please"}],
            "max_tokens": 200, "temperature": 0.9, "seed": 2,
            "response_format": {"type": "json_object"},
        })
        assert resp.status == 200
        data = await resp.json()

        # Invalid response_format 400s through the proxy (checked
        # FIRST so no validation branch below can skip it).
        bad = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "x"}],
            "response_format": {"type": "json_schema"},
        })
        assert bad.status == 400

        text = data["choices"][0]["message"]["content"]
        if data["choices"][0]["finish_reason"] == "stop":
            assert isinstance(json.loads(text), dict)
        else:
            # Truncated mid-document: must still be a valid JSON
            # prefix byte-for-byte (same automaton the engine built,
            # via the same helper).
            from production_stack_tpu.engine.guided import (
                build_json_fsm,
            )
            from production_stack_tpu.engine.tokenizer import (
                ByteTokenizer,
            )
            fsm = build_json_fsm(ByteTokenizer())
            s = 0
            for b in text.encode("utf-8", "surrogatepass"):
                ns = fsm.advance(s, b)
                if ns < 0:
                    # Replacement chars from the lossy decode step
                    # can corrupt raw bytes; fall back to the string
                    # being non-trivially JSON-shaped.
                    assert text.lstrip()[:1] == "{"
                    break
                s = ns
    asyncio.run(_stack(run))


def test_models_aggregation_through_router():
    async def run(client):
        resp = await client.get("/v1/models")
        data = await resp.json()
        assert [m["id"] for m in data["data"]] == ["tiny-llama"]
    asyncio.run(_stack(run))


def test_router_metrics_after_traffic():
    async def run(client):
        await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 2, "temperature": 0, "ignore_eos": True,
        })
        resp = await client.get("/metrics")
        text = await resp.text()
        assert "vllm:current_qps" in text
    asyncio.run(_stack(run))
