"""End-to-end speculative decoding: greedy parity with the
non-speculative engine (byte-identical outputs), hybrid composition
with decode bursts, executable-cache stability, and KV-page
accounting when sequences end mid-speculation."""

import numpy as np

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams


def _engine(spec_k, decode_steps=1, **sched_kw):
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4,
                                  max_model_len=256,
                                  prefill_chunk_size=32,
                                  decode_steps=decode_steps,
                                  speculative_k=spec_k,
                                  **sched_kw),
    )
    return LLMEngine(config)


def _gen(engine, prompts, **kw):
    sampling = dict(max_tokens=16, temperature=0.0, ignore_eos=True)
    sampling.update(kw)
    seqs = []
    for p in prompts:
        sid = engine.add_request(p, SamplingParams(**sampling))
        seqs.append(engine.sequences[sid])
    while engine.has_work():
        engine.step()
    return [s.output_token_ids for s in seqs]


def _drafted(engine):
    return engine.stats()["spec_decode_num_draft_tokens_total"]


# Prompt mix: repetitive histories (the drafting case — includes one
# longer than prefill_chunk_size so speculation follows a chunked
# prefill) plus a random prompt (drafts rarely; exercises the
# mixed-batch fallback rows).
def _prompt_mix():
    rs = np.random.RandomState(7)
    return [
        [5, 6, 7] * 12,
        [9, 9, 9, 9, 9, 9, 9, 9],
        [11, 12, 13, 14] * 20,  # 80 tokens > chunk 32
        [int(x) for x in rs.randint(1, 500, size=23)],
    ]


def test_greedy_parity_byte_identical():
    prompts = _prompt_mix()
    expected = _gen(_engine(spec_k=0), prompts)
    spec = _engine(spec_k=4)
    got = _gen(spec, prompts)
    assert got == expected
    assert all(len(t) == 16 for t in got)
    assert _drafted(spec) > 0


def test_greedy_parity_hybrid_with_decode_bursts():
    """speculative_k composes with decode_steps>1: steps with drafts
    verify, draft-less steps burst — outputs stay byte-identical."""
    prompts = _prompt_mix()
    expected = _gen(_engine(spec_k=0, decode_steps=1), prompts)
    hybrid = _engine(spec_k=4, decode_steps=4)
    got = _gen(hybrid, prompts)
    assert got == expected


def test_hybrid_profitability_gate_still_drafts_when_worthwhile():
    """A solo looping sequence drafts full-k, so the spec step beats
    the 4-token burst it displaces and must actually be taken."""
    engine = _engine(spec_k=6, decode_steps=4)
    _gen(engine, [[5, 6, 7] * 12], max_tokens=24)
    assert _drafted(engine) > 0


def test_spec_respects_max_tokens_and_stop_tokens():
    """Budgets and stop tokens must behave identically when the
    stopping token arrives inside an accepted draft run (the emitted
    tail past the stop is discarded)."""
    prompt = [5, 6, 7] * 12
    ref = _gen(_engine(spec_k=0), [prompt], max_tokens=20)[0]

    got = _gen(_engine(spec_k=4), [prompt], max_tokens=13)[0]
    assert got == ref[:13]

    stop = ref[9]
    kw = dict(max_tokens=20, ignore_eos=False, stop_token_ids=[stop])
    base = _gen(_engine(spec_k=0), [prompt], **kw)[0]
    spec = _gen(_engine(spec_k=4), [prompt], **kw)[0]
    assert spec == base
    assert spec[-1] == stop


def test_stochastic_rows_fall_back_and_finish():
    """Seeded stochastic rows are spec-ineligible (the whole step
    falls back) but must still complete alongside greedy rows, and
    the greedy row must keep parity."""
    prompts = _prompt_mix()[:2]
    solo = _gen(_engine(spec_k=0), [prompts[0]])[0]
    engine = _engine(spec_k=4)
    sids = [
        engine.add_request(prompts[0], SamplingParams(
            max_tokens=16, temperature=0.0, ignore_eos=True)),
        engine.add_request(prompts[1], SamplingParams(
            max_tokens=16, temperature=0.9, seed=42,
            ignore_eos=True)),
    ]
    seqs = [engine.sequences[s] for s in sids]
    while engine.has_work():
        engine.step()
    assert seqs[0].output_token_ids == solo
    assert len(seqs[1].output_token_ids) == 16


def test_no_recompilation_across_mixed_run():
    """A long mixed prefill/decode/speculative run must not grow the
    executable caches: decode + verify each compile ONE fixed shape
    (plus prefill's pow-2 chunk buckets), and further steps reuse
    them."""
    engine = _engine(spec_k=4, decode_steps=4)
    steps = {"n": 0}
    orig_step = engine.step

    def counting_step():
        steps["n"] += 1
        return orig_step()

    engine.step = counting_step

    _gen(engine, _prompt_mix(), max_tokens=24)
    step_sizes = engine.runner._step_jit._cache_size()
    spec_sizes = engine.runner._spec_jit._cache_size()
    assert _drafted(engine) > 0

    # Further waves, same shape mix, until the run passes 50 steps —
    # the caches must never grow past the first wave's.
    while steps["n"] < 50:
        _gen(engine, _prompt_mix()[::-1], max_tokens=24)
        assert engine.runner._step_jit._cache_size() == step_sizes
        assert engine.runner._spec_jit._cache_size() == spec_sizes
    assert steps["n"] >= 50
    assert spec_sizes == 1


def test_kv_pages_released_after_finish_mid_speculation():
    """A sequence ending inside a speculative step (max_tokens hit on
    an accepted draft) must release every page it held and leave
    hashed pages reusable: a second identical prompt prefix-hits and
    reproduces the output exactly."""
    engine = _engine(spec_k=4)
    cm = engine.cache_manager
    assert cm.num_used_pages == 0

    prompt = [5, 6, 7] * 12
    first = _gen(engine, [prompt], max_tokens=13)[0]
    assert _drafted(engine) > 0
    assert cm.num_used_pages == 0, "pages leaked by mid-spec finish"

    hits_before = cm.prefix_hit_tokens
    second = _gen(engine, [prompt], max_tokens=13)[0]
    assert second == first
    assert cm.prefix_hit_tokens > hits_before
    assert cm.num_used_pages == 0
