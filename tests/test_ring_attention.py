"""Ring attention / context parallelism vs. dense single-device ground
truth, on the virtual 8-device CPU mesh (conftest.py).

Mirrors the reference's test style of checking a distributed mechanism
against a minimal local model (reference src/tests/test_session_router.py
pattern: exact behavior vs. stub ground truth), applied to our sp axis —
a capability the reference does not have at all (SURVEY.md §2.6).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from production_stack_tpu.engine.config import tiny_model_config
from production_stack_tpu.models import llama
from production_stack_tpu.ops.ring_attention import ring_attention_sharded
from production_stack_tpu.parallel.context import context_parallel_forward


def _dense_causal_attention(q, k, v):
    """[B, T, Hq, D] x [B, T, Hkv, D] ground truth in fp64-ish fp32."""
    b, t, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    qg = q.astype(jnp.float32).reshape(b, t, hkv, g, d)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg,
                        k.astype(jnp.float32)) / np.sqrt(d)
    mask = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(mask[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, t, hq, d)


def _mesh(shape, names):
    devs = np.asarray(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


@pytest.mark.parametrize("sp", [2, 4, 8])
@pytest.mark.parametrize("gqa", [1, 2])
def test_ring_attention_matches_dense(sp, gqa):
    b, t, hkv, d = 2, 32, 2, 8
    hq = hkv * gqa
    key = jax.random.PRNGKey(0)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, hq, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(kv_, (b, t, hkv, d), jnp.float32)

    mesh = _mesh((sp,), ("sp",))
    out = ring_attention_sharded(q, k, v, mesh)
    ref = _dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_non_causal():
    b, t, h, d = 1, 16, 2, 8
    key = jax.random.PRNGKey(1)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, h, d), jnp.float32)
    v = jax.random.normal(kv_, (b, t, h, d), jnp.float32)

    mesh = _mesh((4,), ("sp",))
    out = ring_attention_sharded(q, k, v, mesh, causal=False)

    qg = q.astype(jnp.float32)
    scores = jnp.einsum("bthd,bshd->bhts", qg, k) / np.sqrt(d)
    probs = jax.nn.softmax(scores, axis=-1)
    ref = jnp.einsum("bhts,bshd->bthd", probs, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("mesh_shape,names", [
    ((8,), ("sp",)),
    ((2, 4), ("dp", "sp")),
])
def test_context_parallel_forward_matches_dense(mesh_shape, names):
    config = tiny_model_config("llama")
    params = llama.init_params(config, jax.random.PRNGKey(0))
    b, t = 2, 64
    tokens = jax.random.randint(jax.random.PRNGKey(2), (b, t), 0,
                                config.vocab_size, jnp.int32)

    mesh = _mesh(mesh_shape, names)
    logits = context_parallel_forward(params, config, tokens, mesh)
    ref = llama.forward_train(params, config, tokens)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=5e-4, atol=5e-4)


def test_context_parallel_grads_flow():
    """The sp-sharded forward is differentiable end to end (training)."""
    config = tiny_model_config("llama")
    params = llama.init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 32), 0,
                                config.vocab_size, jnp.int32)
    mesh = _mesh((4,), ("sp",))

    def loss(p):
        logits = context_parallel_forward(p, config, tokens, mesh)
        return jnp.mean(logits ** 2)

    grads = jax.grad(loss)(params)
    gnorm = sum(float(jnp.sum(jnp.abs(g))) for g in grads.values())
    assert np.isfinite(gnorm) and gnorm > 0
