"""Weight-only int8 quantization: numerics bounds, generation sanity,
TP sharding of (weight, scale) pairs, LoRA composition."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.quantization import (
    dequant_matmul,
    quantize_params,
    quantize_weight,
)
from production_stack_tpu.engine.sequence import SamplingParams
from production_stack_tpu.models import llama


def test_quantize_roundtrip_error_bound():
    rs = np.random.RandomState(0)
    w = jnp.asarray(rs.randn(2, 64, 96).astype(np.float32))
    q, scale = quantize_weight(w)
    assert q.dtype == jnp.int8
    assert scale.shape == (2, 96)
    deq = q.astype(jnp.float32) * scale[:, None, :]
    # Per-channel symmetric int8: error <= scale/2 per element.
    err = np.abs(np.asarray(deq - w))
    bound = np.asarray(scale)[:, None, :] * 0.5 + 1e-6
    assert np.all(err <= bound)


def test_dequant_matmul_close_to_dense():
    rs = np.random.RandomState(1)
    w = jnp.asarray(rs.randn(64, 96).astype(np.float32))
    x = jnp.asarray(rs.randn(4, 8, 64).astype(np.float32))
    q, scale = quantize_weight(w[None])
    got = dequant_matmul(x, (q[0], scale[0]))
    ref = x @ w
    rel = (np.abs(np.asarray(got - ref)).max()
           / np.abs(np.asarray(ref)).max())
    assert rel < 0.02


def _engine(quant, mesh=None, params=None):
    model = tiny_model_config("llama")
    model.quantization = quant
    config = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32),
    )
    return LLMEngine(config, mesh=mesh, params=params)


def test_quantized_generation_tracks_full_precision():
    """Quantizing a given full-precision checkpoint (the real serving
    path — random int8 init draws its own weights by design, see
    quantization.init_random_quantized)."""
    prompt = list(range(3, 40))
    sp = dict(max_tokens=8, temperature=0.0, ignore_eos=True)
    params = llama.init_params(tiny_model_config("llama"),
                               jax.random.PRNGKey(0))
    full = _engine("none", params=params).generate(
        prompt, SamplingParams(**sp)).output_token_ids
    quant = _engine("int8", params=params).generate(
        prompt, SamplingParams(**sp)).output_token_ids
    assert len(quant) == 8
    # Random tiny weights amplify quantization noise; require the
    # greedy paths to agree on a prefix rather than every token.
    assert quant[0] == full[0]


def test_quantized_tp_sharding():
    from production_stack_tpu.parallel.mesh import build_mesh
    mesh = build_mesh(tensor_parallel_size=2)
    engine = _engine("int8", mesh=mesh)
    seq = engine.generate(
        list(range(5, 25)),
        SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True))
    assert len(seq.output_token_ids) == 4
    w, scale = engine.runner.params["wq"]
    assert w.dtype == jnp.int8


def test_quantization_rejects_mixtral():
    config = tiny_model_config("llama")
    config.architecture = "mixtral"
    params = {"wq": jnp.zeros((2, 8, 8))}
    with pytest.raises(NotImplementedError):
        quantize_params(params, config)


def test_quantized_params_reject_embedder():
    from production_stack_tpu.engine.embeddings import Embedder
    engine = _engine("int8")
    with pytest.raises(NotImplementedError, match="unquantized"):
        Embedder(engine.config.model, engine.runner.params,
                 max_len=128)


@pytest.mark.parametrize("family", ["llama", "gpt2"])
def test_direct_int8_random_init_shapes(family):
    """Random int8 init (quantization.init_random_quantized) produces
    the same pytree structure as quantize(init) without ever
    materializing the full-precision model (the 8B-on-16GB OOM fix,
    results/round5_notes.md). gpt2 exercises the bias/norm-bias
    leaves (semantics derived from the family init, not names)."""
    from production_stack_tpu.engine.quantization import (
        init_random_quantized,
        is_quantized,
    )
    from production_stack_tpu.models import gpt2 as gpt2_mod

    init_fns = {"llama": llama.init_params,
                "gpt2": gpt2_mod.init_params}
    model = tiny_model_config(family)
    init_fn = init_fns[family]
    ref = quantize_params(init_fn(model, jax.random.PRNGKey(0)), model)
    direct = init_random_quantized(init_fn, model, seed=0)
    assert set(direct) == set(ref)
    for name, leaf in ref.items():
        if is_quantized(leaf):
            assert is_quantized(direct[name])
            assert direct[name][0].shape == leaf[0].shape
            assert direct[name][0].dtype == jnp.int8
            assert direct[name][1].shape == leaf[1].shape
        else:
            assert direct[name].shape == leaf.shape
            assert direct[name].dtype == leaf.dtype
    # Norm gains must be ones (zeros would zero every activation);
    # biases must be zeros — exactly as the family init defines them.
    for name, leaf in ref.items():
        if is_quantized(leaf):
            continue
        a = np.asarray(leaf, np.float32)
        if np.all(a == 1.0):
            np.testing.assert_array_equal(
                np.asarray(direct[name], np.float32), 1.0)
        elif np.all(a == 0.0):
            np.testing.assert_array_equal(
                np.asarray(direct[name], np.float32), 0.0)
