"""Deployment-layer config sanity (no helm binary in this environment;
values files are validated against the chart's JSON schema and the
engine/router flags they render are cross-checked against the real
argument parsers)."""

import json
import os

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(relpath):
    with open(os.path.join(REPO, relpath)) as f:
        return yaml.safe_load(f)


@pytest.fixture(scope="module")
def schema():
    with open(os.path.join(REPO, "helm/values.schema.json")) as f:
        return json.load(f)


@pytest.mark.parametrize("values_file", [
    "helm/values.yaml",
    "tutorials/assets/values-01-minimal-example.yaml",
    "tutorials/assets/values-02-two-pods-session.yaml",
    "tutorials/assets/values-03-pvc-prefetch.yaml",
    "tutorials/assets/values-06-remote-shared-kv.yaml",
    "tutorials/assets/values-08-lora.yaml",
])
def test_values_match_schema(values_file, schema):
    import jsonschema
    jsonschema.validate(_load(values_file), schema)


def test_engine_flags_in_chart_exist():
    """Every --flag the engine template renders must be a real
    tpu-engine flag."""
    from production_stack_tpu.engine.server import parse_args
    with open(os.path.join(
            REPO, "helm/templates/deployment-engine.yaml")) as f:
        text = f.read()
    import re
    flags = set(re.findall(r'"(--[a-z0-9-]+)"', text))
    parser_flags = set()
    # Probe the parser's registered options.
    import argparse
    parser = argparse.ArgumentParser()
    try:
        parse_args(["--help"])
    except SystemExit:
        pass
    from production_stack_tpu.engine import server as srv
    p = srv.parse_args([])  # defaults
    known = {f"--{k.replace('_', '-')}" for k in vars(p)}
    unknown = flags - known
    assert not unknown, f"chart renders unknown engine flags: {unknown}"


def test_router_flags_in_chart_exist():
    """Router-container flags must be real tpu-router flags; the
    benchmark sidecar's flags must be real multi_round_qa flags."""
    import re
    from production_stack_tpu.router.parser import parse_args
    with open(os.path.join(
            REPO, "helm/templates/deployment-router.yaml")) as f:
        text = f.read()
    router_text, _, sidecar_text = text.partition("- name: benchmark")
    flags = set(re.findall(r'"(--[a-z0-9-]+)"', router_text))
    p = parse_args(["--static-backends", "http://x:1"])
    known = {f"--{k.replace('_', '-')}" for k in vars(p)}
    unknown = flags - known
    assert not unknown, f"chart renders unknown router flags: {unknown}"

    sys_path = os.path.join(REPO)
    import sys
    sys.path.insert(0, sys_path)
    try:
        import benchmarks.multi_round_qa  # noqa: F401
    finally:
        sys.path.remove(sys_path)
    bench_src = open(os.path.join(
        REPO, "benchmarks/multi_round_qa.py")).read()
    bench_known = set(re.findall(r'add_argument\("(--[a-z0-9-]+)"',
                                 bench_src))
    sidecar_flags = set(re.findall(r'"(--[a-z0-9-]+)"', sidecar_text))
    unknown = sidecar_flags - bench_known
    assert not unknown, f"sidecar renders unknown bench flags: {unknown}"


def test_routing_logic_enum_consistency():
    """values.schema.json routing enum == router's actual choices."""
    with open(os.path.join(REPO, "helm/values.schema.json")) as f:
        schema = json.load(f)
    enum = set(
        schema["properties"]["routerSpec"]["properties"]
        ["routingLogic"]["enum"]
    )
    from production_stack_tpu.router.routing.logic import RoutingLogic
    assert enum == {v.value for v in RoutingLogic}


def test_dashboard_metrics_exist():
    """Every metric the Grafana dashboard queries is exported by the
    router metrics service or the engine."""
    with open(os.path.join(
            REPO, "observability/tpu-stack-dashboard.json")) as f:
        dashboard = json.load(f)
    import re
    queried = set()
    for p in dashboard["panels"]:
        for t in p.get("targets", []):
            queried.update(re.findall(r"vllm:[a-z0-9_]+", t["expr"]))
    from production_stack_tpu.router.services import metrics_service
    from prometheus_client import Gauge
    exported = {
        f"vllm:{g._name.split(':', 1)[1]}" if ":" in g._name else g._name
        for g in vars(metrics_service).values()
        if isinstance(g, Gauge)
    }
    # Engine-side series: gauges the engine server exports directly,
    # plus every name EngineMetrics.render() emits (histograms expand
    # to _bucket/_sum/_count in Prometheus).
    engine_metrics = {
        "vllm:num_requests_running", "vllm:num_requests_waiting",
        "vllm:gpu_cache_usage_perc", "vllm:gpu_prefix_cache_hit_rate",
        "vllm:num_preemptions_total",
        # QoS labeled counters rendered by engine/server.py /metrics
        # (and the router's aggregated re-export) rather than by
        # EngineMetrics or a prometheus_client Gauge (docs/qos.md).
        "vllm:preempt_offload_total", "vllm:qos_shed_total",
        # Self-tuning decision counter (docs/autotuning.md): labeled
        # per controller, rendered by engine/server.py /metrics and
        # scraped by cluster Prometheus directly (engine-local; the
        # router re-exports only the autotune gauges).
        "vllm:autotune_decisions_total",
    }
    from production_stack_tpu.engine.metrics import EngineMetrics
    for line in EngineMetrics().render():
        for name in re.findall(r"vllm:[a-z0-9_]+", line):
            engine_metrics.add(name)
            if line.startswith("# TYPE") and "histogram" in line:
                engine_metrics.update(
                    {f"{name}_bucket", f"{name}_sum", f"{name}_count"})
    missing = queried - exported - engine_metrics
    assert not missing, f"dashboard queries unexported metrics: {missing}"


def test_dashboard_json_matches_generator():
    """The committed Grafana dashboard must be exactly what
    observability/gen_dashboard.py emits — edits belong in the
    generator, not the JSON."""
    import importlib.util
    import json
    import pathlib

    root = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "gen_dashboard", root / "observability" / "gen_dashboard.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    committed = json.loads(
        (root / "observability" / "tpu-stack-dashboard.json")
        .read_text())
    assert mod.build() == committed
