"""SLO-driven fleet manager (docs/fleet.md).

Covers the whole subsystem: spec parse/validation, the target-tracking
autoscaler (hysteresis dead-band, per-direction cooldowns, independent
pools), router-metrics signal extraction, the engine server's drain
surface (503+Retry-After, in-flight counting), the fake engine's
mirror of it, drain-aware routing (health prober pulls a draining
endpoint out of rotation while its stream finishes), the reconciler
over real fake-engine subprocesses, and the acceptance E2E: a pool
scales 1 -> 2 on an SLO breach and 2 -> 1 on recovery with the drained
replica finishing its in-flight stream byte-identically and zero
requests dropped or 5xx'd across both transitions.

Fast lane: fake engines only — no LLMEngine is ever built.
"""

import asyncio
import json
import socket
import sys
import time
from types import SimpleNamespace

import aiohttp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.fleet.autoscaler import (
    PoolAutoscaler,
    PoolSignals,
    signals_from_router_metrics,
)
from production_stack_tpu.fleet.manager import (
    DRAINING,
    LIVE,
    FleetManager,
)
from production_stack_tpu.fleet.spec import (
    AutoscalerSpec,
    FleetSpec,
    PoolSpec,
)
from production_stack_tpu.router.resilience import (
    ResilienceConfig,
    initialize_resilience,
)
from production_stack_tpu.router.service_discovery import (
    EndpointInfo,
    initialize_service_discovery,
)
from production_stack_tpu.router.services import request_service
from production_stack_tpu.router.services.rewriter import (
    initialize_request_rewriter,
)
from production_stack_tpu.router.stats.engine_stats import (
    initialize_engine_stats_scraper,
)
from production_stack_tpu.router.stats.request_stats import (
    initialize_request_stats_monitor,
)
from production_stack_tpu.testing.fake_engine import build_fake_engine


# ---- shared helpers -------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _chat_body(model="m1", stream=False, max_tokens=3):
    return {
        "model": model,
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": max_tokens,
        "stream": stream,
    }


def _sse_contents(text: str):
    """Delta contents of an SSE chat stream, in order."""
    contents = []
    for line in text.splitlines():
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        delta = json.loads(line[len("data: "):])["choices"][0]["delta"]
        if delta.get("content"):
            contents.append(delta["content"])
    return contents


def _fake_pool_command(speed: float = 500.0):
    """Argv template running a fake engine instead of a real one."""
    return [sys.executable, "-m",
            "production_stack_tpu.testing.fake_engine",
            "--host", "127.0.0.1", "--port", "{port}",
            "--model", "{model}", "--role", "{role}",
            "--speed", str(speed), "--ttft", "0.0"]


async def _settle(mgr: FleetManager, pool: str, want_live: int,
                  deadline_s: float = 20.0):
    """Reconcile until the pool has exactly want_live LIVE replicas
    and nothing mid-transition."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        await mgr.reconcile_once()
        replicas = mgr.replicas[pool]
        live = [r for r in replicas if r.state == LIVE]
        if len(live) == want_live and len(replicas) == want_live:
            return live
        await asyncio.sleep(0.05)
    states = [(r.port, r.state) for r in mgr.replicas[pool]]
    raise AssertionError(
        f"pool {pool} did not settle at {want_live} live: {states}")


# ---- spec parse + validation ----------------------------------------------

def test_fleet_spec_parses_full_example():
    spec = FleetSpec.from_json(json.dumps({
        "port_start": 9000, "port_end": 9009,
        "router_url": "http://127.0.0.1:8080",
        "router_config_path": "/tmp/dyn.json",
        "routing_logic": "llq",
        "drain_timeout_s": 30.0,
        "pools": [
            {"name": "prefill", "role": "prefill", "min_replicas": 1,
             "max_replicas": 4, "model": "tiny-llama",
             "engine_flags": ["--max-num-seqs", "16"],
             "autoscaler": {"target_ttft_p99_s": 2.0,
                            "target_waiting_per_replica": 4.0}},
            {"name": "decode", "role": "decode", "max_replicas": 6,
             "autoscaler": {"target_itl_p99_s": 0.1,
                            "target_cache_usage": 0.85,
                            "target_awaiting_kv": 8.0,
                            "tolerance": 0.2}},
        ],
    }))
    assert [p.name for p in spec.pools] == ["prefill", "decode"]
    assert spec.pools[0].engine_flags == ["--max-num-seqs", "16"]
    assert spec.pools[0].autoscaler.target_ttft_p99_s == 2.0
    assert spec.pools[1].autoscaler.tolerance == 0.2
    assert spec.routing_logic == "llq"
    assert spec.drain_timeout_s == 30.0


def test_fleet_spec_rejects_bad_shapes():
    ok = {"name": "p", "max_replicas": 2}
    with pytest.raises(ValueError, match="at least one pool"):
        FleetSpec(pools=[])
    with pytest.raises(ValueError, match="duplicate pool names"):
        FleetSpec.from_dict({"pools": [ok, ok]})
    with pytest.raises(ValueError, match="port range holds"):
        FleetSpec.from_dict({"pools": [{"name": "p", "max_replicas": 4}],
                             "port_start": 9000, "port_end": 9001})
    with pytest.raises(ValueError, match="role"):
        PoolSpec(name="p", role="compute")
    with pytest.raises(ValueError, match="pool name"):
        PoolSpec(name="Bad_Name")
    with pytest.raises(ValueError, match="max_replicas"):
        PoolSpec(name="p", min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="tolerance"):
        AutoscalerSpec(tolerance=1.5)
    with pytest.raises(ValueError, match="target_ttft_p99_s"):
        AutoscalerSpec(target_ttft_p99_s=-1.0)


# ---- autoscaler -----------------------------------------------------------

def _pool(name="decode", lo=1, hi=8, **autoscaler):
    return PoolSpec(name=name, min_replicas=lo, max_replicas=hi,
                    autoscaler=AutoscalerSpec(**autoscaler))


def test_autoscaler_target_tracking_up_and_down():
    t = [0.0]
    asc = PoolAutoscaler(
        _pool(target_waiting_per_replica=4.0, scale_up_cooldown_s=15.0,
              scale_down_cooldown_s=60.0),
        clock=lambda: t[0])
    # 30 waiting across 1 replica, target 4/replica -> ratio 7.5.
    assert asc.desired(1, PoolSignals(waiting=30.0)) == 8  # ceil, clamped
    t[0] += 16.0
    # Load vanished, but scale-down waits out the post-scale-up window.
    assert asc.desired(8, PoolSignals(waiting=0.0)) == 8
    t[0] += 60.0
    assert asc.desired(8, PoolSignals(waiting=0.0)) == 1


def test_autoscaler_deadband_and_cooldowns():
    t = [0.0]
    asc = PoolAutoscaler(
        _pool(target_waiting_per_replica=4.0, tolerance=0.25,
              scale_up_cooldown_s=15.0, scale_down_cooldown_s=60.0),
        clock=lambda: t[0])
    # Within +-tolerance of target: never scales.
    assert asc.desired(2, PoolSignals(waiting=9.0)) == 2   # ratio 1.125
    assert asc.desired(2, PoolSignals(waiting=7.0)) == 2   # ratio 0.875
    # Breach scales up and starts the up-cooldown...
    assert asc.desired(2, PoolSignals(waiting=16.0)) == 4
    # ...which blocks an immediate second expansion.
    assert asc.desired(4, PoolSignals(waiting=40.0)) == 4
    t[0] += 15.0
    # Ratio 2.5 wants 10 but the pool caps at max_replicas.
    assert asc.desired(4, PoolSignals(waiting=40.0)) == 8


def test_autoscaler_no_signals_and_disabled_clamp_only():
    asc = PoolAutoscaler(_pool(lo=2, hi=4, target_waiting_per_replica=4.0))
    assert asc.desired(1, None) == 2          # clamped up to min
    assert asc.desired(7, None) == 4          # clamped down to max
    assert asc.desired(3, PoolSignals()) == 3  # no observations yet
    off = PoolAutoscaler(_pool(enable=False, target_waiting_per_replica=4.0))
    assert off.desired(3, PoolSignals(waiting=100.0)) == 3


def test_autoscaler_worst_ratio_wins_and_pools_independent():
    t = [100.0]
    prefill = PoolAutoscaler(
        _pool(name="prefill", target_ttft_p99_s=1.0,
              scale_up_cooldown_s=0.0),
        clock=lambda: t[0])
    decode = PoolAutoscaler(
        _pool(name="decode", target_itl_p99_s=0.1,
              target_cache_usage=0.8, scale_up_cooldown_s=0.0),
        clock=lambda: t[0])
    # Decode's worst signal (cache 3x target) drives it; prefill's TTFT
    # is on target and holds still — the disagg point of the design.
    assert prefill.desired(2, PoolSignals(ttft_p99_s=1.0)) == 2
    sig = PoolSignals(itl_p99_s=0.05, cache_usage=2.4)
    assert decode.desired(2, sig) == 6


def test_signals_from_router_metrics_grouping():
    text = "\n".join([
        '# HELP vllm:ttft_p99_seconds p99 ttft',
        'vllm:ttft_p99_seconds{server="http://a:1"} 0.5',
        'vllm:ttft_p99_seconds{server="http://b:2"} 2.5',
        'vllm:num_requests_waiting{server="http://a:1"} 6.0',
        'vllm:num_requests_waiting{server="http://b:2"} 10.0',
        'vllm:num_requests_waiting{server="http://other:9"} 99.0',
        'vllm:engine_gpu_cache_usage_perc{server="http://c:3"} 0.9',
        'vllm:itl_p99_seconds{server="http://c:3"} -1.0',
        'not a metric line',
    ])
    out = signals_from_router_metrics(text, {
        "http://a:1": "decode", "http://b:2": "decode",
        "http://c:3": "prefill"})
    assert out["decode"].waiting == 16.0           # summed
    assert out["decode"].ttft_p99_s == 2.5         # worst replica
    assert out["prefill"].cache_usage == 0.9
    assert out["prefill"].itl_p99_s == -1.0        # -1 sample ignored
    assert out["prefill"].waiting == -1.0          # unowned server ignored


def test_slo_burn_rate_is_a_fleet_wide_signal():
    text = "\n".join([
        'vllm:slo_burn_rate{window="5m"} 2.5',
        'vllm:slo_burn_rate{window="1h"} 9.0',
        'vllm:num_requests_waiting{server="http://a:1"} 6.0',
    ])
    out = signals_from_router_metrics(text, {
        "http://a:1": "decode", "http://b:2": "prefill"})
    # No server label: every pool sees the 5m value; the 1h window is
    # for paging, never capacity.
    assert out["decode"].slo_burn_rate == 2.5
    assert out["prefill"].slo_burn_rate == 2.5

    # Burn over target scales the pool up like any other signal.
    asc = PoolAutoscaler(_pool(target_slo_burn_rate=1.0,
                               scale_up_cooldown_s=0.0))
    assert asc.desired(2, out["decode"]) == 5          # ratio 2.5
    # Disabled (0) target ignores the signal entirely.
    off = PoolAutoscaler(_pool(target_waiting_per_replica=4.0))
    assert off.desired(2, PoolSignals(slo_burn_rate=50.0,
                                      waiting=8.0)) == 2


# ---- engine server drain surface (stub engine; no LLMEngine build) --------

class _StubEngine:
    """Just enough engine for EngineServer's drain/health surface."""

    tokenizer = None

    def __init__(self, role="both"):
        self.config = SimpleNamespace(engine_role=role)

    def stats(self):
        return {"num_requests_running": 0, "num_requests_waiting": 0}

    def has_work(self):
        return False


def test_engine_server_drain_rejects_and_counts():
    from production_stack_tpu.engine.server import EngineServer

    async def run():
        server = EngineServer(_StubEngine(role="decode"), "m1")
        assert server._drain_rejection() is None

        seen = []

        async def handler(request):
            seen.append(server._active_generations)
            return "ok"

        guarded = server._guarded(handler)
        assert await guarded(None) == "ok"
        assert seen == [1]                      # counted while in flight
        assert server._active_generations == 0  # and released after

        resp = await server.drain(SimpleNamespace(can_read_body=False))
        payload = json.loads(resp.body)
        assert payload["status"] == "draining"
        assert server.draining

        rejected = await guarded(None)
        assert rejected.status == 503
        assert rejected.headers["Retry-After"] == "1"
        assert seen == [1]  # the draining handler was never entered

        health = json.loads((await server.health(None)).body)
        assert health["draining"] is True
        assert health["role"] == "decode"
        assert health["active_requests"] == 0

    asyncio.run(run())


# ---- fake engine drain (in-process; never {"exit": true} here) ------------

async def test_fake_engine_drain_finishes_inflight_stream():
    client = TestClient(TestServer(
        build_fake_engine(model="m1", speed=100.0, ttft=0.0)))
    await client.start_server()
    try:
        n = 100  # 1s of stream at speed=100: in flight across the drain
        resp = await client.request(
            "POST", "/v1/chat/completions",
            json=_chat_body(stream=True, max_tokens=n))
        assert resp.status == 200

        drained = await (await client.post("/drain", json={})).json()
        assert drained["status"] == "draining"

        rejected = await client.post("/v1/chat/completions",
                                     json=_chat_body())
        assert rejected.status == 503
        assert rejected.headers["Retry-After"] == "1"

        health = await (await client.get("/health")).json()
        assert health["draining"] is True

        # The admitted stream still finishes byte-identically.
        assert _sse_contents(await resp.text()) == \
            [f"tok{i} " for i in range(n)]

        # Gauge injection drives the autoscaler's scrape signals.
        await client.post("/gauges", json={"waiting": 7,
                                           "cache_usage": 0.25})
        metrics = await (await client.get("/metrics")).text()
        assert "vllm:num_requests_waiting 7.0" in metrics
        assert "vllm:gpu_cache_usage_perc 0.25" in metrics
        assert "vllm:engine_draining 1.0" in metrics
    finally:
        await client.close()


# ---- drain-aware routing (docs/resilience.md belt-and-braces) -------------

async def _start_router(backends, resilience: ResilienceConfig):
    """backends: [(url, model, role)] -> started router TestClient."""
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.routing.logic import (
        initialize_routing_logic,
    )
    request_service.disagg_handoffs_total = 0
    request_service.disagg_fallbacks_total = 0
    initialize_service_discovery(
        "static",
        urls=[b[0] for b in backends],
        models=[b[1] for b in backends],
        roles=[b[2] for b in backends],
    )
    initialize_request_stats_monitor(60.0)
    initialize_engine_stats_scraper(3600.0)
    initialize_routing_logic("roundrobin")
    initialize_request_rewriter("noop")
    initialize_resilience(resilience)
    client = TestClient(TestServer(build_app()))
    await client.start_server()
    return client


async def test_draining_endpoint_leaves_rotation_stream_unbroken():
    """POST /drain on a backend: the health prober sees ``draining``
    and fails it out of ``usable_endpoints`` while its in-flight
    stream (started through the router) completes byte-identically."""
    from production_stack_tpu.router.resilience import get_resilience
    from production_stack_tpu.router.routing.logic import usable_endpoints

    fakes = [TestServer(build_fake_engine(model="m1", speed=100.0,
                                          ttft=0.0)) for _ in range(2)]
    for server in fakes:
        await server.start_server()
    urls = {f"http://127.0.0.1:{s.port}": s for s in fakes}
    router = await _start_router(
        [(url, "m1", "both") for url in urls],
        ResilienceConfig(max_retries=2, backend_connect_timeout=1.0,
                         backend_timeout=10.0,
                         health_check_interval=0.05,
                         health_failure_threshold=1),
    )
    session = aiohttp.ClientSession()
    try:
        # Roundrobin visits sorted URLs: the first request lands on
        # sorted()[0] — that's the replica we'll drain mid-stream.
        target = sorted(urls)[0]
        n = 150
        stream = await router.request(
            "POST", "/v1/chat/completions",
            json=_chat_body(stream=True, max_tokens=n))
        assert stream.status == 200

        async with session.post(target + "/drain", json={}) as resp:
            assert (await resp.json())["status"] == "draining"

        mgr = get_resilience()
        await mgr.health._probe_one(session, target)
        assert not mgr.health.is_healthy(target)

        eps = [EndpointInfo(url=url) for url in urls]
        usable = [ep.url for ep in usable_endpoints(eps)]
        assert usable == [url for url in urls if url != target]

        # New work keeps succeeding on the survivor during the drain.
        for _ in range(3):
            ok = await router.post("/v1/chat/completions",
                                   json=_chat_body())
            assert ok.status == 200

        # And the admitted stream finishes without a lost byte.
        assert _sse_contents(await stream.text()) == \
            [f"tok{i} " for i in range(n)]
    finally:
        await session.close()
        await router.close()
        for server in fakes:
            await server.close()


# ---- reconciler over real subprocesses ------------------------------------

async def test_reconciler_spawns_registers_and_drains(tmp_path):
    config_path = tmp_path / "dyn.json"
    base = _free_port()
    spec = FleetSpec(
        pools=[PoolSpec(name="decode", role="decode", min_replicas=1,
                        max_replicas=3, model="m1",
                        command=_fake_pool_command())],
        port_start=base, port_end=base + 9,
        router_config_path=str(config_path),
        drain_timeout_s=30.0,
    )
    mgr = FleetManager(spec)
    try:
        (replica,) = await _settle(mgr, "decode", 1)
        assert replica.port == base  # lowest port first
        config = json.loads(config_path.read_text())
        assert config["static_backends"] == [replica.url]
        assert config["static_models"] == ["m1"]
        assert config["static_roles"] == ["decode"]

        mgr.desired["decode"] = 2
        live = await _settle(mgr, "decode", 2)
        config = json.loads(config_path.read_text())
        assert sorted(config["static_backends"]) == \
            sorted(r.url for r in live)

        # Scale down: the newest replica drains, self-exits, and its
        # port is returned to the allocator.
        victim = max(live, key=lambda r: r.port)
        mgr.desired["decode"] = 1
        await mgr.reconcile_once()
        assert victim.state == DRAINING
        config = json.loads(config_path.read_text())
        assert config["static_backends"] == \
            [r.url for r in live if r is not victim]

        (survivor,) = await _settle(mgr, "decode", 1)
        assert survivor is not victim
        assert victim.process.poll() is not None
        assert mgr._alloc_port() == victim.port

        await mgr.drain_all()
        assert mgr.replicas["decode"] == []
        assert json.loads(config_path.read_text())["static_backends"] == []
    finally:
        for reps in mgr.replicas.values():
            for r in reps:
                if r.process.poll() is None:
                    r.process.kill()
        await mgr.close()


# ---- acceptance E2E: breach -> 1->2, recovery -> 2->1, zero loss ----------

async def test_fleet_autoscale_e2e_zero_loss(tmp_path):
    """The PR's acceptance invariant end to end: router + dynamic
    config + fleet manager over fake-engine subprocesses. An SLO
    breach (injected queue depth) scales 1 -> 2; recovery scales
    2 -> 1; the drained replica finishes its in-flight stream
    byte-identically; every request routed across both transitions
    answers 200 — zero dropped, zero 5xx."""
    from production_stack_tpu.router.dynamic_config import (
        initialize_dynamic_config_watcher,
    )
    from production_stack_tpu.router.stats.engine_stats import (
        get_engine_stats_scraper,
    )

    config_path = tmp_path / "dyn.json"
    router = await _start_router(
        [], ResilienceConfig(max_retries=2, backend_connect_timeout=1.0,
                             backend_timeout=10.0,
                             health_check_interval=0.0))
    router_url = f"http://127.0.0.1:{router.server.port}"
    base = _free_port()
    spec = FleetSpec(
        pools=[PoolSpec(
            name="decode", role="decode", min_replicas=1, max_replicas=3,
            model="m1", command=_fake_pool_command(speed=500.0),
            autoscaler=AutoscalerSpec(target_waiting_per_replica=4.0,
                                      tolerance=0.1,
                                      scale_up_cooldown_s=0.0,
                                      scale_down_cooldown_s=0.0))],
        port_start=base, port_end=base + 9,
        router_url=router_url,
        router_config_path=str(config_path),
        drain_timeout_s=30.0,
    )
    mgr = FleetManager(spec)
    session = aiohttp.ClientSession()
    statuses = []

    async def route_one(stream=False, max_tokens=3):
        resp = await router.request(
            "POST", "/v1/chat/completions",
            json=_chat_body(stream=stream, max_tokens=max_tokens))
        statuses.append(resp.status)
        return resp

    try:
        (first,) = await _settle(mgr, "decode", 1)
        watcher = initialize_dynamic_config_watcher(str(config_path),
                                                    3600.0)
        watcher.check_and_apply()
        assert (await route_one()).status == 200

        # SLO breach: 8 waiting against a target of 4 per replica.
        async with session.post(first.url + "/gauges",
                                json={"waiting": 8}) as resp:
            assert resp.status == 200
        get_engine_stats_scraper().scrape_once()
        desired = await mgr.autoscale_once()
        assert desired["decode"] == 2

        live = await _settle(mgr, "decode", 2)
        watcher.check_and_apply()
        for _ in range(4):
            await route_one()

        # The fleet gauges ride the router's shared registry.
        exposition = await (await router.get("/metrics")).text()
        assert 'vllm:fleet_desired_replicas{pool="decode"} 2.0' \
            in exposition

        # Recovery: queues empty on both replicas.
        for replica in live:
            async with session.post(replica.url + "/gauges",
                                    json={"waiting": 0}) as resp:
                assert resp.status == 200
        get_engine_stats_scraper().scrape_once()

        # Park a long stream on the replica about to be drained (the
        # newest port is the reconciler's scale-down victim).
        victim = max(live, key=lambda r: r.port)
        survivor = min(live, key=lambda r: r.port)
        n = 400  # 0.8s at speed=500: spans the whole drain sequence
        stream = await session.post(
            victim.url + "/v1/chat/completions",
            json=_chat_body(stream=True, max_tokens=n))
        assert stream.status == 200

        desired = await mgr.autoscale_once()
        assert desired["decode"] == 1
        await mgr.reconcile_once()
        assert victim.state == DRAINING
        watcher.check_and_apply()

        # New admissions on the draining replica bounce with the
        # retryable 503 — via the router they keep answering 200.
        async with session.post(victim.url + "/v1/chat/completions",
                                json=_chat_body()) as rejected:
            assert rejected.status == 503
            assert rejected.headers["Retry-After"] == "1"
        for _ in range(4):
            await route_one()

        # Byte-identity: the in-flight stream survives the drain.
        assert _sse_contents(await stream.text()) == \
            [f"tok{i} " for i in range(n)]
        stream.close()

        (left,) = await _settle(mgr, "decode", 1)
        assert left is survivor
        assert victim.process.poll() is not None  # clean self-exit

        config = json.loads(config_path.read_text())
        assert config["static_backends"] == [survivor.url]

        # The acceptance bar: zero dropped / zero 5xx across both
        # transitions.
        assert statuses and all(s == 200 for s in statuses)

        await mgr.drain_all()
        assert mgr.replicas["decode"] == []
    finally:
        for reps in mgr.replicas.values():
            for r in reps:
                if r.process.poll() is None:
                    r.process.kill()
        await mgr.close()
        await session.close()
        await router.close()
