"""Pallas paged decode attention vs the XLA reference implementation.

Runs the kernel in interpreter mode (CPU); the same code path compiles
for real TPU. Ground truth is ops.attention.paged_attention at T=1.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from production_stack_tpu.ops.attention import (  # noqa: E402
    paged_attention,
)
from production_stack_tpu.ops.paged_attention_pallas import (  # noqa: E402
    paged_decode_attention,
)


def _setup(b=3, num_pages=16, page_size=8, kv_heads=2, q_heads=8,
           head_dim=64, max_pages=6, seed=0):
    rng = np.random.RandomState(seed)
    q = rng.randn(b, q_heads, head_dim).astype(np.float32)
    k_cache = rng.randn(
        kv_heads, num_pages, head_dim, page_size
    ).astype(np.float32)
    v_cache = rng.randn(
        kv_heads, num_pages, head_dim, page_size
    ).astype(np.float32)
    # Distinct physical pages per sequence (1.. reserved pool).
    page_table = np.zeros((b, max_pages), np.int32)
    next_page = 1
    kv_lens = np.zeros((b,), np.int32)
    for i in range(b):
        n_tokens = rng.randint(1, max_pages * page_size)
        kv_lens[i] = n_tokens
        n_pages = -(-n_tokens // page_size)
        for j in range(n_pages):
            page_table[i, j] = next_page % num_pages or 1
            next_page += 1
    return (jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
            jnp.asarray(page_table), jnp.asarray(kv_lens))


def test_matches_xla_reference():
    q, k_cache, v_cache, page_table, kv_lens = _setup()
    out = paged_decode_attention(
        q, k_cache, v_cache, page_table, kv_lens, interpret=True
    )
    # Reference: T=1 queries positioned at the last cached token.
    ref = paged_attention(
        q[:, None], k_cache, v_cache, page_table,
        (kv_lens - 1)[:, None], kv_lens,
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_single_token_sequence():
    q, k_cache, v_cache, page_table, kv_lens = _setup(b=2, seed=3)
    kv_lens = jnp.asarray([1, 1], jnp.int32)
    out = paged_decode_attention(
        q, k_cache, v_cache, page_table, kv_lens, interpret=True
    )
    ref = paged_attention(
        q[:, None], k_cache, v_cache, page_table,
        (kv_lens - 1)[:, None], kv_lens,
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_gqa_grouping():
    q, k_cache, v_cache, page_table, kv_lens = _setup(
        kv_heads=4, q_heads=16, seed=7
    )
    out = paged_decode_attention(
        q, k_cache, v_cache, page_table, kv_lens, interpret=True
    )
    ref = paged_attention(
        q[:, None], k_cache, v_cache, page_table,
        (kv_lens - 1)[:, None], kv_lens,
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def _prefill_setup(b=2, num_pages=32, page_size=8, kv_heads=2,
                   q_heads=8, head_dim=64, max_pages=6, chunk=16,
                   seed=0):
    """Mid-prefill state: each sequence has some cached context and a
    chunk of T new queries positioned after it."""
    rng = np.random.RandomState(seed)
    q = rng.randn(b, chunk, q_heads, head_dim).astype(np.float32)
    k_cache = rng.randn(
        kv_heads, num_pages, head_dim, page_size).astype(np.float32)
    v_cache = rng.randn(
        kv_heads, num_pages, head_dim, page_size).astype(np.float32)
    page_table = np.zeros((b, max_pages), np.int32)
    positions = np.zeros((b, chunk), np.int32)
    kv_lens = np.zeros((b,), np.int32)
    next_page = 1
    for i in range(b):
        prior = rng.randint(0, (max_pages - 3) * page_size)
        kv_lens[i] = prior + chunk
        n_pages = -(-int(kv_lens[i]) // page_size)
        for j in range(n_pages):
            page_table[i, j] = next_page % num_pages or 1
            next_page += 1
        positions[i] = np.arange(prior, prior + chunk)
    return (jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
            jnp.asarray(page_table), jnp.asarray(positions),
            jnp.asarray(kv_lens))


def test_prefill_kernel_matches_xla_reference():
    from production_stack_tpu.ops.prefill_attention_pallas import (
        paged_prefill_attention,
    )
    args = _prefill_setup()
    out = paged_prefill_attention(*args, interpret=True)
    ref = paged_attention(*args)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_prefill_kernel_first_chunk():
    """Chunk starting at position 0 (no prior context)."""
    from production_stack_tpu.ops.prefill_attention_pallas import (
        paged_prefill_attention,
    )
    (q, k_cache, v_cache, page_table, positions,
     kv_lens) = _prefill_setup(b=1, seed=4)
    positions = jnp.asarray(
        np.arange(q.shape[1], dtype=np.int32)[None])
    kv_lens = jnp.asarray([q.shape[1]], jnp.int32)
    out = paged_prefill_attention(
        q, k_cache, v_cache, page_table, positions, kv_lens,
        interpret=True)
    ref = paged_attention(
        q, k_cache, v_cache, page_table, positions, kv_lens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_prefill_kernel_gqa():
    from production_stack_tpu.ops.prefill_attention_pallas import (
        paged_prefill_attention,
    )
    args = _prefill_setup(kv_heads=4, q_heads=16, seed=9)
    out = paged_prefill_attention(*args, interpret=True)
    ref = paged_attention(*args)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_decode_stacked_cache_layer_form():
    """The 5D + layer form (what the engine serves: SMEM layer index,
    cache passed through via input/output aliasing) must match the 4D
    per-layer slice at a NONZERO layer, and must hand the caches back
    through unchanged."""
    q, k_cache, v_cache, page_table, kv_lens = _setup(seed=11)
    L, layer = 3, 2
    rng = np.random.RandomState(21)
    k5 = jnp.asarray(rng.randn(L, *k_cache.shape).astype(np.float32))
    v5 = jnp.asarray(rng.randn(L, *v_cache.shape).astype(np.float32))
    out, k_thru, v_thru = paged_decode_attention(
        q, k5, v5, page_table, kv_lens, layer=layer, interpret=True
    )
    ref = paged_decode_attention(
        q, k5[layer], v5[layer], page_table, kv_lens, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    np.testing.assert_array_equal(np.asarray(k_thru), np.asarray(k5))
    np.testing.assert_array_equal(np.asarray(v_thru), np.asarray(v5))


def test_prefill_stacked_cache_layer_form():
    from production_stack_tpu.ops.prefill_attention_pallas import (
        paged_prefill_attention,
    )
    (q, k_cache, v_cache, page_table, positions,
     kv_lens) = _prefill_setup(seed=13)
    L, layer = 3, 1
    rng = np.random.RandomState(23)
    k5 = jnp.asarray(rng.randn(L, *k_cache.shape).astype(np.float32))
    v5 = jnp.asarray(rng.randn(L, *v_cache.shape).astype(np.float32))
    out, k_thru, v_thru = paged_prefill_attention(
        q, k5, v5, page_table, positions, kv_lens, layer=layer,
        interpret=True
    )
    ref = paged_prefill_attention(
        q, k5[layer], v5[layer], page_table, positions, kv_lens,
        interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    np.testing.assert_array_equal(np.asarray(k_thru), np.asarray(k5))
    np.testing.assert_array_equal(np.asarray(v_thru), np.asarray(v5))


def test_layer_cache_rank_mismatch_raises():
    q, k_cache, v_cache, page_table, kv_lens = _setup()
    with pytest.raises(ValueError, match="layer index and cache rank"):
        paged_decode_attention(
            q, k_cache, v_cache, page_table, kv_lens, layer=0,
            interpret=True)
    k5 = jnp.asarray(np.zeros((2, *k_cache.shape), np.float32))
    with pytest.raises(ValueError, match="layer index and cache rank"):
        paged_decode_attention(
            q, k5, k5, page_table, kv_lens, interpret=True)
    with pytest.raises(ValueError, match="layer index and cache rank"):
        paged_attention(
            q[:, None], k_cache, v_cache, page_table,
            (kv_lens - 1)[:, None], kv_lens, layer=0)


def test_engine_generates_identically_with_pallas_decode(tmp_path):
    """Greedy generation with the pallas decode path (interpret mode)
    must match the XLA decode path token for token."""
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, SchedulerConfig, tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams

    prompt = list(range(1, 40))

    def gen(impl):
        model = tiny_model_config("llama")
        model.attention_impl = impl
        config = EngineConfig(
            model=model,
            cache=CacheConfig(page_size=16, num_pages=64),
            scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                      prefill_chunk_size=64),
        )
        engine = LLMEngine(config)
        seq = engine.generate(prompt, SamplingParams(
            max_tokens=8, temperature=0.0, ignore_eos=True))
        return seq.output_token_ids

    assert gen("pallas-interpret") == gen("xla")


# ---- int8 quantized KV pages (docs/kv_quantization.md) ----------------------


def _quantize_cache(cache):
    """Quantize a [kv, pages, d, ps] (or [L, ...]) cache per
    (page, slot, head) row — the exact layout write_to_pages emits."""
    from production_stack_tpu.ops.quant_kv import QuantKV, quantize_kv
    perm = ((0, 1, 3, 2) if cache.ndim == 4 else (0, 1, 2, 4, 3))
    q, scale = quantize_kv(jnp.transpose(cache, perm))
    return QuantKV(jnp.transpose(q, perm), scale)


def test_paged_decode_attention_int8_parity():
    """bf16-vs-int8 parity for paged_decode_attention: on the SAME
    quantized cache the kernel must match the XLA reference exactly,
    and track the full-precision answer within the rounding budget."""
    q, k_cache, v_cache, page_table, kv_lens = _setup(seed=17)
    k8, v8 = _quantize_cache(k_cache), _quantize_cache(v_cache)
    out = paged_decode_attention(
        q, k8, v8, page_table, kv_lens, interpret=True
    )
    ref = paged_attention(
        q[:, None], k8, v8, page_table,
        (kv_lens - 1)[:, None], kv_lens,
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    full = paged_attention(
        q[:, None], k_cache, v_cache, page_table,
        (kv_lens - 1)[:, None], kv_lens,
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(full), atol=0.15
    )


def test_paged_prefill_attention_int8_parity():
    from production_stack_tpu.ops.prefill_attention_pallas import (
        paged_prefill_attention,
    )
    (q, k_cache, v_cache, page_table, positions,
     kv_lens) = _prefill_setup(seed=19)
    k8, v8 = _quantize_cache(k_cache), _quantize_cache(v_cache)
    out = paged_prefill_attention(
        q, k8, v8, page_table, positions, kv_lens, interpret=True)
    ref = paged_attention(
        q, k8, v8, page_table, positions, kv_lens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    full = paged_attention(
        q, k_cache, v_cache, page_table, positions, kv_lens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(full), atol=0.15
    )


def test_decode_int8_stacked_cache_layer_form():
    """Stacked quantized caches flow through the aliased layer form:
    output matches the per-layer slice, and BOTH leaves (int8 data +
    scales) hand back through unchanged."""
    q, k_cache, v_cache, page_table, kv_lens = _setup(seed=29)
    L, layer = 3, 2
    rng = np.random.RandomState(31)
    k5 = _quantize_cache(jnp.asarray(
        rng.randn(L, *k_cache.shape).astype(np.float32)))
    v5 = _quantize_cache(jnp.asarray(
        rng.randn(L, *v_cache.shape).astype(np.float32)))
    out, k_thru, v_thru = paged_decode_attention(
        q, k5, v5, page_table, kv_lens, layer=layer, interpret=True
    )
    ref = paged_decode_attention(
        q, k5[layer], v5[layer], page_table, kv_lens, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    for thru, src in ((k_thru, k5), (v_thru, v5)):
        np.testing.assert_array_equal(np.asarray(thru.data),
                                      np.asarray(src.data))
        np.testing.assert_array_equal(np.asarray(thru.scale),
                                      np.asarray(src.scale))


# ---- fused ragged kernel (unified step, docs/unified_step.md) ---------------


def _ragged_setup(kv_lens, last_index, draft_lens=None, w=8,
                  num_pages=64, page_size=8, kv_heads=2, q_heads=8,
                  head_dim=64, max_pages=8, seed=0):
    """Unified-step state from explicit per-row descriptors, plus the
    [R, W] positions the XLA-composed path materializes (recovered
    through the engine's layout invariant q_start = kv_len - 1 -
    last_index — model_runner.run_unified)."""
    rng = np.random.RandomState(seed)
    r = len(kv_lens)
    kv_lens = np.asarray(kv_lens, np.int32)
    last_index = np.asarray(last_index, np.int32)
    q = rng.randn(r, w, q_heads, head_dim).astype(np.float32)
    k_cache = rng.randn(
        kv_heads, num_pages, head_dim, page_size).astype(np.float32)
    v_cache = rng.randn(
        kv_heads, num_pages, head_dim, page_size).astype(np.float32)
    page_table = np.zeros((r, max_pages), np.int32)
    next_page = 1
    for i in range(r):
        for j in range(-(-int(kv_lens[i]) // page_size)):
            page_table[i, j] = next_page % num_pages or 1
            next_page += 1
    positions = np.maximum(
        (kv_lens - 1 - last_index)[:, None]
        + np.arange(w, dtype=np.int32)[None], 0).astype(np.int32)
    dl = (None if draft_lens is None
          else jnp.asarray(np.asarray(draft_lens, np.int32)))
    return (jnp.asarray(q), jnp.asarray(k_cache),
            jnp.asarray(v_cache), jnp.asarray(page_table),
            jnp.asarray(kv_lens), jnp.asarray(last_index), dl,
            jnp.asarray(positions))


def _assert_live_parity(out, ref, kv_lens, last_index):
    """Compare the live slots only: the composed path computes
    garbage attention in pad slots where the fused kernel writes
    zeros — both are discarded by the sampler's span gather."""
    out, ref = np.asarray(out), np.asarray(ref)
    for i in range(out.shape[0]):
        if int(kv_lens[i]) == 0:
            continue
        n = int(last_index[i]) + 1
        np.testing.assert_allclose(
            out[i, :n], ref[i, :n], rtol=2e-5, atol=2e-5)


def test_ragged_kernel_pure_decode():
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    (q, kc, vc, pt, kv, li, dl, pos) = _ragged_setup(
        kv_lens=[17, 1, 48, 33], last_index=[0, 0, 0, 0], seed=43)
    out = paged_ragged_attention(q, kc, vc, pt, kv, li, dl,
                                 interpret=True)
    ref = paged_attention(q, kc, vc, pt, pos, kv)
    _assert_live_parity(out, ref, kv, li)


def test_ragged_kernel_pure_prefill():
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    # Full-width chunks: one first chunk (q_start 0), one mid-prompt.
    (q, kc, vc, pt, kv, li, dl, pos) = _ragged_setup(
        kv_lens=[8, 29], last_index=[7, 7], seed=47)
    out = paged_ragged_attention(q, kc, vc, pt, kv, li, dl,
                                 interpret=True)
    ref = paged_attention(q, kc, vc, pt, pos, kv)
    _assert_live_parity(out, ref, kv, li)


def test_ragged_kernel_mixed_rows_and_pads():
    """The flagship mix: decode + spec-verify + short chunk + full
    chunk + pad rows, one grid."""
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    (q, kc, vc, pt, kv, li, dl, pos) = _ragged_setup(
        kv_lens=[20, 23, 13, 30, 0, 0],
        last_index=[0, 3, 4, 7, 0, 0],
        draft_lens=[0, 3, 0, 0, 0, 0], seed=53)
    out = paged_ragged_attention(q, kc, vc, pt, kv, li, dl,
                                 interpret=True)
    ref = paged_attention(q, kc, vc, pt, pos, kv)
    _assert_live_parity(out, ref, kv, li)
    # Dead slots and pad rows are fully masked to zero (the composed
    # path leaves garbage there; both are sliced off by the span
    # gather — this contract is what makes the fused output safe to
    # gather from without a validity mask).
    out = np.asarray(out)
    assert np.all(out[1, 4:] == 0)
    assert np.all(out[4] == 0) and np.all(out[5] == 0)


def test_ragged_kernel_verify_span_matches_composed():
    """A spec-verify row's draft span must score exactly like the
    composed prefill path scores it (the draft span is causally
    self-masking — no extra mask term)."""
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    (q, kc, vc, pt, kv, li, dl, pos) = _ragged_setup(
        kv_lens=[25, 41], last_index=[3, 2],
        draft_lens=[3, 2], seed=59)
    out = paged_ragged_attention(q, kc, vc, pt, kv, li, dl,
                                 interpret=True)
    ref = paged_attention(q, kc, vc, pt, pos, kv)
    _assert_live_parity(out, ref, kv, li)


def test_ragged_kernel_draft_lens_invariance():
    """Attention is invariant to draft_lens (the descriptor rides the
    prefetch tuple for the contract; the span is self-masking)."""
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    (q, kc, vc, pt, kv, li, dl, _pos) = _ragged_setup(
        kv_lens=[25, 41], last_index=[3, 2],
        draft_lens=[3, 2], seed=61)
    with_dl = paged_ragged_attention(q, kc, vc, pt, kv, li, dl,
                                     interpret=True)
    without = paged_ragged_attention(q, kc, vc, pt, kv, li, None,
                                     interpret=True)
    np.testing.assert_array_equal(np.asarray(with_dl),
                                  np.asarray(without))


def test_ragged_kernel_gqa_wide():
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    (q, kc, vc, pt, kv, li, dl, pos) = _ragged_setup(
        kv_lens=[20, 23, 30, 0], last_index=[0, 2, 5, 0],
        draft_lens=[0, 2, 0, 0], kv_heads=4, q_heads=16, w=16,
        seed=67)
    out = paged_ragged_attention(q, kc, vc, pt, kv, li, dl,
                                 interpret=True)
    ref = paged_attention(q, kc, vc, pt, pos, kv)
    _assert_live_parity(out, ref, kv, li)


def test_ragged_stacked_cache_layer_form():
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    (q, kc, vc, pt, kv, li, dl, _pos) = _ragged_setup(
        kv_lens=[20, 23, 30, 0], last_index=[0, 2, 5, 0],
        draft_lens=[0, 2, 0, 0], seed=71)
    L, layer = 3, 2
    rng = np.random.RandomState(73)
    k5 = jnp.asarray(rng.randn(L, *kc.shape).astype(np.float32))
    v5 = jnp.asarray(rng.randn(L, *vc.shape).astype(np.float32))
    out, k_thru, v_thru = paged_ragged_attention(
        q, k5, v5, pt, kv, li, dl, layer=layer, interpret=True)
    ref = paged_ragged_attention(
        q, k5[layer], v5[layer], pt, kv, li, dl, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(k_thru), np.asarray(k5))
    np.testing.assert_array_equal(np.asarray(v_thru), np.asarray(v5))


def test_paged_ragged_attention_int8_parity():
    """int8 parity for paged_ragged_attention (kv-parity staticcheck
    contract): on the SAME quantized cache the fused kernel matches
    the XLA reference exactly over the live slots, and tracks the
    full-precision answer within the rounding budget."""
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    (q, kc, vc, pt, kv, li, dl, pos) = _ragged_setup(
        kv_lens=[20, 23, 13, 30, 0], last_index=[0, 3, 4, 7, 0],
        draft_lens=[0, 3, 0, 0, 0], seed=79)
    k8, v8 = _quantize_cache(kc), _quantize_cache(vc)
    out = paged_ragged_attention(q, k8, v8, pt, kv, li, dl,
                                 interpret=True)
    ref = paged_attention(q, k8, v8, pt, pos, kv)
    _assert_live_parity(out, ref, kv, li)
    full = paged_attention(q, kc, vc, pt, pos, kv)
    out, full = np.asarray(out), np.asarray(full)
    for i in range(out.shape[0]):
        if int(kv[i]) == 0:
            continue
        n = int(li[i]) + 1
        np.testing.assert_allclose(out[i, :n], full[i, :n],
                                   atol=0.15)


def test_ragged_int8_stacked_cache_layer_form():
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    (q, kc, vc, pt, kv, li, dl, _pos) = _ragged_setup(
        kv_lens=[20, 23, 30, 0], last_index=[0, 2, 5, 0],
        draft_lens=[0, 2, 0, 0], seed=83)
    L, layer = 3, 1
    rng = np.random.RandomState(89)
    k5 = _quantize_cache(jnp.asarray(
        rng.randn(L, *kc.shape).astype(np.float32)))
    v5 = _quantize_cache(jnp.asarray(
        rng.randn(L, *vc.shape).astype(np.float32)))
    out, k_thru, v_thru = paged_ragged_attention(
        q, k5, v5, pt, kv, li, dl, layer=layer, interpret=True)
    ref = paged_ragged_attention(
        q, k5[layer], v5[layer], pt, kv, li, dl, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    for thru, src in ((k_thru, k5), (v_thru, v5)):
        np.testing.assert_array_equal(np.asarray(thru.data),
                                      np.asarray(src.data))
        np.testing.assert_array_equal(np.asarray(thru.scale),
                                      np.asarray(src.scale))


def test_prefill_int8_stacked_cache_layer_form():
    from production_stack_tpu.ops.prefill_attention_pallas import (
        paged_prefill_attention,
    )
    (q, k_cache, v_cache, page_table, positions,
     kv_lens) = _prefill_setup(seed=37)
    L, layer = 3, 1
    rng = np.random.RandomState(41)
    k5 = _quantize_cache(jnp.asarray(
        rng.randn(L, *k_cache.shape).astype(np.float32)))
    v5 = _quantize_cache(jnp.asarray(
        rng.randn(L, *v_cache.shape).astype(np.float32)))
    out, k_thru, v_thru = paged_prefill_attention(
        q, k5, v5, page_table, positions, kv_lens, layer=layer,
        interpret=True
    )
    ref = paged_prefill_attention(
        q, k5[layer], v5[layer], page_table, positions, kv_lens,
        interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    for thru, src in ((k_thru, k5), (v_thru, v5)):
        np.testing.assert_array_equal(np.asarray(thru.data),
                                      np.asarray(src.data))
        np.testing.assert_array_equal(np.asarray(thru.scale),
                                      np.asarray(src.scale))
