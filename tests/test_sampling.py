"""Sampling op: greedy fast path, top-k/top-p masking, mixed batches
(per-row params in one call — the continuous-batching requirement)."""

import numpy as np

import jax
import jax.numpy as jnp

from production_stack_tpu.ops.sampling import sample_tokens


def _logits(rows, vocab=50, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randn(rows, vocab).astype(np.float32)
    )


def test_greedy_is_argmax():
    logits = _logits(4)
    out = sample_tokens(
        logits, jnp.zeros(4), jnp.ones(4), jnp.zeros(4, jnp.int32),
        jax.random.PRNGKey(0),
    )
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.argmax(logits, -1))
    )


def test_topk_restricts_support():
    logits = _logits(2, seed=3)
    top2 = set()
    for row in np.asarray(logits):
        top2.update(np.argsort(-row)[:2].tolist())
    for seed in range(20):
        out = sample_tokens(
            logits, jnp.ones(2), jnp.ones(2),
            jnp.full((2,), 2, jnp.int32), jax.random.PRNGKey(seed),
        )
        for i, tok in enumerate(np.asarray(out)):
            row_top2 = np.argsort(-np.asarray(logits)[i])[:2]
            assert tok in row_top2


def test_topp_keeps_most_likely():
    logits = _logits(3, seed=5) * 5  # peaked
    for seed in range(10):
        out = sample_tokens(
            logits, jnp.ones(3), jnp.full((3,), 1e-6),
            jnp.zeros(3, jnp.int32), jax.random.PRNGKey(seed),
        )
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(jnp.argmax(logits, -1))
        )


def test_mixed_greedy_and_stochastic_rows():
    logits = _logits(2, seed=7)
    out = sample_tokens(
        logits, jnp.asarray([0.0, 1.0]), jnp.ones(2),
        jnp.zeros(2, jnp.int32), jax.random.PRNGKey(1),
    )
    # Row 0 greedy regardless of the stochastic row alongside.
    assert int(out[0]) == int(jnp.argmax(logits[0]))


def test_apply_penalties_semantics():
    from production_stack_tpu.ops.sampling import apply_penalties

    logits = jnp.asarray([[2.0, -1.0, 0.5, 3.0]], jnp.float32)
    counts = jnp.asarray([[2, 0, 1, 0]], jnp.int32)     # output so far
    pmask = jnp.asarray([[False, True, False, False]])  # in prompt
    out = apply_penalties(
        logits, counts, pmask,
        presence=jnp.asarray([0.5], jnp.float32),
        frequency=jnp.asarray([0.25], jnp.float32),
        repetition=jnp.asarray([2.0], jnp.float32),
    )
    out = np.asarray(out)[0]
    # vLLM/HF order: repetition first on the raw logit, then the
    # presence/frequency subtractions.
    # token 0: seen twice -> 2.0/2 = 1.0, then -0.5 - 2*0.25
    np.testing.assert_allclose(out[0], 2.0 / 2.0 - 0.5 - 0.5)
    # token 1: prompt-only -> negative logit * r; no pres/freq
    np.testing.assert_allclose(out[1], -1.0 * 2.0)
    # token 2: seen once -> 0.5/2 = 0.25, then -0.5 - 0.25
    np.testing.assert_allclose(out[2], 0.5 / 2.0 - 0.5 - 0.25)
    # token 3: never seen -> unchanged
    np.testing.assert_allclose(out[3], 3.0)


def test_apply_penalties_disabled_is_identity():
    from production_stack_tpu.ops.sampling import apply_penalties

    logits = _logits(3, seed=9)
    counts = jnp.ones(logits.shape, jnp.int32)
    pmask = jnp.ones(logits.shape, bool)
    out = apply_penalties(
        logits, counts, pmask,
        presence=jnp.zeros(3, jnp.float32),
        frequency=jnp.zeros(3, jnp.float32),
        repetition=jnp.ones(3, jnp.float32),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(logits),
                               rtol=1e-6)
