"""Sampling op: greedy fast path, top-k/top-p masking, mixed batches
(per-row params in one call — the continuous-batching requirement)."""

import numpy as np

import jax
import jax.numpy as jnp

from production_stack_tpu.ops.sampling import sample_tokens


def _logits(rows, vocab=50, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randn(rows, vocab).astype(np.float32)
    )


def test_greedy_is_argmax():
    logits = _logits(4)
    out = sample_tokens(
        logits, jnp.zeros(4), jnp.ones(4), jnp.zeros(4, jnp.int32),
        jax.random.PRNGKey(0),
    )
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.argmax(logits, -1))
    )


def test_topk_restricts_support():
    logits = _logits(2, seed=3)
    top2 = set()
    for row in np.asarray(logits):
        top2.update(np.argsort(-row)[:2].tolist())
    for seed in range(20):
        out = sample_tokens(
            logits, jnp.ones(2), jnp.ones(2),
            jnp.full((2,), 2, jnp.int32), jax.random.PRNGKey(seed),
        )
        for i, tok in enumerate(np.asarray(out)):
            row_top2 = np.argsort(-np.asarray(logits)[i])[:2]
            assert tok in row_top2


def test_topp_keeps_most_likely():
    logits = _logits(3, seed=5) * 5  # peaked
    for seed in range(10):
        out = sample_tokens(
            logits, jnp.ones(3), jnp.full((3,), 1e-6),
            jnp.zeros(3, jnp.int32), jax.random.PRNGKey(seed),
        )
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(jnp.argmax(logits, -1))
        )


def test_mixed_greedy_and_stochastic_rows():
    logits = _logits(2, seed=7)
    out = sample_tokens(
        logits, jnp.asarray([0.0, 1.0]), jnp.ones(2),
        jnp.zeros(2, jnp.int32), jax.random.PRNGKey(1),
    )
    # Row 0 greedy regardless of the stochastic row alongside.
    assert int(out[0]) == int(jnp.argmax(logits[0]))
