"""Multi-host serving tests: 2 jax.distributed CPU processes execute
the same engine steps via the MultihostStepBridge broadcast.

This is the distributed-without-cluster test the reference gets from
envtest/kind (SURVEY.md §4); here the real jax.distributed runtime runs
as local processes, so the broadcast protocol and global-mesh dispatch
are exercised without TPU pods.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


HELPER = os.path.join(os.path.dirname(__file__), "multihost_helper.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_two_process_bridge_generation():
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {**os.environ, "PYTHONPATH": REPO}
    env.pop("XLA_FLAGS", None)  # helper sets its own device count
    procs = [
        subprocess.Popen(
            [sys.executable, HELPER, coordinator, "2", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=REPO,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=420)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for code, out, err in outs:
        assert code == 0, f"proc failed:\n{out}\n{err}"
    token_line = [ln for ln in outs[0][1].splitlines()
                  if ln.startswith("TOKENS=")]
    assert token_line, outs[0][1]
    tokens = json.loads(token_line[0][len("TOKENS="):])
    assert len(tokens) == 6
    assert "WORKER_DONE" in outs[1][1]

    # The coordinator's greedy output must match a plain single-process
    # run of the same config/seed (the bridge must not perturb numerics).
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, SchedulerConfig, tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32,
                                  decode_steps=4),
    )
    ref_engine = LLMEngine(config)
    ref = ref_engine.generate(
        list(range(1, 20)),
        SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True),
    )
    assert ref.output_token_ids == tokens

    # The embed bridge leg (KIND_EMBED) must also have run and matched
    # a single-process embed of the same inputs.
    embed_line = [ln for ln in outs[0][1].splitlines()
                  if ln.startswith("EMBED=")]
    assert embed_line, outs[0][1]
    embed_first_dims = json.loads(embed_line[0][len("EMBED="):])
    from production_stack_tpu.engine.embeddings import Embedder
    embedder = Embedder(config.model, ref_engine.runner.params,
                        max_len=config.scheduler.max_model_len)
    ref_vecs = embedder.embed_batch([[1, 2, 3], [4, 5, 6, 7]])
    np.testing.assert_allclose(embed_first_dims, ref_vecs[:, 0],
                               atol=1e-4)


def test_bridge_template_matches_real_payloads():
    """The worker-side payload template must structurally match what
    host 0 actually publishes for every optional-input combination —
    template/payload drift desyncs the broadcast and hangs the slice."""
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, SchedulerConfig, tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams
    from production_stack_tpu.parallel.distributed import (
        MultihostStepBridge,
    )

    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256,
                                  prefill_chunk_size=64,
                                  decode_steps=4),
    )
    engine = LLMEngine(config)
    bridge = MultihostStepBridge(engine.runner)

    published = []

    def fake_publish(kind, t, payload):
        flags = 0
        if "pen_prompt_mask" in payload:
            flags |= bridge.FLAG_PENALTIES
        if "seed_rows" in payload:
            flags |= bridge.FLAG_SEEDING
        if payload.get("want_logprobs"):
            flags |= bridge.FLAG_LOGPROBS
        if "logit_bias" in payload:
            flags |= bridge.FLAG_BIAS
        if "sup_ids" in payload:
            flags |= bridge.FLAG_SUPPRESS
        if "fsm_state" in payload:
            flags |= bridge.FLAG_GUIDED
        arrays = {k: v for k, v in payload.items()
                  if k != "want_logprobs"}
        published.append((kind, t, flags, arrays))

    engine.runner.bridge = bridge
    bridge.publish = fake_publish

    engine.generate(list(range(1, 40)), SamplingParams(
        max_tokens=6, temperature=0.7, seed=7,
        presence_penalty=0.5, logprobs=True, top_logprobs=2,
        logit_bias={9: -1.5}, min_tokens=4, guided="json",
    ))

    assert published, "bridge.publish never called"
    for kind, t, flags, arrays in published:
        template = bridge._payload_template(kind, t, flags)
        assert set(template) == set(arrays), (
            f"kind={kind} t={t} flags={flags}: template keys "
            f"{sorted(template)} != payload keys {sorted(arrays)}")
        for k in template:
            assert template[k].shape == np.asarray(arrays[k]).shape, (
                f"{k}: {template[k].shape} != "
                f"{np.asarray(arrays[k]).shape}")
            assert template[k].dtype == np.asarray(arrays[k]).dtype, (
                f"{k}: {template[k].dtype} != "
                f"{np.asarray(arrays[k]).dtype}")
