"""Batched prefill: the next chunks of several waiting sequences run
as one fixed-width device program (scheduler.PrefillPlan.chunks) and
must generate exactly what serial admission generates."""

import numpy as np

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams


def _engine(prefill_batch_size):
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=128,
                          enable_prefix_caching=False),
        scheduler=SchedulerConfig(max_num_seqs=8, max_model_len=256,
                                  prefill_chunk_size=32,
                                  prefill_batch_size=prefill_batch_size),
    )
    return LLMEngine(config)


def _prompts(n, rs):
    return [[int(x) for x in rs.randint(1, 500, size=rs.randint(5, 60))]
            for _ in range(n)]


def test_plan_batches_multiple_sequences():
    engine = _engine(prefill_batch_size=4)
    for p in _prompts(4, np.random.RandomState(0)):
        engine.add_request(p, SamplingParams(max_tokens=4,
                                             temperature=0.0,
                                             ignore_eos=True))
    plan = engine.scheduler.plan_step()
    assert plan.prefill is not None
    # Short prompts (< chunk size): all four batch into one program.
    assert len(plan.prefill.chunks) == 4
    assert len({c.seq.seq_id for c in plan.prefill.chunks}) == 4


def test_batched_matches_serial_generation():
    rs = np.random.RandomState(42)
    prompts = _prompts(5, rs)
    sampling = dict(max_tokens=6, temperature=0.0, ignore_eos=True)

    serial = _engine(prefill_batch_size=1)
    expected = [serial.generate(p, SamplingParams(**sampling))
                .output_token_ids for p in prompts]

    batched = _engine(prefill_batch_size=4)
    seqs = []
    for p in prompts:
        sid = batched.add_request(p, SamplingParams(**sampling))
        seqs.append(batched.sequences[sid])
    while batched.has_work():
        batched.step()
    got = [s.output_token_ids for s in seqs]
    assert got == expected


def test_chunked_long_prompts_batch_with_short():
    """A multi-chunk prompt interleaves its chunks with other
    sequences' chunks and still completes correctly."""
    rs = np.random.RandomState(7)
    long_prompt = [int(x) for x in rs.randint(1, 500, size=100)]
    short = [[3, 4, 5], [9, 8, 7, 6]]
    sampling = dict(max_tokens=4, temperature=0.0, ignore_eos=True)

    ref = _engine(prefill_batch_size=1)
    exp_long = ref.generate(long_prompt,
                            SamplingParams(**sampling)).output_token_ids

    engine = _engine(prefill_batch_size=3)
    sid_long = engine.add_request(long_prompt, SamplingParams(**sampling))
    sids = [engine.add_request(p, SamplingParams(**sampling))
            for p in short]
    all_seqs = [engine.sequences[s] for s in [sid_long] + sids]
    while engine.has_work():
        engine.step()
    assert all(len(s.output_token_ids) == 4 for s in all_seqs)
    assert all_seqs[0].output_token_ids == exp_long
