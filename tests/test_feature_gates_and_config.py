"""Feature gates + dynamic config hot-reload."""

import json

import pytest

from production_stack_tpu.router.dynamic_config import (
    DynamicConfigWatcher,
    DynamicRouterConfig,
)
from production_stack_tpu.router.experimental.feature_gates import (
    SEMANTIC_CACHE_GATE,
    FeatureGates,
)
from production_stack_tpu.router.routing.logic import (
    LeastLoadedPolicy,
    RoundRobinPolicy,
    get_routing_logic,
    initialize_routing_logic,
)
from production_stack_tpu.router.service_discovery import (
    get_service_discovery,
    initialize_service_discovery,
)
from production_stack_tpu.router.stats.request_stats import (
    initialize_request_stats_monitor,
)


def test_feature_gates_parse():
    gates = FeatureGates("SemanticCache=true")
    assert gates.enabled(SEMANTIC_CACHE_GATE)
    assert not gates.enabled("PIIDetection")


def test_feature_gates_reject_unknown():
    with pytest.raises(ValueError):
        FeatureGates("NoSuchGate=true")
    with pytest.raises(ValueError):
        FeatureGates("SemanticCache")


def test_dynamic_config_parses_string_and_list_backends():
    config = DynamicRouterConfig.from_json(json.dumps({
        "service_discovery": "static",
        "routing_logic": "llq",
        "static_backends": "http://a:1,http://b:2",
        "static_models": ["m1", "m2"],
    }))
    assert config.static_backends == ["http://a:1", "http://b:2"]
    assert config.static_models == ["m1", "m2"]


def test_dynamic_config_watcher_applies_changes(tmp_path):
    initialize_request_stats_monitor(60.0)
    initialize_service_discovery("static", urls=["http://old:1"])
    initialize_routing_logic("roundrobin")
    assert isinstance(get_routing_logic(), RoundRobinPolicy)

    config_path = tmp_path / "dynamic.json"
    config_path.write_text(json.dumps({
        "service_discovery": "static",
        "routing_logic": "llq",
        "static_backends": "http://new:2",
        "static_models": "modelA",
    }))
    watcher = DynamicConfigWatcher(str(config_path), poll_interval_s=3600)
    try:
        watcher.check_and_apply()
        eps = get_service_discovery().get_endpoint_info()
        assert [ep.url for ep in eps] == ["http://new:2"]
        assert eps[0].model_names == ["modelA"]
        assert isinstance(get_routing_logic(), LeastLoadedPolicy)

        # Unchanged file is a no-op.
        assert watcher.check_and_apply() is False

        # Changed file reapplies.
        config_path.write_text(json.dumps({
            "service_discovery": "static",
            "routing_logic": "roundrobin",
            "static_backends": "http://third:3",
        }))
        assert watcher.check_and_apply() is True
        assert isinstance(get_routing_logic(), RoundRobinPolicy)
    finally:
        watcher.close()


def test_dynamic_config_watcher_survives_bad_json(tmp_path):
    initialize_request_stats_monitor(60.0)
    initialize_service_discovery("static", urls=["http://keep:1"])
    initialize_routing_logic("roundrobin")
    config_path = tmp_path / "dynamic.json"
    config_path.write_text("{not json")
    watcher = DynamicConfigWatcher(str(config_path), poll_interval_s=3600)
    try:
        assert watcher.check_and_apply() is False
        eps = get_service_discovery().get_endpoint_info()
        assert [ep.url for ep in eps] == ["http://keep:1"]
    finally:
        watcher.close()
