"""Live wiring of the cluster SLO ledger (docs/observability.md):
fake engines under a breaching timing fault drive the burn-rate
gauges, slow-request exemplar capture with a stitched waterfall at
GET /debug/slow, the /cluster/status rollup and the stacktop console;
plus the scrape-side regression test for the -1 "no data" p99
sentinel and the fake engine's SLO fault modes.
"""

import asyncio
import json
import time

from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.router.app import build_app
from production_stack_tpu.router.parser import parse_args
from production_stack_tpu.testing.fake_engine import build_fake_engine

SLO_SPEC = {
    "objective": 0.9,
    "classes": {
        # Interactive gets a generous TTFT budget the slow fault stays
        # inside; batch gets one it always breaches.
        "interactive": {"ttft_s": 5.0},
        "batch": {"ttft_s": 0.05},
    },
}


def _write_spec(tmp_path, spec=SLO_SPEC, name="slo.json"):
    path = tmp_path / name
    path.write_text(json.dumps(spec))
    return str(path)


async def _rig(fake, router_args, fn):
    """One fake engine + a router built from CLI args."""
    fake_server = TestServer(fake)
    await fake_server.start_server()
    url = f"http://127.0.0.1:{fake_server.port}"
    try:
        args = parse_args([
            "--service-discovery", "static",
            "--static-backends", url,
            "--static-models", "m1",
            "--routing-logic", "roundrobin",
        ] + router_args)
        client = TestClient(TestServer(build_app(args)))
        await client.start_server()
        try:
            await fn(client, url)
        finally:
            await client.close()
    finally:
        await fake_server.close()
        from production_stack_tpu.router.tracing import (
            initialize_span_logger,
        )
        initialize_span_logger(None)


def _sample(text, name, **labels):
    """Value of one Prometheus sample from exposition text, or None."""
    frag = ",".join(f'{k}="{v}"' for k, v in labels.items())
    for line in text.splitlines():
        if line.startswith(f"{name}{{") and frag in line:
            return float(line.rsplit(" ", 1)[1])
        if not labels and line.startswith(f"{name} "):
            return float(line.rsplit(" ", 1)[1])
    return None


def test_overload_breach_burns_budget_and_archives_exemplar(tmp_path):
    fake = build_fake_engine(model="m1", speed=1000, ttft=0.0,
                             fault="slow_ttft")
    fake["state"].slow_ttft_s = 0.2

    async def run(client, url):
        async def one(priority):
            resp = await client.post(
                "/v1/chat/completions",
                json={"model": "m1",
                      "messages": [{"role": "user", "content": "x"}],
                      "max_tokens": 4, "stream": True},
                headers={"x-priority": priority})
            assert resp.status == 200
            await resp.read()

        # ~2x overload: all six requests in flight at once against one
        # engine; four batch (breaching), two interactive (within).
        await asyncio.gather(*[one("batch") for _ in range(4)],
                             *[one("interactive") for _ in range(2)])
        # Exemplar capture is fire-and-forget; let the tasks finish.
        await asyncio.sleep(0.5)

        resp = await client.get("/metrics")
        text = await resp.text()
        burn = _sample(text, "vllm:slo_burn_rate", window="5m")
        assert burn is not None and burn > 1.0
        att_int = _sample(text, "vllm:slo_attainment",
                          **{"class": "interactive", "model": "m1"})
        att_batch = _sample(text, "vllm:slo_attainment",
                            **{"class": "batch", "model": "m1"})
        assert att_int == 1.0
        assert att_batch == 0.0
        assert _sample(text, "vllm:slo_bad_requests_total",
                       **{"class": "batch", "model": "m1"}) == 4.0
        assert _sample(text, "vllm:slow_archive_depth") == 4.0

        resp = await client.get("/debug/slow")
        body = await resp.json()
        assert body["depth"] == 4 and body["archived_total"] == 4
        entry = body["entries"][0]
        assert entry["class"] == "batch" and entry["model"] == "m1"
        assert entry["breach"][0]["metric"] == "ttft"
        assert entry["server"] == url
        # The stitched waterfall carries both the router span and the
        # engine flight-recorder timeline for the same request id.
        rid = entry["request_id"]
        spans = entry["spans"]
        assert {s["span"] for s in spans} == {"request",
                                              "engine_request"}
        assert all(s["request_id"] == rid for s in spans)
        assert entry["waterfall"].startswith(
            f"request {rid}  ({len(spans)} spans)")
        assert "first_token" in entry["waterfall"]

        # Class/model filters and the limit contract.
        resp = await client.get("/debug/slow?class=interactive")
        assert (await resp.json())["entries"] == []
        resp = await client.get("/debug/slow?limit=bogus")
        assert resp.status == 400

        # Replayable offline through traceview --from-slow-archive.
        from production_stack_tpu.traceview import main as traceview
        path = tmp_path / "slow.json"
        path.write_text(json.dumps(body))
        assert traceview(["--from-slow-archive", str(path),
                          "--request-id", rid]) == 0

    asyncio.run(_rig(fake, [
        "--slo-spec", _write_spec(tmp_path),
        "--slow-archive-size", "16",
    ], run))


def test_debug_slow_is_503_without_spec():
    fake = build_fake_engine(model="m1", speed=1000, ttft=0.0)

    async def run(client, url):
        resp = await client.get("/debug/slow")
        assert resp.status == 503

    asyncio.run(_rig(fake, [], run))


def test_cluster_status_and_stacktop_console(tmp_path):
    fake = build_fake_engine(model="m1", speed=1000, ttft=0.0)
    baseline = tmp_path / "perf_baseline.json"
    baseline.write_text(json.dumps(
        {"band": 0.25, "phases": {"decode": 0.025, "prefill": 0.5}}))

    async def run(client, url):
        resp = await client.post(
            "/v1/chat/completions",
            json={"model": "m1",
                  "messages": [{"role": "user", "content": "x"}],
                  "max_tokens": 2},
            headers={"x-priority": "interactive"})
        assert resp.status == 200
        await resp.read()

        resp = await client.get("/cluster/status")
        snap = await resp.json()
        assert url in snap["servers"]
        server = snap["servers"][url]
        assert server["model"] == "m1" and server["healthy"] is True
        assert snap["slo"]["good_requests"] == 1
        assert snap["slow_archive"]["depth"] == 0
        # Sentinel enabled: verdict block present (engine medians only
        # arrive with the stats scrape, so no trip is asserted here).
        assert set(snap["perf_drift"]) == {"decode", "prefill"}

        # The console renders that snapshot; --once --plain is the
        # scriptable mode, exercised against the live router from a
        # worker thread (stacktop polls with sync requests).
        from production_stack_tpu import stacktop
        base = f"http://127.0.0.1:{client.port}"
        loop = asyncio.get_running_loop()
        rc = await loop.run_in_executor(
            None, stacktop.main, ["--url", base, "--once", "--plain"])
        assert rc == 0
        snap2 = await loop.run_in_executor(
            None, stacktop.fetch_snapshot, base)
        out = stacktop.render_snapshot(snap2)
        assert "tpu-stack cluster status" in out
        assert url in out and "SLO objective=0.9" in out

    asyncio.run(_rig(fake, [
        "--slo-spec", _write_spec(tmp_path),
        "--perf-baseline", str(baseline),
    ], run))


def test_spans_and_stats_carry_class_and_tenant(tmp_path):
    """Satellite: every router span and request-stats observation is
    attributed with priority class and tenant."""
    fake = build_fake_engine(model="m1", speed=1000, ttft=0.0)
    span_log = str(tmp_path / "spans.jsonl")

    async def run(client, url):
        resp = await client.post(
            "/v1/chat/completions",
            json={"model": "m1",
                  "messages": [{"role": "user", "content": "x"}],
                  "max_tokens": 2},
            headers={"x-priority": "interactive",
                     "x-api-key": "tenant-a"})
        assert resp.status == 200
        await resp.read()

        from production_stack_tpu.router.stats.request_stats import (
            get_request_stats_monitor,
        )
        monitor = get_request_stats_monitor()
        assert monitor.arrivals_by_class.get("interactive") == 1

    asyncio.run(_rig(fake, ["--request-span-log", span_log], run))
    line = json.loads(open(span_log).read().splitlines()[0])
    assert line["priority_class"] == "interactive"
    assert line["tenant"] == "tenant-a"


def test_idle_p99_sentinel_not_exported(tmp_path):
    """Satellite regression: RequestStats' -1 "no observation" p99
    sentinel must never reach the Prometheus exposition — an idle
    server renders no sample, and a stale sample is removed once its
    window empties."""
    from prometheus_client import REGISTRY, generate_latest

    from production_stack_tpu.router.services import metrics_service
    from production_stack_tpu.router.stats.request_stats import (
        initialize_request_stats_monitor,
    )

    monitor = initialize_request_stats_monitor(0.2)
    url = "http://idle-p99-regression:1"
    now = time.time()
    monitor.on_request_arrival("rid-1", now)
    monitor.on_request_routed(url, "rid-1", now)

    def exposition():
        metrics_service.refresh_gauges()
        return generate_latest(REGISTRY).decode()

    # Routed but no first token yet: the p99 windows are empty (-1
    # internally) and the exposition must carry NO sample — not -1.
    text = exposition()
    assert f'vllm:ttft_p99_seconds{{server="{url}"}}' not in text
    assert f'vllm:itl_p99_seconds{{server="{url}"}}' not in text

    # First token observed: a real sample appears.
    monitor.on_request_response(url, "rid-1", time.time(),
                                is_first_token=True)
    text = exposition()
    value = None
    for line in text.splitlines():
        if line.startswith(f'vllm:ttft_p99_seconds{{server="{url}"}}'):
            value = float(line.rsplit(" ", 1)[1])
    assert value is not None and value >= 0

    # Window expires: the stale child is removed again, not left at
    # its last value and not reset to -1.
    time.sleep(0.3)
    text = exposition()
    assert f'vllm:ttft_p99_seconds{{server="{url}"}}' not in text


def test_fake_engine_slow_faults_and_cluster_status():
    """Satellite: the fake engine honors the slow_ttft / slow_itl
    timing faults (breach-but-succeed) and serves /cluster/status-
    shaped stats."""

    async def run():
        fake = build_fake_engine(model="m1", speed=1000, ttft=0.0,
                                 fault="slow_ttft")
        fake["state"].slow_ttft_s = 0.25
        server = TestServer(fake)
        await server.start_server()
        try:
            client = TestClient(server)
            t0 = time.monotonic()
            resp = await client.post("/v1/chat/completions", json={
                "model": "m1",
                "messages": [{"role": "user", "content": "x"}],
                "max_tokens": 2, "stream": True})
            assert resp.status == 200
            await resp.content.readany()
            assert time.monotonic() - t0 >= 0.25
            await resp.read()
            # The non-streaming completions path honors the fault too.
            t0 = time.monotonic()
            resp = await client.post("/v1/completions", json={
                "model": "m1", "prompt": "x", "max_tokens": 2})
            assert resp.status == 200
            await resp.read()
            assert time.monotonic() - t0 >= 0.25

            status = await (await client.get("/cluster/status")).json()
            assert "ts" in status and "servers" in status
            (entry,) = status["servers"].values()
            assert "running" in entry and "cache_usage" in entry
        finally:
            await server.close()

        fake = build_fake_engine(model="m1", speed=1000, ttft=0.0,
                                 fault="slow_itl")
        fake["state"].slow_itl_s = 0.1
        server = TestServer(fake)
        await server.start_server()
        try:
            client = TestClient(server)
            t0 = time.monotonic()
            resp = await client.post("/v1/chat/completions", json={
                "model": "m1",
                "messages": [{"role": "user", "content": "x"}],
                "max_tokens": 4, "stream": True})
            assert resp.status == 200
            await resp.read()
            # 4 tokens at a forced >= 0.1s cadence.
            assert time.monotonic() - t0 >= 0.3
        finally:
            await server.close()

    asyncio.run(run())
