"""vLLM ``min_tokens``: EOS and stop_token_ids cannot be GENERATED
until min_tokens tokens exist — suppressed on device while under the
minimum (model_runner._suppress_payload / _apply_suppression), with a
host finish guard for stop sets wider than the compiled width."""

import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams


def _engine(decode_steps=1, deferred=False):
    return LLMEngine(EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256,
                                  prefill_chunk_size=32,
                                  decode_steps=decode_steps,
                                  deferred_kv_writes=deferred),
    ))


PROMPT = list(range(5, 25))


def _gen(engine, **kw):
    sampling = dict(max_tokens=16, temperature=0.0)
    sampling.update(kw)
    return engine.generate(PROMPT, SamplingParams(**sampling))


def _greedy_stop():
    """The unconstrained greedy first token — used as a stop id so the
    stop would fire immediately without min_tokens."""
    seq = _gen(_engine(), max_tokens=1, ignore_eos=True)
    return seq.output_token_ids[0]


def test_min_tokens_defers_stop():
    stop = _greedy_stop()
    # Without min_tokens the stop fires on the first token.
    base = _gen(_engine(), stop_token_ids=[stop])
    assert len(base.output_token_ids) == 1
    assert base.output_token_ids[-1] == stop
    # With min_tokens=5 the stop id cannot appear in the first 5
    # tokens at all (suppressed, not just non-terminal).
    got = _gen(_engine(), stop_token_ids=[stop], min_tokens=5)
    assert len(got.output_token_ids) >= 5
    assert stop not in got.output_token_ids[:5]


def test_min_tokens_parity_across_decode_paths():
    stop = _greedy_stop()
    kw = dict(stop_token_ids=[stop], min_tokens=6)
    ref = _gen(_engine(), **kw).output_token_ids
    burst = _gen(_engine(decode_steps=4), **kw).output_token_ids
    deferred = _gen(_engine(decode_steps=4, deferred=True),
                    **kw).output_token_ids
    assert burst == ref
    assert deferred == ref


def test_min_tokens_then_stop_naturally():
    """After the minimum, generation is unconstrained: with a stop on
    every-greedy-token, the very next token after the minimum is the
    (now permitted) greedy stop."""
    stop = _greedy_stop()
    got = _gen(_engine(decode_steps=4), stop_token_ids=[stop],
               min_tokens=3)
    out = got.output_token_ids
    assert len(out) >= 3 and stop not in out[:3]
    if got.finish_reason is not None and len(out) < 16:
        assert out[-1] == stop  # finished BY the stop, post-minimum


def test_preemption_preserves_generation_budgets():
    """KV-pressure preemption folds generated tokens back into the
    prompt (scheduler._preempt); num_prior_output_tokens must keep
    the max_tokens budget counting across the fold — a preempted
    sequence must NOT restart its generation window (and by the same
    counter, min_tokens and the seeded emitted index survive too)."""
    from production_stack_tpu.engine.config import EngineConfig

    engine = LLMEngine(EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=12,
                          enable_prefix_caching=False),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32,
                                  decode_steps=4),
    ))
    seqs = []
    for i in range(2):
        sid = engine.add_request(
            list(range(2, 42 + i)),
            SamplingParams(max_tokens=48, temperature=0.0,
                           ignore_eos=True))
        seqs.append(engine.sequences[sid])
    while engine.has_work():
        engine.step()
    assert engine.scheduler.num_preemptions >= 1, (
        "test setup no longer forces preemption — shrink the cache")
    finished = [s for s in seqs if s.finish_reason is not None
                and s.finish_reason.value == "length"]
    assert finished, "no sequence ran to its max_tokens budget"
    for s in finished:
        assert s.num_generated == 48, (
            s.num_generated, s.num_prior_output_tokens)


def test_min_tokens_validation():
    from production_stack_tpu.engine.server import _sampling_from_body

    p = _sampling_from_body({"min_tokens": 4, "max_tokens": 8}, 256)
    assert p.min_tokens == 4
    with pytest.raises(ValueError, match="min_tokens"):
        _sampling_from_body({"min_tokens": 9, "max_tokens": 8}, 256)
    with pytest.raises(ValueError, match="min_tokens"):
        _sampling_from_body({"min_tokens": -1}, 256)
