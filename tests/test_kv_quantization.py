"""Quantized int8 paged KV cache (docs/kv_quantization.md):
config gating + page-budget expansion, ops-level quantization error
bounds, XLA attention parity against full precision, engine-level
greedy token-stream parity int8 vs bf16 (plain decode, prefix-cache
hits on quantized pages, speculative decoding), executable-cache
stability, and /metrics exposition + router scrape of the KV gauges.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    ParallelConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams
from production_stack_tpu.ops.attention import (
    paged_attention,
    write_to_pages,
)
from production_stack_tpu.ops.quant_kv import (
    QuantKV,
    quant_cache_zeros,
    quantize_kv,
)


def _engine(kv_dtype="auto", num_pages=64, **sched_kw):
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=num_pages,
                          kv_cache_dtype=kv_dtype),
        scheduler=SchedulerConfig(max_num_seqs=4,
                                  max_model_len=256,
                                  prefill_chunk_size=32,
                                  **sched_kw),
    )
    return LLMEngine(config)


def _prompts():
    rs = np.random.RandomState(3)
    return [
        [5, 6, 7] * 12,
        [9, 9, 9, 9, 9, 9, 9, 9],
        [11, 12, 13, 14] * 20,
        [int(x) for x in rs.randint(1, 500, size=23)],
    ]


def _greedy(engine, prompts, max_tokens=12):
    return [
        list(engine.generate(p, SamplingParams(
            temperature=0.0, max_tokens=max_tokens,
            ignore_eos=True)).output_token_ids)
        for p in prompts
    ]


# ---- config -----------------------------------------------------------------


def test_kv_dtype_validation():
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        _engine(kv_dtype="fp8")
    # int8 composes with pipeline and context parallelism: the
    # shard_map boundaries carry congruent QuantKV pytree specs
    # (docs/parallelism.md), so these configs now construct cleanly.
    for parallel in (ParallelConfig(pipeline_parallel_size=2),
                     ParallelConfig(context_parallel_size=2)):
        cfg = EngineConfig(
            model=tiny_model_config("llama"),
            cache=CacheConfig(page_size=16, num_pages=64,
                              kv_cache_dtype="int8"),
            scheduler=SchedulerConfig(max_num_seqs=4,
                                      max_model_len=256),
            parallel=parallel,
        )
        assert cfg.cache.resolved_kv_dtype() == "int8"


def test_page_budget_expansion_and_idempotency():
    model = tiny_model_config("llama")
    model.dtype = "bfloat16"
    base = CacheConfig(page_size=16, num_pages=1024,
                       kv_cache_dtype="int8")
    config = EngineConfig(
        model=model, cache=base,
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256),
    )
    # bf16 slot = 2*d bytes; int8 slot = d + 4 (scale amortized over
    # the head row) -> ~1.88x more pages at the same byte budget for
    # d=32.
    ratio = config.cache.num_pages / 1024
    assert 1.7 <= ratio <= 2.0
    # Same HBM bytes, up to one slot of rounding.
    full_slot = model.head_dim * 2
    assert (config.cache.num_pages * (model.head_dim + 4)
            <= 1024 * full_slot)
    # dataclasses.replace reuses the already-expanded CacheConfig:
    # __post_init__ must not expand twice.
    replaced = dataclasses.replace(config)
    assert replaced.cache.num_pages == config.cache.num_pages

    # Full precision never expands.
    cfg2 = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256),
    )
    assert cfg2.cache.num_pages == 64
    assert cfg2.cache.resolved_kv_dtype() == "bf16"


def test_kv_bytes_accounting():
    model = tiny_model_config("llama")  # f32, d=32, 2L, 2kv
    cache = CacheConfig(page_size=16, num_pages=64,
                        kv_cache_dtype="int8")
    assert cache.kv_slot_bytes(model) == model.head_dim + 4
    assert cache.kv_bytes_per_token(model) == (
        2 * model.num_hidden_layers * model.num_key_value_heads
        * (model.head_dim + 4))
    full = CacheConfig(page_size=16, num_pages=64)
    assert full.kv_slot_bytes(model) == model.head_dim * 4  # f32


# ---- ops --------------------------------------------------------------------


def test_quantize_kv_roundtrip_bound():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, 8, 2, 32).astype(np.float32))
    q, scale = quantize_kv(x)
    assert q.dtype == jnp.int8 and scale.shape == x.shape[:-1]
    dq = q.astype(jnp.float32) * scale[..., None]
    # Symmetric rounding error is at most half a quantization step
    # per element, amax/127 per (token, head) row.
    step = np.abs(np.asarray(x)).max(axis=-1, keepdims=True) / 127.0
    assert np.all(np.abs(np.asarray(dq - x)) <= step * 0.5 + 1e-6)


def test_quantkv_pytree_and_indexing():
    kv = quant_cache_zeros((2, 2, 8, 16, 4))
    leaves, treedef = jax.tree_util.tree_flatten(kv)
    assert len(leaves) == 2
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert isinstance(rebuilt, QuantKV)
    assert rebuilt.data.shape == (2, 2, 8, 16, 4)
    assert rebuilt.scale.shape == (2, 2, 8, 4)
    layer = kv[0]
    assert layer.data.shape == (2, 8, 16, 4)
    assert layer.scale.shape == (2, 8, 4)


def test_paged_attention_int8_parity_with_f32():
    """bf16-vs-int8 parity for paged_attention (the XLA impl): the
    quantized cache's output must track the full-precision one within
    the int8 rounding budget on identical inputs."""
    rs = np.random.RandomState(1)
    kv_heads, pages, d, ps, b, qh = 2, 9, 32, 16, 3, 4
    kf = jnp.asarray(rs.randn(kv_heads, pages, d, ps) * 0.5,
                     jnp.float32)
    vf = jnp.asarray(rs.randn(kv_heads, pages, d, ps) * 0.5,
                     jnp.float32)
    # Quantize the same cache content per (page, slot, head) row.
    kq, ks = quantize_kv(kf.transpose(1, 3, 0, 2))
    vq, vs = quantize_kv(vf.transpose(1, 3, 0, 2))
    k8 = QuantKV(kq.transpose(2, 0, 3, 1), ks.transpose(2, 0, 1))
    v8 = QuantKV(vq.transpose(2, 0, 3, 1), vs.transpose(2, 0, 1))
    q = jnp.asarray(rs.randn(b, 1, qh, d) * 0.5, jnp.float32)
    table = jnp.asarray(
        np.stack([rs.choice(pages - 1, 4, replace=False) + 1
                  for _ in range(b)]),
        jnp.int32)
    kv_lens = jnp.asarray([50, 17, 33], jnp.int32)
    q_pos = (kv_lens - 1)[:, None]
    ref = paged_attention(q, kf, vf, table, q_pos, kv_lens)
    got = paged_attention(q, k8, v8, table, q_pos, kv_lens)
    assert got.shape == ref.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=0.05)


def test_write_to_pages_quantized_matches_full_precision():
    rs = np.random.RandomState(2)
    kv_heads, pages, d, ps, b, t = 2, 6, 32, 16, 2, 5
    new_kv = jnp.asarray(rs.randn(b, t, kv_heads, d), jnp.float32)
    table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(t)[None], (b, t))
    valid = jnp.ones((b, t), bool)
    full = write_to_pages(
        jnp.zeros((kv_heads, pages, d, ps)), new_kv, table,
        positions, valid)
    quant = write_to_pages(
        quant_cache_zeros((kv_heads, pages, d, ps)), new_kv, table,
        positions, valid)
    dq = (quant.data.astype(jnp.float32)
          * quant.scale[:, :, None, :])
    step = (jnp.max(jnp.abs(new_kv), axis=-1).max() / 127.0 + 1e-6)
    assert float(jnp.abs(dq - full).max()) <= float(step) * 0.5 + 1e-6
    # Stacked form with a static layer index scatters identically.
    stacked = write_to_pages(
        quant_cache_zeros((1, kv_heads, pages, d, ps)), new_kv,
        table, positions, valid, layer=0)
    np.testing.assert_array_equal(np.asarray(stacked.data[0]),
                                  np.asarray(quant.data))
    np.testing.assert_array_equal(np.asarray(stacked.scale[0]),
                                  np.asarray(quant.scale))


# ---- engine -----------------------------------------------------------------


def test_int8_greedy_token_stream_parity():
    expected = _greedy(_engine("auto"), _prompts())
    got = _greedy(_engine("int8"), _prompts())
    assert got == expected


def test_prefix_cache_hit_on_quantized_pages():
    engine = _engine("int8")
    prompt = list(range(2, 66))  # 4 full pages => 3 cacheable
    first = _greedy(engine, [prompt], max_tokens=8)
    hits0 = engine.cache_manager.prefix_hit_tokens
    second = _greedy(engine, [prompt], max_tokens=8)
    assert engine.cache_manager.prefix_hit_tokens > hits0
    assert second == first


def test_prefix_query_tokens_not_counted_when_disabled():
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64,
                          enable_prefix_caching=False),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256),
    )
    engine = LLMEngine(config)
    _greedy(engine, [_prompts()[0]], max_tokens=4)
    assert engine.cache_manager.prefix_query_tokens == 0
    assert engine.cache_manager.prefix_hit_rate() == 0.0


def test_spec_decode_on_quantized_pages():
    # Draft-free speculation is lossless: spec-on int8 must emit the
    # same greedy stream as spec-off int8 (repetitive prompt so the
    # prompt-lookup proposer actually drafts).
    prompt = list(range(5, 25)) + list(range(5, 25))
    plain = _greedy(_engine("int8"), [prompt], max_tokens=16)
    spec = _engine("int8", speculative_k=3)
    got = _greedy(spec, [prompt], max_tokens=16)
    assert got == plain
    assert spec.metrics.spec_draft_tokens_total > 0


def test_no_per_step_recompiles_int8():
    engine = _engine("int8")
    _greedy(engine, _prompts()[:2], max_tokens=8)
    jit = engine.runner._step_jit
    if not hasattr(jit, "_cache_size"):
        pytest.skip("jit cache introspection unavailable")
    before = jit._cache_size()
    _greedy(engine, _prompts()[2:], max_tokens=8)
    assert jit._cache_size() == before


# ---- telemetry --------------------------------------------------------------


def test_engine_stats_and_metrics_exposition():
    engine = _engine("int8", num_pages=64)
    st = engine.stats()
    assert st["engine_kv_cache_page_capacity"] == (
        engine.config.cache.num_pages - 1)
    assert st["engine_kv_bytes_per_decode_step"] == (
        4 * engine.config.cache.kv_bytes_per_token(
            engine.config.model))

    import asyncio

    from production_stack_tpu.engine.server import EngineServer
    server = EngineServer(engine, "tiny-llama")
    resp = asyncio.new_event_loop().run_until_complete(
        server.metrics(None))
    text = resp.text
    assert "vllm:engine_kv_cache_page_capacity" in text
    assert "vllm:engine_kv_bytes_per_decode_step" in text
    assert 'vllm:engine_kv_cache_dtype{kv_dtype="int8"} 1.0' in text

    from production_stack_tpu.router.stats.engine_stats import (
        EngineStats,
    )
    scraped = EngineStats.from_prometheus_text(text)
    assert scraped.engine_kv_cache_page_capacity == (
        engine.config.cache.num_pages - 1)
    assert scraped.engine_kv_bytes_per_decode_step == (
        st["engine_kv_bytes_per_decode_step"])
    assert scraped.engine_kv_cache_dtype == "int8"


def test_server_flag_threading():
    from production_stack_tpu.engine.server import parse_args
    args = parse_args(["--kv-cache-dtype", "int8"])
    assert args.kv_cache_dtype == "int8"
    assert parse_args([]).kv_cache_dtype == "auto"
