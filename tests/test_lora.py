"""Multi-LoRA serving tests (reference capability: --enable-lora
pass-through, helm/values.yaml:56-58, tutorials/08-lora flow).

Covers: zero-slot == base numerics, per-row adapter isolation in one
batch, PEFT safetensors loading, engine-level generation by adapter
name, and the server's /v1/models + adapter routing.
"""

import asyncio
import json
import os

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

import jax
import jax.numpy as jnp

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    LoRAConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.lora import (
    LoRAAdapter,
    LoRARegistry,
    empty_lora_stack,
    load_peft_adapter,
    target_shapes,
)
from production_stack_tpu.engine.sequence import SamplingParams
from production_stack_tpu.models import llama


def _tiny_forward_setup():
    config = tiny_model_config("llama")
    params = llama.init_params(config, jax.random.PRNGKey(0))
    num_pages, page_size, max_pages = 8, 16, 4
    cache_shape = (config.num_hidden_layers, config.num_key_value_heads,
                   num_pages, config.head_dim, page_size)
    k_cache = jnp.zeros(cache_shape, config.jax_dtype)
    v_cache = jnp.zeros(cache_shape, config.jax_dtype)
    b, t = 2, 8
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, config.vocab_size, (b, t)),
        jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(t)[None], (b, t))
    page_table = jnp.asarray([[1, 2, 0, 0], [3, 4, 0, 0]], jnp.int32)
    kv_lens = jnp.asarray([t, t], jnp.int32)
    valid = jnp.ones((b, t), bool)
    return (config, params, tokens, positions, page_table, kv_lens,
            valid, k_cache, v_cache)


def _random_adapter(config, rank, max_rank, scale=1.0, seed=7):
    rs = np.random.RandomState(seed)
    weights = {}
    for tgt, (d_in, d_out) in target_shapes(config).items():
        a = np.zeros((config.num_hidden_layers, d_in, max_rank),
                     np.float32)
        b = np.zeros((config.num_hidden_layers, max_rank, d_out),
                     np.float32)
        a[:, :, :rank] = rs.randn(
            config.num_hidden_layers, d_in, rank).astype(np.float32)
        b[:rank] = 0.0
        b[:, :rank, :] = rs.randn(
            config.num_hidden_layers, rank, d_out).astype(np.float32)
        weights[tgt] = (a, b)
    return LoRAAdapter(name="test-adapter", rank=rank, scaling=scale,
                       weights=weights)


def test_zero_stack_matches_base():
    """An all-zero LoRA stack must not change base-model logits."""
    (config, params, tokens, positions, page_table, kv_lens, valid,
     k_cache, v_cache) = _tiny_forward_setup()
    stack = empty_lora_stack(config, max_loras=2, max_lora_rank=4)
    ids = jnp.zeros((2,), jnp.int32)

    base_logits, _, _ = llama.forward(
        params, config, tokens, positions, page_table, kv_lens, valid,
        k_cache, v_cache)
    lora_logits, _, _ = llama.forward(
        params, config, tokens, positions, page_table, kv_lens, valid,
        k_cache, v_cache, lora=stack, lora_ids=ids)
    np.testing.assert_allclose(
        np.asarray(base_logits), np.asarray(lora_logits), atol=1e-5)


def test_per_row_adapter_isolation():
    """Row with slot 0 must match base; row with an adapter must not."""
    (config, params, tokens, positions, page_table, kv_lens, valid,
     k_cache, v_cache) = _tiny_forward_setup()
    registry = LoRARegistry(config, max_loras=2, max_lora_rank=4)
    slot = registry.register(
        _random_adapter(config, rank=4, max_rank=4, scale=0.5))
    assert slot == 1
    ids = jnp.asarray([0, 1], jnp.int32)

    base_logits, _, _ = llama.forward(
        params, config, tokens, positions, page_table, kv_lens, valid,
        k_cache, v_cache)
    mixed_logits, _, _ = llama.forward(
        params, config, tokens, positions, page_table, kv_lens, valid,
        k_cache, v_cache, lora=registry.stack, lora_ids=ids)
    base = np.asarray(base_logits)
    mixed = np.asarray(mixed_logits)
    np.testing.assert_allclose(base[0], mixed[0], atol=1e-5)
    assert np.abs(base[1] - mixed[1]).max() > 1e-3


def _write_peft_dir(tmp_path, config, rank=2, alpha=4.0):
    from safetensors.numpy import save_file
    rs = np.random.RandomState(3)
    raw = {}
    for i in range(config.num_hidden_layers):
        for proj, (d_in, d_out) in (
            ("q_proj", (config.hidden_size,
                        config.num_attention_heads * config.head_dim)),
            ("v_proj", (config.hidden_size,
                        config.num_key_value_heads * config.head_dim)),
        ):
            prefix = (f"base_model.model.model.layers.{i}."
                      f"self_attn.{proj}")
            raw[f"{prefix}.lora_A.weight"] = rs.randn(
                rank, d_in).astype(np.float32)
            raw[f"{prefix}.lora_B.weight"] = rs.randn(
                d_out, rank).astype(np.float32)
    adapter_dir = os.path.join(str(tmp_path), "adapter")
    os.makedirs(adapter_dir, exist_ok=True)
    save_file(raw, os.path.join(adapter_dir, "adapter_model.safetensors"))
    with open(os.path.join(adapter_dir, "adapter_config.json"), "w") as f:
        json.dump({"r": rank, "lora_alpha": alpha,
                   "target_modules": ["q_proj", "v_proj"]}, f)
    return adapter_dir, raw


def test_peft_loader(tmp_path):
    config = tiny_model_config("llama")
    adapter_dir, raw = _write_peft_dir(tmp_path, config, rank=2,
                                       alpha=4.0)
    adapter = load_peft_adapter(adapter_dir, config, max_lora_rank=4)
    assert adapter.rank == 2
    assert adapter.scaling == pytest.approx(2.0)  # alpha / r
    assert set(adapter.weights) == {"wq", "wv"}
    A, B = adapter.weights["wq"]
    layers = config.num_hidden_layers
    nh_d = config.num_attention_heads * config.head_dim
    assert A.shape == (layers, config.hidden_size, 4)  # rank-padded
    assert B.shape == (layers, 4, nh_d)
    # Transposition round-trip: A[i] == raw A.T, pad columns zero.
    key = "base_model.model.model.layers.0.self_attn.q_proj.lora_A.weight"
    np.testing.assert_allclose(A[0, :, :2], raw[key].T)
    assert np.all(A[0, :, 2:] == 0)


def test_peft_loader_rejects_oversized_rank(tmp_path):
    config = tiny_model_config("llama")
    adapter_dir, _ = _write_peft_dir(tmp_path, config, rank=8)
    with pytest.raises(ValueError, match="exceeds"):
        load_peft_adapter(adapter_dir, config, max_lora_rank=4)


def _lora_engine(tmp_path=None, modules=()):
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=128,
                                  prefill_chunk_size=32),
        lora=LoRAConfig(enable=True, max_loras=2, max_lora_rank=4),
    )
    engine = LLMEngine(config)
    for name, path in modules:
        engine.register_lora(path, name=name)
    return engine


def test_engine_generation_with_adapter(tmp_path):
    config = tiny_model_config("llama")
    adapter_dir, _ = _write_peft_dir(tmp_path, config, rank=2)
    engine = _lora_engine(modules=[("my-adapter", adapter_dir)])
    prompt = list(range(2, 20))
    sampling = SamplingParams(max_tokens=8, temperature=0.0,
                              ignore_eos=True)

    base_id = engine.add_request(prompt, SamplingParams(**vars(sampling)))
    base_seq = engine.sequences[base_id]
    lora_id = engine.add_request(prompt, SamplingParams(**vars(sampling)),
                                 lora_name="my-adapter")
    lora_seq = engine.sequences[lora_id]
    while engine.has_work():
        engine.step()
    assert len(base_seq.output_token_ids) == 8
    assert len(lora_seq.output_token_ids) == 8
    assert lora_seq.lora_id == 1

    # Same prompt again on base must reproduce (greedy, deterministic
    # given per-engine rng is unused at temperature 0).
    rerun_id = engine.add_request(prompt, SamplingParams(**vars(sampling)))
    rerun_seq = engine.sequences[rerun_id]
    while engine.has_work():
        engine.step()
    assert rerun_seq.output_token_ids == base_seq.output_token_ids


def test_engine_rejects_unknown_adapter():
    engine = _lora_engine()
    with pytest.raises(KeyError):
        engine.add_request([1, 2, 3], lora_name="nope")


def test_server_lists_and_serves_adapters(tmp_path):
    from production_stack_tpu.engine.server import EngineServer

    config = tiny_model_config("llama")
    adapter_dir, _ = _write_peft_dir(tmp_path, config, rank=2)
    engine = _lora_engine(modules=[("sql-lora", adapter_dir)])
    server = EngineServer(engine, "tiny-llama")

    async def run():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            resp = await client.get("/v1/models")
            data = await resp.json()
            ids = [m["id"] for m in data["data"]]
            assert ids == ["tiny-llama", "sql-lora"]
            assert data["data"][1]["parent"] == "tiny-llama"

            resp = await client.post("/v1/chat/completions", json={
                "model": "sql-lora",
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4,
            })
            assert resp.status == 200
            payload = await resp.json()
            assert payload["choices"][0]["message"]["content"] is not None
        finally:
            await client.close()

    asyncio.run(run())


def test_registry_rollback_on_failed_install():
    """A failed install must not leave the name mapped to a zero slot
    (which would silently serve the base model for that adapter)."""
    config = tiny_model_config("llama")
    registry = LoRARegistry(config, max_loras=2, max_lora_rank=4)
    bad = LoRAAdapter(name="bad", rank=2, scaling=1.0,
                      weights={"not_a_target": (np.zeros((2, 4, 4)),
                                                np.zeros((2, 4, 4)))})
    with pytest.raises(ValueError, match="Unknown LoRA target"):
        registry.register(bad)
    assert "bad" not in registry.slots
    # The slot stays free for the next adapter.
    ok = _random_adapter(config, rank=2, max_rank=4, scale=1.0)
    assert registry.register(ok) == 1


def _write_gpt2_peft_dir(tmp_path, config, rank=2, alpha=4.0):
    from safetensors.numpy import save_file
    rs = np.random.RandomState(7)
    h = config.hidden_size
    raw = {}
    for i in range(config.num_hidden_layers):
        prefix = f"base_model.model.transformer.h.{i}.attn.c_attn"
        raw[f"{prefix}.lora_A.weight"] = rs.randn(
            rank, h).astype(np.float32)
        raw[f"{prefix}.lora_B.weight"] = rs.randn(
            3 * h, rank).astype(np.float32)
        mlp = f"base_model.model.transformer.h.{i}.mlp.c_fc"
        raw[f"{mlp}.lora_A.weight"] = rs.randn(
            rank, h).astype(np.float32)
        raw[f"{mlp}.lora_B.weight"] = rs.randn(
            config.intermediate_size, rank).astype(np.float32)
    adapter_dir = os.path.join(str(tmp_path), "gpt2-adapter")
    os.makedirs(adapter_dir, exist_ok=True)
    save_file(raw, os.path.join(adapter_dir,
                                "adapter_model.safetensors"))
    with open(os.path.join(adapter_dir, "adapter_config.json"),
              "w") as f:
        json.dump({"r": rank, "lora_alpha": alpha,
                   "target_modules": ["c_attn", "c_fc"]}, f)
    return adapter_dir, raw


def test_peft_loader_gpt2_splits_fused_qkv(tmp_path):
    """GPT-2's fused c_attn (A shared, B split into q/k/v thirds) must
    decompose exactly: the q output block of x@(BA).T equals
    x @ A.T @ B[:h].T."""
    config = tiny_model_config("gpt2")
    h = config.hidden_size
    adapter_dir, raw = _write_gpt2_peft_dir(tmp_path, config, rank=2,
                                            alpha=4.0)
    adapter = load_peft_adapter(adapter_dir, config, max_lora_rank=4)
    assert {"wq", "wk", "wv", "fc1"} <= set(adapter.weights)

    A_raw = raw["base_model.model.transformer.h.0.attn.c_attn"
                ".lora_A.weight"]  # [r, h]
    B_raw = raw["base_model.model.transformer.h.0.attn.c_attn"
                ".lora_B.weight"]  # [3h, r]
    x = np.random.RandomState(0).randn(3, h).astype(np.float32)
    fused = x @ A_raw.T @ B_raw.T  # [3, 3h]
    for j, tgt in enumerate(("wq", "wk", "wv")):
        a, b = adapter.weights[tgt]
        ours = x @ a[0] @ b[0]  # rank-padded cols are zero
        np.testing.assert_allclose(ours, fused[:, j * h:(j + 1) * h],
                                   rtol=1e-5, atol=1e-5)


def test_gpt2_engine_generation_with_adapter(tmp_path):
    config = tiny_model_config("gpt2")
    adapter_dir, _ = _write_gpt2_peft_dir(tmp_path, config, rank=2)
    engine_config = EngineConfig(
        model=config,
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=128,
                                  prefill_chunk_size=32),
        lora=LoRAConfig(enable=True, max_loras=2, max_lora_rank=4),
    )
    engine = LLMEngine(engine_config)
    engine.register_lora(adapter_dir, name="gpt2-lora")
    seq_id = engine.add_request(
        [1, 2, 3, 4],
        SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True),
        lora_name="gpt2-lora")
    seq = engine.sequences[seq_id]
    while engine.has_work():
        engine.step()
    assert len(seq.output_token_ids) == 4
    assert seq.lora_id == 1


def test_prefix_cache_never_crosses_adapters():
    """Adapter KV (wk/wv carry the deltas) must not serve base-model
    requests with the same prompt, or vice versa — the page-hash chain
    is salted per (adapter, generation). Round-4 fix: before it, the
    second request below hit the first's pages and decoded against
    adapter-contaminated KV."""
    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        LoRAConfig,
        SchedulerConfig,
        tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams

    def make_engine(with_adapter):
        config = EngineConfig(
            model=tiny_model_config("llama"),
            cache=CacheConfig(page_size=16, num_pages=64),
            scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                      prefill_chunk_size=32,
                                      prefill_batch_size=2),
            lora=LoRAConfig(enable=True, max_loras=2, max_lora_rank=4),
        )
        engine = LLMEngine(config)
        if with_adapter:
            engine.runner.lora_registry.register(
                _random_adapter(engine.config.model, rank=4,
                                max_rank=4, scale=2.0))
        return engine

    sampling = lambda: SamplingParams(  # noqa: E731
        max_tokens=6, temperature=0.0, ignore_eos=True)
    # > 2 full pages so the prefix cache has chainable pages.
    prompt = list(range(3, 3 + 40))

    # Ground truth: base-only engine, no adapter ever ran.
    clean = make_engine(False)
    base_expected = clean.generate(prompt, sampling()).output_token_ids

    # Adapter request first (pages get cached), then the SAME prompt
    # as base: the base answer must be identical to the clean engine's.
    eng = make_engine(True)
    adapter_out = eng.generate(prompt, sampling(),
                               lora_name="test-adapter").output_token_ids
    base_out = eng.generate(prompt, sampling()).output_token_ids
    assert base_out == base_expected
    # Sanity: the adapter path actually diverges (scale 2.0 adapter).
    assert adapter_out != base_expected

    # And adapter-after-adapter still hits its own namespace: same
    # output, now with a prefix-cache hit.
    hits_before = eng.cache_manager.prefix_hit_tokens
    adapter_out2 = eng.generate(prompt, sampling(),
                                lora_name="test-adapter"
                                ).output_token_ids
    assert adapter_out2 == adapter_out
    assert eng.cache_manager.prefix_hit_tokens > hits_before
