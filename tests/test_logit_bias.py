"""OpenAI ``logit_bias``: per-request {token_id: bias in [-100, 100]}
added to the logits after penalties, before sampling; logprobs keep
reporting the RAW distribution (the OpenAI contract). Applied on
device as a dense [B, vocab] add only for batches where some row uses
it (model_runner._bias_payload — bias-free batches keep their
bias-free compiled program)."""

import numpy as np
import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams


def _engine(decode_steps=1, deferred=False):
    return LLMEngine(EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256,
                                  prefill_chunk_size=32,
                                  decode_steps=decode_steps,
                                  deferred_kv_writes=deferred),
    ))


PROMPT = list(range(5, 25))


def _gen(engine, **kw):
    sampling = dict(max_tokens=8, temperature=0.0, ignore_eos=True)
    sampling.update(kw)
    return engine.generate(PROMPT, SamplingParams(**sampling))


def test_ban_and_force_tokens():
    base = _gen(_engine()).output_token_ids
    # Ban the greedy first token: it must never be sampled again.
    banned = base[0]
    got = _gen(_engine(), logit_bias={banned: -100.0}).output_token_ids
    assert banned not in got
    # Force an arbitrary token: +100 dominates tiny-model logits.
    forced = 123
    got = _gen(_engine(), logit_bias={forced: 100.0}).output_token_ids
    assert got == [forced] * 8


def test_bias_parity_across_decode_paths():
    """Single-step, eager burst, and deferred burst must apply the
    bias identically (it rides the shared _burst_sample_step)."""
    bias = {77: 5.0, 300: -100.0}
    ref = _gen(_engine(), logit_bias=bias).output_token_ids
    burst = _gen(_engine(decode_steps=4),
                 logit_bias=bias).output_token_ids
    deferred = _gen(_engine(decode_steps=4, deferred=True),
                    logit_bias=bias).output_token_ids
    assert burst == ref
    assert deferred == ref


def test_mixed_batch_rows_isolated():
    """A biased row must not leak its bias into unbiased rows of the
    same compiled (biased) batch."""
    engine = _engine(decode_steps=4)
    plain_ref = _gen(_engine(decode_steps=4)).output_token_ids
    seqs = []
    for kw in ({}, {"logit_bias": {123: 100.0}}):
        sid = engine.add_request(PROMPT, SamplingParams(
            max_tokens=8, temperature=0.0, ignore_eos=True, **kw))
        seqs.append(engine.sequences[sid])
    while engine.has_work():
        engine.step()
    plain, biased = (s.output_token_ids for s in seqs)
    assert plain == plain_ref
    assert biased == [123] * 8


def test_logprobs_stay_raw():
    """A +100-forced token is sampled but its reported logprob comes
    from the RAW distribution — near-certain under the biased one,
    unlikely under the raw one."""
    engine = _engine()
    sid = engine.add_request(PROMPT, SamplingParams(
        max_tokens=4, temperature=0.0, ignore_eos=True,
        logprobs=True, top_logprobs=3, logit_bias={123: 100.0}))
    seq = engine.sequences[sid]
    lps = []
    while engine.has_work():
        for out in engine.step():
            if out.logprobs is not None:
                lps.append(out.logprobs)
    assert seq.output_token_ids == [123] * 4
    for sampled_lp, _top in lps:
        # ln p(123) under the biased distribution would be ~0; under
        # the raw one the forced token is a bystander.
        assert sampled_lp < -1.0


def test_server_parses_and_validates_logit_bias():
    from production_stack_tpu.engine.server import _sampling_from_body

    p = _sampling_from_body(
        {"logit_bias": {"123": 50, "7": -100}}, 256)
    assert p.logit_bias == {123: 50.0, 7: -100.0}
    with pytest.raises(ValueError, match="at most 300"):
        _sampling_from_body(
            {"logit_bias": {str(i): 1 for i in range(301)}}, 256)
    with pytest.raises(ValueError, match=r"\[-100, 100\]"):
        _sampling_from_body({"logit_bias": {"5": 101}}, 256)
    with pytest.raises(ValueError, match="integer token ids"):
        _sampling_from_body({"logit_bias": {"abc": 1}}, 256)
    with pytest.raises(ValueError, match="must be an object"):
        _sampling_from_body({"logit_bias": [1, 2]}, 256)
    with pytest.raises(ValueError, match="outside the model"):
        _sampling_from_body({"logit_bias": {"600": 1}}, 256,
                            vocab_size=512)
    # Without a known vocab (direct callers), ids pass through.
    assert _sampling_from_body(
        {"logit_bias": {"600": 1}}, 256).logit_bias == {600: 1.0}
