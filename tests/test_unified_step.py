"""Unified ragged step (docs/unified_step.md): greedy byte-parity
with the bimodal scheduler over mixed staggered-admission runs (bf16
and int8 KV), spec-decode under async scheduling, executable-cache
stability across a repeated mixed run, dissolved exclusivity rules,
and page accounting when a row finishes inside a ragged batch."""

import numpy as np
import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import (
    SamplingParams,
    SequenceState,
)


def _engine(unified=False, async_on=False, kv_dtype="auto",
            unified_impl=None, **sched_kw):
    model = tiny_model_config("llama")
    if unified_impl is not None:
        # Pin the unified step's kernel (e.g. the fused ragged kernel
        # in interpret mode — how CPU tier-1 holds the byte-parity
        # contract against the XLA-composed path).
        model.attention_impl_unified = unified_impl
    config = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_pages=128,
                          kv_cache_dtype=kv_dtype),
        scheduler=SchedulerConfig(max_num_seqs=4,
                                  max_model_len=256,
                                  prefill_chunk_size=32,
                                  unified_step=unified,
                                  async_scheduling=async_on,
                                  **sched_kw),
    )
    return LLMEngine(config)


def _prompts(seed=7):
    rs = np.random.RandomState(seed)
    return [
        [4, 5, 6] * 13,
        [8, 8, 8, 8, 8, 8, 8, 8, 8, 8],
        [21, 22, 23, 24] * 20,  # 80 tokens: 3 chunks under chunk 32
        [int(x) for x in rs.randint(1, 500, size=41)],
    ]


# Varied budgets so rows finish at different steps; the long third
# prompt keeps prefilling while rows 1-2 decode, so a unified
# scheduler plans genuinely mixed batches.
_MAX_TOKENS = [18, 9, 14, 25]


def _run_mixed(engine, seed=7):
    """~50-step run: chunked prefills, staggered admission (the 4th
    prompt arrives only after the 2nd finishes — mid-decode, so its
    chunks are admitted INTO live decode steps under unified
    scheduling), interleaved finishes."""
    prompts = _prompts(seed)
    seqs = []
    for p, m in zip(prompts[:3], _MAX_TOKENS[:3]):
        sid = engine.add_request(p, SamplingParams(
            temperature=0.0, max_tokens=m, ignore_eos=True))
        seqs.append(engine.sequences[sid])
    late_added = False
    for _ in range(500):
        engine.step()
        if (not late_added
                and seqs[1].state == SequenceState.FINISHED):
            sid = engine.add_request(prompts[3], SamplingParams(
                temperature=0.0, max_tokens=_MAX_TOKENS[3],
                ignore_eos=True))
            seqs.append(engine.sequences[sid])
            late_added = True
        if late_added and not engine.has_work():
            break
    assert late_added and not engine.has_work()
    return [list(s.output_token_ids) for s in seqs]


@pytest.mark.parametrize("kv_dtype", ["auto", "int8"])
def test_greedy_parity_bimodal_vs_unified(kv_dtype):
    bimodal = _engine(unified=False, kv_dtype=kv_dtype)
    expected = _run_mixed(bimodal)
    unified = _engine(unified=True, kv_dtype=kv_dtype)
    got = _run_mixed(unified)
    assert got == expected
    assert [len(t) for t in got] == _MAX_TOKENS
    # Mixed batches genuinely ran through the ragged program, and the
    # bimodal engine never did.
    assert unified.metrics.ragged_steps_total > 0
    assert bimodal.metrics.ragged_steps_total == 0


@pytest.mark.parametrize("kv_dtype", ["auto", "int8"])
def test_greedy_parity_composed_vs_ragged_kernel(kv_dtype):
    """Greedy streams must be byte-identical between the XLA-composed
    unified step and the fused Pallas ragged kernel (interpret mode)
    — over a staggered mixed run WITH drafted rows, so every row kind
    (decode, spec-verify with draft spans, prefill chunk, pad)
    crosses the kernel's in-kernel mask rebuild, for bf16 AND int8
    KV."""
    base = [3, 9, 27, 9] * 14
    prompts = [base, base[:24] * 2, list(reversed(base))]
    max_tokens = [14, 26, 20]

    def run(engine):
        seqs = []
        for p, m in zip(prompts, max_tokens):
            sid = engine.add_request(p, SamplingParams(
                temperature=0.0, max_tokens=m, ignore_eos=True))
            seqs.append(engine.sequences[sid])
        late_added = False
        for _ in range(500):
            engine.step()
            if (not late_added
                    and seqs[0].state == SequenceState.FINISHED):
                sid = engine.add_request(base[:20] * 2, SamplingParams(
                    temperature=0.0, max_tokens=10, ignore_eos=True))
                seqs.append(engine.sequences[sid])
                late_added = True
            if late_added and not engine.has_work():
                break
        assert late_added and not engine.has_work()
        return [list(s.output_token_ids) for s in seqs]

    composed = _engine(unified=True, kv_dtype=kv_dtype,
                       speculative_k=3)
    expected = run(composed)
    ragged = _engine(unified=True, kv_dtype=kv_dtype,
                     unified_impl="pallas_ragged-interpret",
                     speculative_k=3)
    got = run(ragged)
    assert got == expected
    # The run genuinely mixed AND drafted — both engines — and the
    # fused kernel genuinely served the unified phase (observatory
    # one-hot, the vllm:engine_attention_impl{phase="unified"} value).
    for eng in (composed, ragged):
        assert eng.metrics.ragged_steps_total > 0
        assert eng.stats()["spec_decode_num_draft_tokens_total"] > 0
    impls = ragged.runner.observatory.attention_impls()
    assert impls["unified"] == "pallas_ragged-interpret"
    assert composed.runner.observatory.attention_impls()[
        "unified"] == "xla"


def test_spec_decode_under_async_mixed():
    """speculative_k x async_scheduling is a dissolved rule: verify
    steps reconcile through the assume-1 stale-drop path
    (docs/unified_step.md section 'spec under async'). Greedy output
    must stay byte-identical to the plain synchronous loop."""
    # Repetitive prompts so the ngram proposer actually drafts, and a
    # late-admitted request: its prefill is a pipeline break, and the
    # re-plan after a break is where the async loop consults the
    # proposer (mid-chain ahead-dispatches never speculate).
    base = [3, 9, 27, 9] * 14
    prompts = [base, base[:24] * 2, list(reversed(base))]
    max_tokens = [14, 26, 20]

    def run(engine):
        seqs = []
        for p, m in zip(prompts, max_tokens):
            sid = engine.add_request(p, SamplingParams(
                temperature=0.0, max_tokens=m, ignore_eos=True))
            seqs.append(engine.sequences[sid])
        late_added = False
        for _ in range(500):
            engine.step()
            if (not late_added
                    and seqs[0].state == SequenceState.FINISHED):
                sid = engine.add_request(base[:20] * 2, SamplingParams(
                    temperature=0.0, max_tokens=10, ignore_eos=True))
                seqs.append(engine.sequences[sid])
                late_added = True
            if late_added and not engine.has_work():
                break
        assert late_added and not engine.has_work()
        return [list(s.output_token_ids) for s in seqs]

    expected = run(_engine())
    eng = _engine(unified=True, async_on=True, speculative_k=3)
    got = run(eng)
    assert got == expected
    st = eng.stats()
    assert st["spec_decode_num_draft_tokens_total"] > 0
    # Mixed ragged dispatch and speculation coexisted in one run.
    assert eng.metrics.ragged_steps_total > 0
    # The pipeline engaged around the verify steps rather than
    # degrading to fully synchronous stepping.
    assert eng.metrics.pipeline_ahead_steps_total > 0
    assert eng._in_flight is None


def test_mixed_run_zero_recompiles():
    """After one warm mixed staggered-admission run, a second one
    (fresh token values, same ~50-step shape) must add zero compiled
    executables: every ragged width buckets into the fixed shape
    lattice, so staggered admission cannot trigger recompilation."""
    engine = _engine(unified=True)
    # Warm both pure-prefill buckets (a 48-token prompt prefills as a
    # 32-chunk then a 16-chunk) and the decode step: the scheduler's
    # prefill/decode alternation phase carries across runs, so run 2
    # may legitimately hit a bimodal bucket run 1 skipped — those
    # shapes are not what this guard is about.
    engine.add_request(list(range(2, 50)), SamplingParams(
        temperature=0.0, max_tokens=2, ignore_eos=True))
    while engine.has_work():
        engine.step()
    _run_mixed(engine, seed=7)
    ragged0 = engine.metrics.ragged_steps_total
    assert ragged0 > 0
    obs = engine.runner.observatory
    assert obs.compile_events_total() > 0  # the warm-up compiled
    before_events = obs.compile_events_total()
    before_caches = obs.executable_cache_sizes()
    _run_mixed(engine, seed=13)
    assert engine.metrics.ragged_steps_total > ragged0
    assert obs.compile_events_total() == before_events
    assert obs.executable_cache_sizes() == before_caches


def test_mixed_run_zero_recompiles_with_ragged_kernel():
    """The recompile guard with the fused ragged kernel active: the
    kernel's [rows_pad, d_pad] padding and descriptor prefetch are
    functions of the (row bucket, W bucket) pair only, so repeated
    mixed runs must add zero compiled executables."""
    engine = _engine(unified=True,
                     unified_impl="pallas_ragged-interpret")
    engine.add_request(list(range(2, 50)), SamplingParams(
        temperature=0.0, max_tokens=2, ignore_eos=True))
    while engine.has_work():
        engine.step()
    _run_mixed(engine, seed=7)
    ragged0 = engine.metrics.ragged_steps_total
    assert ragged0 > 0
    obs = engine.runner.observatory
    before_events = obs.compile_events_total()
    before_caches = obs.executable_cache_sizes()
    _run_mixed(engine, seed=13)
    assert engine.metrics.ragged_steps_total > ragged0
    assert obs.compile_events_total() == before_events
    assert obs.executable_cache_sizes() == before_caches


def test_finish_mid_ragged_batch_no_page_leak():
    """A row that hits max_tokens inside a ragged batch (its final
    decode token sampled in the same dispatch that prefills another
    request's chunk) must return every page once the run drains."""
    engine = _engine(unified=True)
    free0 = engine.cache_manager.num_free_pages
    sid_a = engine.add_request([7, 11, 13] * 8, SamplingParams(
        temperature=0.0, max_tokens=20, ignore_eos=True))
    seq_a = engine.sequences[sid_a]
    # Decode A down to its last few tokens, then admit an 80-token
    # prompt: its 3 chunks ride the next ragged steps, so A's finish
    # lands inside one of them.
    for _ in range(100):
        engine.step()
        if len(seq_a.output_token_ids) >= 17:
            break
    assert seq_a.state == SequenceState.RUNNING
    engine.add_request(_prompts()[2], SamplingParams(
        temperature=0.0, max_tokens=8, ignore_eos=True))
    finished_in_ragged = False
    for _ in range(200):
        ragged_before = engine.metrics.ragged_steps_total
        engine.step()
        stepped_ragged = (
            engine.metrics.ragged_steps_total > ragged_before)
        if (stepped_ragged and seq_a.state == SequenceState.FINISHED
                and not finished_in_ragged):
            finished_in_ragged = True
        if not engine.has_work():
            break
    assert not engine.has_work()
    assert seq_a.state == SequenceState.FINISHED
    assert finished_in_ragged
    assert engine.cache_manager.num_free_pages == free0


def test_dissolved_exclusivity_rules():
    """The three rules dissolved by the unified step
    (docs/unified_step.md section 'dissolved rules') now construct —
    and the prefill-role x speculation rule still fires."""
    EngineConfig(scheduler=SchedulerConfig(async_scheduling=True,
                                           decode_steps=4))
    EngineConfig(scheduler=SchedulerConfig(async_scheduling=True,
                                           speculative_k=4))
    EngineConfig(engine_role="prefill",
                 scheduler=SchedulerConfig(async_scheduling=True))
    with pytest.raises(ValueError, match="engine_role"):
        EngineConfig(engine_role="prefill",
                     scheduler=SchedulerConfig(speculative_k=2))


def test_eligibility_and_server_resolution():
    from production_stack_tpu.engine.model_runner import (
        unified_step_eligible,
    )
    assert unified_step_eligible()
    # pp and cp runners execute the ragged [R, W] block natively
    # (docs/parallelism.md), so neither disqualifies any more.
    assert unified_step_eligible(pipeline_parallel=4)
    assert unified_step_eligible(context_parallel=8)
    assert not unified_step_eligible(distributed=True)
    assert not unified_step_eligible(engine_role="prefill")
    assert not unified_step_eligible(engine_role="decode")

    from production_stack_tpu.engine.server import (
        _resolve_unified_step,
        parse_args,
    )
    assert _resolve_unified_step(parse_args([]))
    assert not _resolve_unified_step(parse_args(["--unified-step", "off"]))
    assert _resolve_unified_step(
        parse_args(["--unified-step", "on", "--distributed"]))
    assert not _resolve_unified_step(parse_args(["--distributed"]))
    assert _resolve_unified_step(
        parse_args(["--pipeline-parallel-size", "4"]))
    assert not _resolve_unified_step(
        parse_args(["--engine-role", "prefill"]))


def test_ragged_metrics_rendered_and_scraped():
    from production_stack_tpu.engine.metrics import EngineMetrics
    m = EngineMetrics()
    m.on_ragged_step(prefill_rows=2, decode_rows=3, pad_rows=11)
    text = "\n".join(m.render())
    assert "vllm:engine_step_prefill_rows 2" in text
    assert "vllm:engine_step_decode_rows 3" in text
    assert "vllm:engine_step_pad_rows 11" in text
    assert "vllm:engine_ragged_steps_total 1" in text
    assert "vllm:engine_ragged_rows_total 16" in text
    assert "vllm:engine_ragged_pad_rows_total 11" in text
    from production_stack_tpu.router.stats.engine_stats import (
        EngineStats,
    )
    stats = EngineStats.from_prometheus_text(text + "\n")
    assert stats.engine_step_prefill_rows == 2.0
    assert stats.engine_step_decode_rows == 3.0
    assert stats.engine_step_pad_rows == 11.0
    assert stats.engine_ragged_steps == 1.0
    assert stats.engine_ragged_rows == 16.0
    assert stats.engine_ragged_pad_rows == 11.0


# ---- unified step on the pp / cp runners (docs/parallelism.md) ---------


def _parallel_engine(unified, pp=1, sp=1, kv_dtype="auto",
                     **sched_kw):
    """Engine on a (pp) or (sp) mesh over the virtual 8-device CPU
    harness (tests/conftest.py); pp needs layers % stages == 0."""
    from production_stack_tpu.engine.config import ParallelConfig
    from production_stack_tpu.parallel.mesh import build_mesh

    model = tiny_model_config("llama")
    model.num_hidden_layers = 4  # divisible by pp=2
    config = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_pages=128,
                          kv_cache_dtype=kv_dtype),
        scheduler=SchedulerConfig(max_num_seqs=4,
                                  max_model_len=256,
                                  prefill_chunk_size=32,
                                  unified_step=unified,
                                  **sched_kw),
        parallel=ParallelConfig(
            pipeline_parallel_size=pp,
            context_parallel_size=sp,
            long_prefill_threshold=64 if sp > 1 else None,
        ),
    )
    mesh = (build_mesh(pipeline_parallel_size=pp,
                       context_parallel_size=sp)
            if pp > 1 or sp > 1 else None)
    return LLMEngine(config, mesh=mesh)


@pytest.mark.parametrize("kv_dtype", ["auto", "int8"])
def test_greedy_parity_bimodal_vs_unified_pp(kv_dtype):
    """pp=2: the mixed staggered run through the staged ragged
    program is byte-identical to the bimodal pp scheduler — the
    dissolved int8 x pp rule rides the same congruent QuantKV specs."""
    bimodal = _parallel_engine(False, pp=2, kv_dtype=kv_dtype,
                               speculative_k=3)
    expected = _run_mixed(bimodal)
    unified = _parallel_engine(True, pp=2, kv_dtype=kv_dtype,
                               speculative_k=3)
    got = _run_mixed(unified)
    assert got == expected
    assert [len(t) for t in got] == _MAX_TOKENS
    assert unified.metrics.ragged_steps_total > 0
    assert bimodal.metrics.ragged_steps_total == 0


@pytest.mark.parametrize("kv_dtype", ["auto", "int8"])
def test_greedy_parity_bimodal_vs_unified_cp(kv_dtype):
    """cp=2: multi-token unified dispatches shard their W axis over
    sp (a parallel query axis — no numeric change), so the greedy
    stream matches the bimodal cp engine byte for byte."""
    bimodal = _parallel_engine(False, sp=2, kv_dtype=kv_dtype,
                               speculative_k=3)
    expected = _run_mixed(bimodal)
    unified = _parallel_engine(True, sp=2, kv_dtype=kv_dtype,
                               speculative_k=3)
    got = _run_mixed(unified)
    assert got == expected
    assert [len(t) for t in got] == _MAX_TOKENS
    assert unified.metrics.ragged_steps_total > 0
    assert bimodal.metrics.ragged_steps_total == 0


def test_pp_mixed_run_zero_recompiles():
    """The row-bucket lattice holds on the pp runner: a second mixed
    staggered run (fresh token values, same step shape) adds zero
    compiled executables — ragged microbatching through the ppermute
    ring reuses the same staged programs."""
    engine = _parallel_engine(True, pp=2)
    engine.add_request(list(range(2, 50)), SamplingParams(
        temperature=0.0, max_tokens=2, ignore_eos=True))
    while engine.has_work():
        engine.step()
    _run_mixed(engine, seed=7)
    ragged0 = engine.metrics.ragged_steps_total
    assert ragged0 > 0
    obs = engine.runner.observatory
    assert obs.compile_events_total() > 0
    before_events = obs.compile_events_total()
    before_caches = obs.executable_cache_sizes()
    _run_mixed(engine, seed=13)
    assert engine.metrics.ragged_steps_total > ragged0
    assert obs.compile_events_total() == before_events
    assert obs.executable_cache_sizes() == before_caches
