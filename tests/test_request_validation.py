"""Sampling-parameter validation + per-request seed integrity.

The reference's capability here is vLLM's request validation: out-of-
range OpenAI sampling params are rejected with HTTP 400 rather than
reaching the device (where e.g. repetition_penalty=0 divides logits
into NaN and returns garbage with a 200). These are pure-function
tests — no engine, no compile.
"""

import numpy as np
import pytest

from production_stack_tpu.engine.sequence import SamplingParams, Sequence
from production_stack_tpu.engine.server import _sampling_from_body


def _body(**kw):
    return dict(kw)


def test_defaults_pass():
    p = _sampling_from_body(_body(), 2048)
    assert p.temperature == 1.0 and p.top_p == 1.0
    assert p.repetition_penalty == 1.0


@pytest.mark.parametrize("body", [
    {"repetition_penalty": 0},
    {"repetition_penalty": -1.5},
    {"presence_penalty": 2.5},
    {"presence_penalty": -2.5},
    {"frequency_penalty": 3},
    {"frequency_penalty": -2.01},
    {"top_p": 0},
    {"top_p": -0.5},
    {"top_p": 1.5},
    {"temperature": -0.1},
    {"temperature": 2.5},
    {"top_k": -2},
    {"max_tokens": 0},
    {"logprobs": True, "top_logprobs": 21},
])
def test_out_of_range_raises(body):
    with pytest.raises((ValueError, TypeError)):
        _sampling_from_body(body, 2048)


@pytest.mark.parametrize("body", [
    {"repetition_penalty": 1.3},
    {"presence_penalty": 2.0},
    {"presence_penalty": -2.0},
    {"frequency_penalty": -2.0},
    {"top_p": 1.0},
    {"top_p": 0.01},
    {"temperature": 0},
    {"temperature": 2.0},
    {"top_k": 0},
    {"logprobs": True, "top_logprobs": 20},
])
def test_boundary_values_accepted(body):
    _sampling_from_body(body, 2048)


def test_top_logprobs_20_served_at_full_width():
    # OpenAI allows up to 20 alternatives; the compiled width must not
    # silently truncate a legal request (round-3 advisor finding).
    from production_stack_tpu.engine.model_runner import (
        TOP_LOGPROBS_WIDTH,
    )
    assert TOP_LOGPROBS_WIDTH >= 20
    p = _sampling_from_body({"logprobs": True, "top_logprobs": 20}, 2048)
    assert p.top_logprobs == 20


def _seed_payload(seeds_list):
    from production_stack_tpu.engine.model_runner import ModelRunner
    seqs = []
    for s in seeds_list:
        seq = Sequence(
            seq_id=f"s{len(seqs)}",
            sampling=SamplingParams(max_tokens=4, seed=s),
            prompt_token_ids=[1, 2],
        )
        seqs.append(seq)
    return ModelRunner._seed_payload(None, seqs, len(seqs))


def test_distinct_seeds_never_collide_on_device():
    # Round-3 advisor finding: the 31-bit XOR fold mapped seed=1 and
    # seed=0x80000001 to the same device value. The payload now
    # carries the full 32 bits plus a separate seeded mask.
    payload = _seed_payload([1, 0x80000001, None])
    rows = payload["seed_rows"]
    on = payload["seed_on"]
    assert rows[0] != rows[1]
    assert bool(on[0]) and bool(on[1]) and not bool(on[2])
    # Full 32-bit round trip: the int32 view re-interprets to the
    # original user seed.
    assert int(np.uint32(rows[1])) == 0x80000001


def test_seeded_rows_reproduce_and_differ_by_seed():
    import jax
    import jax.numpy as jnp

    from production_stack_tpu.ops.sampling import sample_tokens

    logits = jnp.asarray(
        np.random.RandomState(0).randn(2, 64).astype(np.float32))
    kw = dict(
        temperature=jnp.ones(2), top_p=jnp.ones(2),
        top_k=jnp.zeros(2, jnp.int32), emitted=jnp.zeros(2, jnp.int32),
    )

    def draw(seed_pair, engine_key):
        seeds = np.asarray(seed_pair, np.uint32).view(np.int32)
        return np.asarray(sample_tokens(
            logits, key=jax.random.PRNGKey(engine_key),
            seeds=jnp.asarray(seeds),
            seed_mask=jnp.ones(2, bool), **kw))

    # Same seeds reproduce regardless of the engine's key stream.
    np.testing.assert_array_equal(draw([7, 7], 0), draw([7, 7], 123))
    # The colliding pair from the advisor finding now draws from
    # distinct streams: over several emitted indices the sequences
    # must diverge somewhere.
    diverged = False
    for e in range(8):
        kw["emitted"] = jnp.full(2, e, jnp.int32)
        x = draw([1, 0x80000001], 0)
        if x[0] != x[1]:
            diverged = True
            break
    assert diverged, "seeds 1 and 0x80000001 still collide"
