"""Device performance observatory (docs/observability.md): compile
ledger exactly-once semantics under shape perturbation, HBM ledger
page-math invariants for bf16 and int8 KV, useful-token MFU
arithmetic, zero-overhead byte parity with the observatory removed,
the /debug/compiles + /debug/memory endpoint matrix, the profiler
start/stop guard with span events, the engine /metrics exposition and
its router scrape/re-export round trip, and benchcompare exit codes.
"""

import asyncio
import json

import jax
import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.benchcompare import main as benchcompare_main
from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.perf_observatory import (
    PerfObservatory,
    resolve_peak_flops,
)
from production_stack_tpu.engine.sequence import SamplingParams
from production_stack_tpu.engine.server import EngineServer
from production_stack_tpu.engine.tracing import EngineTracer


def _engine(kv_dtype="auto", **sched_kw):
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=128,
                          kv_cache_dtype=kv_dtype),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256,
                                  prefill_chunk_size=32, **sched_kw),
    )
    return LLMEngine(config)


def _run(engine, prompt, max_tokens=4):
    sid = engine.add_request(list(prompt), SamplingParams(
        temperature=0.0, max_tokens=max_tokens, ignore_eos=True))
    seq = engine.sequences[sid]
    while engine.has_work():
        engine.step()
    return list(seq.output_token_ids)


# ---- peak-FLOPs resolution ------------------------------------------------


def test_resolve_peak_flops():
    # Explicit override always wins.
    assert resolve_peak_flops("TPU v5e", 123.0) == 123.0
    # Prefix match against the device-kind table.
    assert resolve_peak_flops("TPU v4") == 275e12
    assert resolve_peak_flops("TPU v5 lite") == 197e12
    # Unknown devices (including CPU) report an honest 0.
    assert resolve_peak_flops("cpu") == 0.0
    assert resolve_peak_flops(None) == 0.0


def test_mfu_arithmetic():
    engine = _engine()
    obs = engine.runner.observatory
    # Unknown device on CPU: MFU must be 0, never a guess.
    assert obs.peak_flops == 0.0
    _run(engine, range(2, 12))
    assert obs.mfu() == 0.0
    # Pin the peak so the quotient is exact: 2 * params * tokens
    # FLOPs over device-seconds over peak.
    obs.peak_flops = 1e9
    expected = (2.0 * obs.param_count * obs.tokens_total
                / obs.device_seconds_total / 1e9)
    assert obs.mfu() == pytest.approx(expected)
    assert obs.tokens_total > 0 and obs.device_seconds_total > 0


# ---- compile ledger -------------------------------------------------------


def test_compile_ledger_first_run_then_stable():
    engine = _engine()
    obs = engine.runner.observatory
    # Registered at wrap time: the gauge exists at 0 pre-dispatch.
    assert obs.compile_events_total("step") == 0
    out1 = _run(engine, range(2, 12))
    assert len(out1) == 4
    first = obs.compile_events_total("step")
    assert first > 0
    assert sum(obs.compile_seconds_by_kind().values()) > 0
    assert obs.executable_cache_sizes()["step"] >= first
    for entry in obs.recent_compiles():
        assert entry["kind"] == "step"
        assert entry["seconds"] >= 0
        assert entry["cache_size"] >= 1
        assert isinstance(entry["key"], list)
    # Same shapes again: a warm engine must not compile.
    _run(engine, range(30, 40))
    assert obs.compile_events_total("step") == first


def test_dispatch_timing_fold_in(monkeypatch):
    """Under PSTPU_TIMING the per-dispatch walls fold into the
    observatory's ledger (served by /debug/compiles), not just the
    stderr log."""
    from production_stack_tpu.engine import model_runner
    monkeypatch.setattr(model_runner, "_TIMING", True)
    engine = _engine()
    obs = engine.runner.observatory
    _run(engine, range(2, 12))
    timings = obs.dispatch_timings()
    assert timings["prefill"]["count"] >= 1
    assert timings["decode"]["count"] >= 1
    assert all(t["wall_seconds"] > 0 for t in timings.values())


def test_shape_perturbation_compiles_exactly_once():
    """A prompt that crosses into the next W bucket (16 -> 32) adds
    exactly one compile event, and the ledger records the shape key
    that triggered it."""
    engine = _engine()
    obs = engine.runner.observatory
    _run(engine, range(2, 12))  # 10 tokens: the W=16 prefill bucket
    warm = obs.compile_events_total("step")
    _run(engine, range(2, 22))  # 20 tokens: first W=32 prefill
    assert obs.compile_events_total("step") == warm + 1
    newest = obs.recent_compiles()[-1]
    assert newest["kind"] == "step"
    assert newest["key"][-1] == 32


def test_observatory_none_is_passthrough_byte_identical():
    """Removing the observatory flips every hook to its no-op branch;
    greedy output must stay byte-identical (zero-overhead contract)."""
    plain = _engine()
    expected = _run(plain, range(2, 20), max_tokens=8)
    bare = _engine()
    bare.runner.observatory = None
    got = _run(bare, range(2, 20), max_tokens=8)
    assert got == expected
    assert len(got) == 8


# ---- HBM memory ledger ----------------------------------------------------


def test_hbm_ledger_bf16_invariants():
    engine = _engine(kv_dtype="auto")
    obs = engine.runner.observatory
    cfg = engine.config
    hbm = obs.hbm_bytes()
    leaves = jax.tree_util.tree_leaves(engine.runner.params)
    assert hbm["weights"] == sum(int(x.nbytes) for x in leaves)
    # Full-precision KV: no scale tensors, and the page bytes equal
    # the config's own per-token accounting exactly.
    assert hbm["kv_scales"] == 0
    assert hbm["kv_pages"] == (
        cfg.cache.num_pages * cfg.cache.page_size
        * cfg.cache.kv_bytes_per_token(cfg.model))
    assert hbm["step_buffers"] > 0
    report = obs.memory_report()
    assert report["total_analytic_bytes"] == sum(hbm.values())
    assert report["kv_cache_dtype"] == "bf16"


def test_hbm_ledger_int8_exact_page_math():
    engine = _engine(kv_dtype="int8")
    obs = engine.runner.observatory
    cfg = engine.config
    model = cfg.model
    hbm = obs.hbm_bytes()
    slots = 2 * model.num_hidden_layers * model.num_key_value_heads
    tokens = cfg.cache.num_pages * cfg.cache.page_size
    assert hbm["kv_pages"] == slots * tokens * model.head_dim
    assert hbm["kv_scales"] == slots * tokens * 4
    # pages + scales is exactly the post-expansion slot budget.
    assert hbm["kv_pages"] + hbm["kv_scales"] == (
        tokens * cfg.cache.kv_bytes_per_token(model))
    # int8 capacity expansion actually happened and the ledger sees
    # the expanded page count.
    full_slot = model.head_dim * jnp.dtype(model.jax_dtype).itemsize
    assert cfg.cache.num_pages == max(
        128 * full_slot // (model.head_dim + 4), 128)
    assert obs.memory_report()["num_pages"] == cfg.cache.num_pages


# ---- debug endpoints + profiler guard -------------------------------------


def _server(engine=None):
    return EngineServer(engine or _engine(), "tiny-llama")


async def _with_client(server, fn):
    client = TestClient(TestServer(server.build_app()))
    await client.start_server()
    try:
        await fn(client)
    finally:
        await client.close()


def test_debug_endpoint_matrix():
    engine = _engine()
    _run(engine, range(2, 12))
    server = _server(engine)

    async def run(client):
        resp = await client.get("/debug/compiles")
        assert resp.status == 200
        data = await resp.json()
        assert data["events"]["step"] > 0
        assert data["executable_cache_sizes"]["step"] >= 1
        assert data["recent"] and "timings" in data
        resp = await client.get("/debug/compiles?limit=1")
        assert len((await resp.json())["recent"]) == 1
        assert (await client.get(
            "/debug/compiles?limit=nope")).status == 400
        resp = await client.get("/debug/memory")
        assert resp.status == 200
        mem = await resp.json()
        assert mem["analytic"]["weights"] > 0
        assert mem["total_analytic_bytes"] == sum(
            mem["analytic"].values())
    asyncio.run(_with_client(server, run))


def test_debug_endpoints_404_without_observatory():
    engine = _engine()
    engine.runner.observatory = None
    server = _server(engine)

    async def run(client):
        for path in ("/debug/compiles", "/debug/memory"):
            resp = await client.get(path)
            assert resp.status == 404
            assert "observatory" in (
                await resp.json())["error"]["message"]
    asyncio.run(_with_client(server, run))


def test_profiler_start_stop_guard_and_spans(monkeypatch):
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda trace_dir: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    engine = _engine()
    engine.tracer = EngineTracer(ring_size=8)
    server = _server(engine)

    async def run(client):
        # Double-stop before any capture: honest 409.
        assert (await client.post("/debug/profiler/stop")).status == 409
        resp = await client.post("/debug/profiler/start?dir=/tmp/t")
        assert resp.status == 200
        assert (await resp.json())["dir"] == "/tmp/t"
        # Single-capture guard.
        assert (await client.post(
            "/debug/profiler/start")).status == 409
        assert (await client.post("/debug/profiler/stop")).status == 200
        assert (await client.post("/debug/profiler/stop")).status == 409
        # The capture window is span-evented into the flight recorder.
        span = list(engine.tracer._ring)[-1]
        names = [e["event"] for e in span.events]
        assert "profiler_start" in names and "profiler_stop" in names
        assert span.seq_id.startswith("prof-")
    asyncio.run(_with_client(server, run))


# ---- /metrics exposition + router round trip ------------------------------


def test_metrics_exposition_and_router_roundtrip():
    engine = _engine()
    _run(engine, range(2, 12))
    server = _server(engine)
    text_holder = {}

    async def run(client):
        resp = await client.get("/metrics")
        assert resp.status == 200
        text_holder["text"] = await resp.text()
    asyncio.run(_with_client(server, run))
    text = text_holder["text"]
    for needle in (
        'vllm:engine_compile_events_total{kind="step"}',
        'vllm:engine_compile_seconds_total{kind="step"}',
        'vllm:engine_executable_cache_size{kind="step"}',
        'vllm:engine_hbm_bytes{category="weights"}',
        'vllm:engine_step_device_seconds_total{kind="prefill"}',
        "vllm:engine_mfu",
        'vllm:engine_attention_impl{phase="decode"',
    ):
        assert needle in text, needle

    from production_stack_tpu.router.stats.engine_stats import (
        EngineStats,
        initialize_engine_stats_scraper,
    )
    es = EngineStats.from_prometheus_text(text)
    assert es.compile_events_by_kind["step"] > 0
    assert es.executable_cache_size_by_kind["step"] >= 1
    assert es.hbm_bytes_by_category["weights"] > 0
    assert es.step_device_seconds_by_kind["prefill"] > 0
    assert es.engine_mfu == 0.0  # CPU: honest zero
    assert es.attention_impl_by_phase["decode"]

    # Router re-export: the scraped stats surface as per-server gauges.
    from production_stack_tpu.router.services import metrics_service
    from production_stack_tpu.router.stats.request_stats import (
        initialize_request_stats_monitor,
    )
    initialize_request_stats_monitor(60.0)
    scraper = initialize_engine_stats_scraper(scrape_interval=3600.0)
    try:
        with scraper._lock:
            scraper._stats = {"http://e1:8000": es}
        metrics_service.refresh_gauges()
        g = metrics_service.engine_compile_events
        assert g.labels(server="http://e1:8000",
                        kind="step")._value.get() > 0
        g = metrics_service.engine_hbm_bytes
        assert g.labels(server="http://e1:8000",
                        category="weights")._value.get() > 0
        g = metrics_service.engine_attention_impl
        impl = es.attention_impl_by_phase["decode"]
        assert g.labels(server="http://e1:8000", phase="decode",
                        impl=impl)._value.get() == 1.0
    finally:
        scraper.close()


# ---- benchcompare ---------------------------------------------------------


def _bench_record(req_per_s, compile_events, mfu):
    return {"metric": "bench_tiny", "value": req_per_s,
            "unit": "req/s",
            "extra": {"compile_events": {"step": compile_events},
                      "observatory_mfu": mfu,
                      "hbm_bytes": {"weights": 1048576}}}


def test_benchcompare_exit_codes(tmp_path, capsys):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_bench_record(10.0, 5, 0.4)))
    # Identical runs: exit 0.
    new.write_text(json.dumps(_bench_record(10.0, 5, 0.4)))
    assert benchcompare_main([str(old), str(new)]) == 0
    # Throughput regression beyond the 5% default: exit 1.
    new.write_text(json.dumps(_bench_record(8.0, 5, 0.4)))
    assert benchcompare_main([str(old), str(new)]) == 1
    # A compile storm is a regression even with throughput flat.
    new.write_text(json.dumps(_bench_record(10.0, 50, 0.4)))
    assert benchcompare_main([str(old), str(new)]) == 1
    # ...unless it is inside the caller's threshold.
    assert benchcompare_main(
        [str(old), str(new), "--threshold", "20"]) == 0
    # MFU going up is an improvement, not a regression.
    new.write_text(json.dumps(_bench_record(10.0, 5, 0.8)))
    assert benchcompare_main([str(old), str(new)]) == 0
    capsys.readouterr()
