"""Real-HF-checkpoint serving under dp/tp sharding: logit parity.

Round-3 verdict gap: every multi-device leg ran random graft weights
("Initializing random weights" in MULTICHIP_r03.json), so sharded
serving was validated for plumbing but never for numerics of an actual
checkpoint loaded through the weights path. Here a real HF Llama
checkpoint (safetensors on disk — the same format as
meta-llama/Meta-Llama-3-8B) is loaded once, then served single-device
and under tp=2 and dp=2 x tp=2 meshes; greedy tokens and prompt logits
must agree.
"""

import numpy as np
import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams
from production_stack_tpu.engine.weights import (
    load_model_config,
    load_weights,
)

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(7)
    config = LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,  # GQA: tp=2 shards 1 kv head per device
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    model = LlamaForCausalLM(config)
    model.eval()
    path = str(tmp_path_factory.mktemp("ckpt") / "tiny_llama")
    model.save_pretrained(path)
    return path


def _serve(path, mesh, prompts):
    model_config = load_model_config(path)
    params = load_weights(path, model_config)
    config = EngineConfig(
        model=model_config,
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32,
                                  prefill_batch_size=2),
    )
    engine = LLMEngine(config, mesh=mesh, params=params)
    seqs = []
    for p in prompts:
        sid = engine.add_request(
            p, SamplingParams(max_tokens=8, temperature=0.0,
                              ignore_eos=True))
        seqs.append(engine.sequences[sid])
    while engine.has_work():
        engine.step()
    return [s.output_token_ids for s in seqs]


def test_tp_and_dp_serve_real_checkpoint_identically(checkpoint):
    from production_stack_tpu.parallel.mesh import build_mesh
    rs = np.random.RandomState(3)
    prompts = [[int(x) for x in rs.randint(1, 127, size=n)]
               for n in (9, 21)]

    base_tokens = _serve(checkpoint, None, prompts)
    assert all(len(t) == 8 for t in base_tokens)

    tp_tokens = _serve(
        checkpoint, build_mesh(tensor_parallel_size=2), prompts)
    assert tp_tokens == base_tokens

    dptp_tokens = _serve(
        checkpoint,
        build_mesh(tensor_parallel_size=2, data_parallel_size=2),
        prompts)
    assert dptp_tokens == base_tokens
