"""Crash-safe serving (docs/crash_recovery.md).

Covers the whole failure-domain story: the router's mid-stream
failover (kill an engine mid-greedy-stream, the client's concatenated
SSE bytes match an uninterrupted run), the real engine's checkpoint
ship + /v1/resume restore (bf16 and int8 KV, hit and miss-recompute
paths), honest terminal errors when no checkpoint exists, poison-
request quarantine after repeated crashes, the step watchdog flipping
/health, and the fleet manager's crash-loop containment (jittered
exponential backoff, per-pool breaker, crash vs drain-exit).

Fast lane: fake engines only (crash fakes run as subprocesses — the
crash fault SIGKILLs its whole process). The real-engine parity tests
build LLMEngines and ride the slow lane.
"""

import asyncio
import json
import socket
import subprocess
import sys
import time
from types import SimpleNamespace

import aiohttp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.fleet.manager import FleetManager, LIVE
from production_stack_tpu.fleet.spec import FleetSpec, PoolSpec
from production_stack_tpu.router.resilience import (
    ResilienceConfig,
    initialize_resilience,
)
from production_stack_tpu.router.service_discovery import (
    initialize_service_discovery,
)
from production_stack_tpu.router.services import request_service
from production_stack_tpu.router.services.metrics_service import (
    fleet_crash_respawns,
)
from production_stack_tpu.router.services.rewriter import (
    initialize_request_rewriter,
)
from production_stack_tpu.router.stats.engine_stats import (
    initialize_engine_stats_scraper,
)
from production_stack_tpu.router.stats.request_stats import (
    initialize_request_stats_monitor,
)
from production_stack_tpu.testing.fake_engine import build_fake_engine


# ---- shared helpers -------------------------------------------------------

def _free_ports(n: int):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    # Roundrobin sorts endpoints lexicographically by URL: hand back
    # the ports in that order so tests control who gets request #1.
    return sorted(ports, key=str)


def _chat_body(model="m1", stream=False, max_tokens=3):
    return {
        "model": model,
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": max_tokens,
        "stream": stream,
    }


def _sse_contents(text: str):
    """Delta contents of an SSE chat stream, in order."""
    contents = []
    for line in text.splitlines():
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        event = json.loads(line[len("data: "):])
        if "choices" not in event:  # terminal in-band error event
            continue
        choice = event["choices"][0]
        delta = choice.get("delta") or {}
        if delta.get("content"):
            contents.append(delta["content"])
    return contents


def _spawn_fake(port: int, *extra: str) -> subprocess.Popen:
    """A fake engine in its own process: the crash fault SIGKILLs the
    whole process, so an in-process fake would kill the test runner."""
    argv = [sys.executable, "-m",
            "production_stack_tpu.testing.fake_engine",
            "--host", "127.0.0.1", "--port", str(port),
            "--model", "m1", "--ttft", "0.0", "--speed", "200",
            *extra]
    return subprocess.Popen(argv, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


async def _wait_up(url: str, deadline_s: float = 15.0) -> None:
    deadline = time.monotonic() + deadline_s
    async with aiohttp.ClientSession() as session:
        while time.monotonic() < deadline:
            try:
                async with session.get(url + "/health") as resp:
                    if resp.status in (200, 503):
                        return
            except Exception:
                pass
            await asyncio.sleep(0.05)
    raise AssertionError(f"fake engine at {url} never came up")


def _reap(*procs: subprocess.Popen) -> None:
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)


async def _start_router(urls) -> TestClient:
    """Router singletons over *urls* (all model m1, role both), with
    the crash-recovery counters reset."""
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.routing.logic import (
        initialize_routing_logic,
    )
    request_service.stream_resumes_by_outcome.clear()
    request_service.poison_quarantines_total = 0
    request_service._poison_crashes.clear()
    initialize_service_discovery(
        "static", urls=list(urls), models=["m1"] * len(urls))
    initialize_request_stats_monitor(60.0)
    initialize_engine_stats_scraper(3600.0)
    initialize_routing_logic("roundrobin")
    initialize_request_rewriter("noop")
    initialize_resilience(ResilienceConfig(
        max_retries=2, backend_connect_timeout=1.0, backend_timeout=10.0,
        health_check_interval=0.0,
    ))
    client = TestClient(TestServer(build_app()))
    await client.start_server()
    return client


# ---- router chaos E2E: mid-stream failover --------------------------------

async def test_router_resumes_crashed_stream_byte_identical():
    """The acceptance kill test: the engine serving a greedy stream is
    SIGKILLed mid-generation; the router resumes it from the last
    checkpoint on the surviving replica and the client's concatenated
    stream is byte-identical to an uninterrupted run — same deltas,
    same response id, one role chunk, no leaked checkpoint frames, no
    client-visible error."""
    n = 10
    crash_port, ok_port = _free_ports(2)
    crash = _spawn_fake(crash_port, "--fault", "crash",
                        "--checkpoint-interval-tokens", "2",
                        "--crash-after-tokens", "4")
    ok = _spawn_fake(ok_port, "--checkpoint-interval-tokens", "2")
    crash_url = f"http://127.0.0.1:{crash_port}"
    ok_url = f"http://127.0.0.1:{ok_port}"
    router = None
    try:
        await _wait_up(crash_url)
        await _wait_up(ok_url)
        router = await _start_router([crash_url, ok_url])

        resp = await router.post(
            "/v1/chat/completions",
            json=_chat_body(stream=True, max_tokens=n))
        assert resp.status == 200  # never a client-visible 5xx
        text = await resp.text()

        # Byte identity with an uninterrupted run: every token exactly
        # once, in order, under the original response id.
        assert _sse_contents(text) == [f"tok{i} " for i in range(n)]
        ids = {json.loads(line[len("data: "):])["id"]
               for line in text.splitlines()
               if line.startswith("data: ") and line != "data: [DONE]"}
        assert len(ids) == 1
        roles = [line for line in text.splitlines()
                 if '"role"' in line]
        assert len(roles) == 1  # the resumed leg never re-sends it
        assert "data: [DONE]" in text
        assert "upstream_error" not in text
        # Checkpoint frames are router-internal control traffic.
        assert ": checkpoint" not in text

        # The crash fake really died (SIGKILL, not a clean finish).
        assert crash.wait(timeout=10) != 0
        assert request_service.stream_resumes_by_outcome == {
            "resumed": 1}

        # The recovery counters ride the router's /metrics.
        metrics = await (await router.get("/metrics")).text()
        assert ('vllm:stream_resumes_total{outcome="resumed"} 1.0'
                in metrics)
        assert "vllm:fleet_poison_quarantines_total 0.0" in metrics
    finally:
        if router is not None:
            await router.close()
        _reap(crash, ok)


async def test_crash_without_checkpoint_ends_with_terminal_error():
    """Checkpointing off: a mid-stream crash cannot be resumed, and
    the stream must end with an explicit in-band error event plus
    [DONE] — never a silent truncation the client could mistake for a
    completed response."""
    (port,) = _free_ports(1)
    crash = _spawn_fake(port, "--fault", "crash",
                        "--crash-after-tokens", "4")
    url = f"http://127.0.0.1:{port}"
    router = None
    try:
        await _wait_up(url)
        router = await _start_router([url])
        resp = await router.post(
            "/v1/chat/completions",
            json=_chat_body(stream=True, max_tokens=10))
        assert resp.status == 200  # headers were already streamed
        text = await resp.text()
        contents = _sse_contents(text)
        # A clean prefix of the generation, then the terminal error.
        assert contents == [f"tok{i} " for i in range(len(contents))]
        assert len(contents) <= 4
        assert '"type": "upstream_error"' in text
        assert "no resume checkpoint" in text
        assert text.rstrip().endswith("data: [DONE]")
        assert request_service.stream_resumes_by_outcome == {
            "no_checkpoint": 1}
    finally:
        if router is not None:
            await router.close()
        _reap(crash)


async def test_poison_request_quarantined_after_two_crashes():
    """A request that crashes two engines is poison: the router must
    stop resuming it (no third victim) and end the stream with a
    terminal quarantine error."""
    p_a, p_b, p_h = _free_ports(3)
    crash_a = _spawn_fake(p_a, "--fault", "crash",
                          "--checkpoint-interval-tokens", "2",
                          "--crash-after-tokens", "4")
    crash_b = _spawn_fake(p_b, "--fault", "crash",
                          "--checkpoint-interval-tokens", "2",
                          "--crash-after-tokens", "4")
    url_a = f"http://127.0.0.1:{p_a}"
    url_b = f"http://127.0.0.1:{p_b}"
    # The would-be third victim runs in-process so its state is
    # inspectable: quarantine means it is NEVER asked to resume.
    healthy = TestServer(
        build_fake_engine(model="m1", speed=200, ttft=0.0,
                          checkpoint_interval=2),
        port=p_h)
    await healthy.start_server()
    url_h = f"http://127.0.0.1:{p_h}"
    router = None
    try:
        await _wait_up(url_a)
        await _wait_up(url_b)
        router = await _start_router([url_a, url_b, url_h])

        resp = await router.post(
            "/v1/chat/completions",
            json=_chat_body(stream=True, max_tokens=12))
        assert resp.status == 200
        text = await resp.text()
        contents = _sse_contents(text)
        # Two crash legs delivered a gapless, duplicate-free prefix...
        assert contents == [f"tok{i} " for i in range(len(contents))]
        assert 4 <= len(contents) <= 8
        # ...then the honest quarantine verdict.
        assert "quarantined" in text
        assert text.rstrip().endswith("data: [DONE]")
        assert crash_a.wait(timeout=10) != 0
        assert crash_b.wait(timeout=10) != 0
        # No third retry: the healthy replica was never touched.
        assert healthy.app["state"].requests_received == 0
        assert healthy.app["state"].stream_resumes == 0
        assert request_service.poison_quarantines_total == 1
        assert request_service.stream_resumes_by_outcome == {
            "quarantined": 1}
        metrics = await (await router.get("/metrics")).text()
        assert "vllm:fleet_poison_quarantines_total 1.0" in metrics
        assert ('vllm:stream_resumes_total{outcome="quarantined"} 1.0'
                in metrics)
    finally:
        if router is not None:
            await router.close()
        await healthy.close()
        _reap(crash_a, crash_b)


# ---- step watchdog --------------------------------------------------------

async def test_fake_hang_step_flips_health_to_watchdog():
    client = TestClient(TestServer(build_fake_engine(
        model="m1", speed=200, ttft=0.0, fault="hang_step")))
    await client.start_server()
    try:
        resp = await client.get("/health")
        assert resp.status == 503
        payload = await resp.json()
        assert payload["status"] == "watchdog"
        assert payload["stuck_step_s"] > 0
        # Clearing the fault recovers the replica.
        await client.post("/fault", json={"mode": None})
        assert (await client.get("/health")).status == 200
    finally:
        await client.close()


class _StubEngine:
    """Just enough engine for EngineServer's health/watchdog surface."""

    tokenizer = None
    tracer = None

    def __init__(self, step_watchdog_s=0.0):
        self.config = SimpleNamespace(engine_role="both",
                                      step_watchdog_s=step_watchdog_s)

    def stats(self):
        return {"num_requests_running": 0, "num_requests_waiting": 0}

    def has_work(self):
        return False


def test_engine_server_watchdog_flips_health():
    """A device step exceeding --step-watchdog-s flips /health to 503
    {"status": "watchdog"}; a finished step recovers it. With the flag
    unset (0) a long step is never reported."""
    from production_stack_tpu.engine.server import EngineServer

    async def run():
        server = EngineServer(_StubEngine(step_watchdog_s=0.25), "m1")
        resp = await server.health(None)
        assert resp.status == 200

        # A step has been executing for ~1s: way past the 0.25s bound.
        server.async_engine._step_started = time.time() - 1.0
        resp = await server.health(None)
        assert resp.status == 503
        payload = json.loads(resp.body)
        assert payload["status"] == "watchdog"
        assert payload["stuck_step_s"] >= 0.9
        assert server._watchdog_tripped  # latched: logged once

        # Step finished: health recovers and the latch clears.
        server.async_engine._step_started = None
        resp = await server.health(None)
        assert resp.status == 200
        assert not server._watchdog_tripped

        # Watchdog disabled: a long step is not a trip.
        off = EngineServer(_StubEngine(step_watchdog_s=0.0), "m1")
        off.async_engine._step_started = time.time() - 60.0
        assert (await off.health(None)).status == 200

    asyncio.run(run())


# ---- fleet crash-loop containment -----------------------------------------

def _gauge_value(pool: str) -> float:
    return fleet_crash_respawns.labels(pool=pool)._value.get()


async def test_crash_loop_backoff_and_breaker():
    """A pool whose replicas die instantly must not fork-storm the
    host: respawns back off exponentially (jittered downward), the
    per-pool breaker opens after crash_loop_threshold crashes in the
    window, and respawning restarts once the window cools."""
    t = [1000.0]
    base = _free_ports(1)[0]
    spec = FleetSpec(
        pools=[PoolSpec(
            name="doomed", min_replicas=1, max_replicas=1,
            command=[sys.executable, "-c", "import sys; sys.exit(3)"],
            respawn_backoff_base_s=1.0, respawn_backoff_max_s=8.0,
            crash_loop_threshold=3, crash_loop_window_s=100.0)],
        port_start=base, port_end=base + 9,
    )
    mgr = FleetManager(spec, clock=lambda: t[0])
    respawns_before = _gauge_value("doomed")

    async def crash_once():
        """Reconcile until the current replica is spawned and reaped
        as a crash."""
        await mgr.reconcile_once()
        assert len(mgr.replicas["doomed"]) == 1
        mgr.replicas["doomed"][0].process.wait(timeout=10)
        streak = mgr._crash_streak["doomed"]
        await mgr.reconcile_once()
        assert mgr._crash_streak["doomed"] == streak + 1

    try:
        await crash_once()  # crash #1
        # Backoff gates the respawn: same clock, no new replica.
        await mgr.reconcile_once()
        assert mgr.replicas["doomed"] == []
        gate = mgr._next_spawn_ok["doomed"]
        assert 1000.0 + 0.5 <= gate <= 1000.0 + 1.0  # jitter in [.5,1]

        t[0] += 1.0
        await crash_once()  # crash #2 (respawn counted)
        assert _gauge_value("doomed") == respawns_before + 1
        gate = mgr._next_spawn_ok["doomed"]
        assert t[0] + 1.0 <= gate <= t[0] + 2.0  # doubled, jittered

        t[0] += 2.0
        await crash_once()  # crash #3: breaker threshold reached
        assert _gauge_value("doomed") == respawns_before + 2

        # Breaker open: even far past the backoff, no respawn while
        # three crashes sit inside the window.
        t[0] += 50.0
        for _ in range(3):
            await mgr.reconcile_once()
        assert mgr.replicas["doomed"] == []
        assert mgr._breaker_logged["doomed"]

        # Window cools: respawning resumes.
        t[0] += 200.0
        await mgr.reconcile_once()
        assert len(mgr.replicas["doomed"]) == 1
        assert _gauge_value("doomed") == respawns_before + 3
    finally:
        for reps in mgr.replicas.values():
            for r in reps:
                if r.process.poll() is None:
                    r.process.kill()
        await mgr.close()


async def test_drain_exit_is_not_a_crash():
    """Crash vs drain-exit is always distinguished: a replica that
    exits through the drain path advances neither the backoff streak
    nor the breaker window, and a healthy promotion resets a prior
    streak."""
    base = _free_ports(1)[0]
    spec = FleetSpec(
        pools=[PoolSpec(
            name="decode", min_replicas=1, max_replicas=2, model="m1",
            command=[sys.executable, "-m",
                     "production_stack_tpu.testing.fake_engine",
                     "--host", "127.0.0.1", "--port", "{port}",
                     "--model", "{model}", "--role", "{role}",
                     "--speed", "500", "--ttft", "0.0"])],
        port_start=base, port_end=base + 9,
        drain_timeout_s=30.0,
    )
    mgr = FleetManager(spec)
    try:
        # Pretend the pool crashed before: the healthy boot must
        # forgive the streak.  (The first spawn therefore counts as a
        # respawn — baseline the gauge after it.)
        mgr._crash_streak["decode"] = 2
        deadline = time.time() + 20.0
        while time.time() < deadline:
            await mgr.reconcile_once()
            live = [r for r in mgr.replicas["decode"]
                    if r.state == LIVE]
            if live:
                break
            await asyncio.sleep(0.05)
        assert live, "fake replica never went live"
        assert mgr._crash_streak["decode"] == 0
        respawns_before = _gauge_value("decode")

        await mgr.drain_all()
        assert mgr.replicas["decode"] == []
        assert mgr._crash_streak["decode"] == 0
        assert list(mgr._crash_times["decode"]) == []
        assert _gauge_value("decode") == respawns_before
    finally:
        for reps in mgr.replicas.values():
            for r in reps:
                if r.process.poll() is None:
                    r.process.kill()
        await mgr.close()


# ---- real-engine parity (slow lane) ---------------------------------------
#
# The fast tests above prove the router protocol against fakes; these
# prove the engine side of the contract with the REAL model: the
# shipped checkpoint restores on a fresh process (bf16 and int8 KV)
# and the concatenated stream is byte-identical to an uninterrupted
# run — on a checkpoint miss too, via journal recompute.

import threading

from aiohttp import web


def _serve_app_in_thread(app):
    """Run an aiohttp app on a real socket in a daemon thread (the
    engine's sync offload tier needs real HTTP); (url, stop_fn)."""
    loop = asyncio.new_event_loop()
    started = threading.Event()
    port_box = {}

    def serve():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)
        loop.run_until_complete(site.start())
        port_box["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())

    thread = threading.Thread(target=serve, daemon=True)
    thread.start()
    started.wait(10.0)

    def stop():
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=10.0)

    return f"http://127.0.0.1:{port_box['port']}", stop


@pytest.fixture(scope="module")
def cache_server_url():
    from production_stack_tpu.engine.cache_server import build_cache_server
    url, stop = _serve_app_in_thread(build_cache_server(256 * 1024 ** 2))
    yield url
    stop()


def _engine_config(cache_url, kv_dtype="auto", checkpoint=4,
                   handoff_timeout_s=30.0):
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, OffloadConfig, SchedulerConfig,
        tiny_model_config,
    )
    return EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64,
                          kv_cache_dtype=kv_dtype),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=256,
                                  prefill_chunk_size=64),
        # host_pool_bytes=0: remote-only tier, so every restore is a
        # real cross-process fetch like a replacement pod would do.
        offload=OffloadConfig(enable=True, remote_url=cache_url,
                              host_pool_bytes=0),
        checkpoint_interval_tokens=checkpoint,
        handoff_timeout_s=handoff_timeout_s,
    )


def _engine_server(cache_url, **kwargs):
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.server import EngineServer
    from production_stack_tpu.engine.tokenizer import BenchTokenizer
    # BenchTokenizer: under random weights, greedy ids are almost
    # surely >= 256, which ByteTokenizer decodes to nothing — and a
    # stream with no content deltas relays no checkpoint frames (they
    # piggyback on deltas).  Bench decode emits one printable char per
    # token, like a real vocab would.
    return EngineServer(
        LLMEngine(_engine_config(cache_url, **kwargs),
                  tokenizer=BenchTokenizer(512)),
        "tiny-llama")


# Long prompt: several full KV pages committed before generation, so
# checkpoints have real pages to ship.
_LONG_CHAT = {
    "model": "tiny-llama",
    "messages": [{"role": "user",
                  "content": " ".join(["hello"] * 8)}],
    "max_tokens": 12,
    "temperature": 0,
    "ignore_eos": True,
    "stream": True,
}


def _parse_stream(raw: str):
    """Ordered (kind, payload) events: ("ckpt", descriptor dict) for
    checkpoint comment frames, ("data", event dict) for data events."""
    events = []
    for block in raw.split("\n\n"):
        block = block.strip()
        if block.startswith(": checkpoint "):
            events.append(
                ("ckpt", json.loads(block[len(": checkpoint "):])))
        elif block.startswith("data: ") and block != "data: [DONE]":
            events.append(
                ("data", json.loads(block[len("data: "):])))
    return events


def _delta_content(event: dict) -> str:
    return (event["choices"][0].get("delta") or {}).get("content") or ""


async def _capture_interrupted(client, page_size=16):
    """Stream _LONG_CHAT and pick a resume point: returns (full_text,
    rid, descriptor, delivered_chars_before_it)."""
    resp = await client.post("/v1/chat/completions", json=_LONG_CHAT)
    assert resp.status == 200
    raw = await resp.text()
    events = _parse_stream(raw)
    datas = [e for kind, e in events if kind == "data"]
    full_text = "".join(_delta_content(e) for e in datas)
    rid = datas[0]["id"]
    assert raw.rstrip().endswith("data: [DONE]")

    desc, delivered = None, 0
    seen = 0
    for kind, payload in events:
        if kind == "data":
            seen += len(_delta_content(payload))
        elif (kind == "ckpt"
              # Mid-stream (something left to generate) and the
              # journal doesn't end exactly on a page boundary, so the
              # last full page was shipped -> the restore probe hits.
              and payload["output_tokens"] < _LONG_CHAT["max_tokens"]
              and len(payload["tokens"]) % page_size != 0
              and desc is None):
            desc, delivered = payload, seen
    assert desc is not None, "no usable mid-stream checkpoint frame"
    assert len(desc["tokens"]) // page_size >= 1
    return full_text, rid, desc, delivered


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", ["auto", "int8"])
def test_resume_byte_identical_real_engine(cache_server_url, kv_dtype):
    """Kill-and-resume with the real engine: a fresh process restores
    the shipped checkpoint pages and continues the greedy stream; the
    concatenated text is byte-identical, under the original response
    id, with no second role chunk — for bf16 and int8 KV."""

    async def run():
        a = _engine_server(cache_server_url, kv_dtype=kv_dtype)
        client_a = TestClient(TestServer(a.build_app()))
        await client_a.start_server()
        try:
            full_text, rid, desc, delivered = await _capture_interrupted(
                client_a)
        finally:
            await client_a.close()
        assert desc["kv_dtype"] == a.engine.config.cache.resolved_kv_dtype()
        assert a.engine.stats()["checkpoint_ships_total"] > 0
        assert a.engine.stats()["checkpoint_kv_bytes_total"] > 0

        # "a" is dead now. A replacement pod picks up the descriptor.
        b = _engine_server(cache_server_url, kv_dtype=kv_dtype)
        client_b = TestClient(TestServer(b.build_app()))
        await client_b.start_server()
        try:
            # A different-dtype pod can NEVER restore these pages:
            # it must refuse with 409 so the router keeps looking.
            wrong = dict(desc)
            wrong["kv_dtype"] = ("int8" if desc["kv_dtype"] != "int8"
                                 else "bf16")
            resp = await client_b.post("/v1/resume", json={
                "descriptor": wrong, "delivered_text_chars": 0})
            assert resp.status == 409

            resp = await client_b.post("/v1/resume", json={
                "descriptor": desc,
                "delivered_text_chars": delivered,
                "stream": True,
            })
            assert resp.status == 200
            resumed = _parse_stream(await resp.text())
            assert all(kind in ("data", "ckpt") for kind, _ in resumed)
            datas = [e for kind, e in resumed if kind == "data"]
            tail = "".join(_delta_content(e) for e in datas)

            # Byte-exact continuation under the original identity.
            assert full_text[:delivered] + tail == full_text
            assert {e["id"] for e in datas} == {rid}
            assert all("role" not in (e["choices"][0].get("delta") or {})
                       for e in datas)
            assert datas[-1]["choices"][0]["finish_reason"] == "length"
            # The pages really came back from the tier (hit, not
            # recompute): the frame choice guarantees restorability.
            assert b.engine.offload.restored_pages > 0
            assert b.engine.stats()["stream_resumes_total"] == 1
        finally:
            await client_b.close()

    asyncio.run(run())


@pytest.mark.slow
def test_resume_checkpoint_miss_recomputes_parity(cache_server_url):
    """Degraded-never-dropped: a replacement whose tier lost the pages
    (here: unreachable) recomputes from the token journal and still
    produces the byte-identical tail."""

    async def run():
        a = _engine_server(cache_server_url)
        client_a = TestClient(TestServer(a.build_app()))
        await client_a.start_server()
        try:
            full_text, rid, desc, delivered = await _capture_interrupted(
                client_a)
        finally:
            await client_a.close()

        b = _engine_server(_free_port_url(), checkpoint=0,
                           handoff_timeout_s=0.0)
        client_b = TestClient(TestServer(b.build_app()))
        await client_b.start_server()
        try:
            resp = await client_b.post("/v1/resume", json={
                "descriptor": desc,
                "delivered_text_chars": delivered,
                "stream": True,
            })
            assert resp.status == 200
            datas = [e for kind, e in
                     _parse_stream(await resp.text()) if kind == "data"]
            tail = "".join(_delta_content(e) for e in datas)
            assert full_text[:delivered] + tail == full_text
            assert {e["id"] for e in datas} == {rid}
            assert b.engine.offload.restored_pages == 0  # recomputed
            assert b.engine.stats()["stream_resumes_total"] == 1
        finally:
            await client_b.close()

    asyncio.run(run())


def _free_port_url() -> str:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"http://127.0.0.1:{port}"


@pytest.mark.slow
def test_resume_abort_releases_nothing_awaiting_kv(cache_server_url):
    """Regression: a resume parked in AWAITING_KV holds zero pages, so
    a client abort while it waits must release nothing and leave no
    work behind."""
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import (
        SamplingParams, SequenceState,
    )
    eng = LLMEngine(_engine_config(cache_server_url))
    # Pin the sequence in AWAITING_KV: no tier verdict, and the 30s
    # timeout never fires within the test.
    eng.offload.handoff_ready = lambda page_hash: None
    free_before = eng.cache_manager.num_free_pages
    sid = eng.add_resume(
        list(range(1, 50)), 7,
        SamplingParams(max_tokens=12, temperature=0.0, ignore_eos=True))
    seq = eng.sequences[sid]
    for _ in range(3):
        eng.step()
    assert seq.state == SequenceState.AWAITING_KV
    assert eng.stats()["num_requests_waiting"] == 1
    assert eng.stats()["stream_resumes_total"] == 1
    assert eng.cache_manager.num_free_pages == free_before

    eng.abort_request(sid)
    assert sid not in eng.sequences
    assert eng.stats()["num_requests_waiting"] == 0
    assert eng.cache_manager.num_free_pages == free_before
    assert not eng.scheduler.has_work()
