"""Static check: no blocking host reads on the decode dispatch path.

The overlapped async pipeline (docs/async_pipeline.md) only hides
host work if ``ModelRunner.dispatch_decode`` and everything it calls
stays purely dispatching — a single ``np.asarray(device array)``,
``jax.device_get`` or ``.block_until_ready()`` on that path silently
re-serializes the pipeline.

Since PR 5 this is a thin wrapper over the staticcheck ``host-read``
rule (production_stack_tpu/staticcheck/analyzers/dispatch_path.py),
which also owns the DISPATCH_PATH function list and the
tracks-reality check. Test names are kept so history stays
comparable. Waivers: ``# lint: allow-host-read`` on the call line.
"""

import pathlib

from production_stack_tpu.staticcheck import Project, run_rules

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _findings(project):
    return [f for f in run_rules(project, rules=["host-read"])
            if f.rule == "host-read"]


def test_dispatch_path_has_no_blocking_host_reads():
    # Covers both halves of the old test: no blocking reads inside
    # the DISPATCH_PATH functions, and every DISPATCH_PATH name still
    # existing in model_runner.py (the rule emits a finding when one
    # falls out of the real call graph).
    findings = _findings(Project.from_root(ROOT))
    assert not findings, (
        "Blocking host reads inside the async dispatch path (these "
        "re-serialize the pipeline; move the read to result()/"
        "completion, or add a '# lint: allow-host-read' waiver with "
        "justification):\n" + "\n".join(f.render() for f in findings)
    )


def test_lint_catches_a_violation():
    """The checker itself must actually flag offending calls."""
    findings = _findings(Project.from_sources({
        "production_stack_tpu/engine/model_runner.py":
            "def dispatch_decode(self):\n"
            "    x = np.asarray(self._next_rng())\n"
            "    y = jax.device_get(x)\n"
            "    z = sampled.block_until_ready()\n"
            "    return int(x[0])\n",
    }))
    blocking = [f for f in findings
                if "blocking host read" in f.message]
    # np.asarray, device_get, block_until_ready — int() is not one.
    assert len(blocking) == 3
    # A clean dispatch body produces no blocking-read findings.
    clean = _findings(Project.from_sources({
        "production_stack_tpu/engine/model_runner.py":
            "def dispatch_decode(self):\n"
            "    return jax.device_put(tuple(x))\n",
    }))
    assert not [f for f in clean if "blocking host read" in f.message]
