"""Static check: no blocking host reads on the decode dispatch path.

The overlapped async pipeline (docs/async_pipeline.md) only hides
host work if ``ModelRunner.dispatch_decode`` and everything it calls
stays purely dispatching: building a payload, one fused host->device
transfer, launching the jitted step. A single ``np.asarray(device
array)``, ``jax.device_get`` or ``.block_until_ready()`` anywhere on
that path silently re-serializes the pipeline — the step "works" but
the overlap is gone, which no functional test notices. Flags, inside
the DISPATCH_PATH functions of engine/model_runner.py:

- ``np.asarray(...)`` / ``np.array(...)`` (device->host copy when fed
  a device array),
- ``jax.device_get(...)`` / ``device_get(...)``,
- ``<anything>.block_until_ready(...)``,
- ``<anything>.item(...)`` / ``float(...)`` / ``int(...)`` on a call's
  result is not flagged — literal coercions of host scalars are fine —
  but ``.item()`` on arrays is.

A deliberate host read can carry a ``# lint: allow-host-read`` comment
on the call line, which must be rare and justified in review.
"""

import ast
import pathlib

ROOT = pathlib.Path(__file__).resolve().parent.parent
RUNNER = ROOT / "production_stack_tpu" / "engine" / "model_runner.py"

# Every function the async dispatch path runs through. run_decode /
# result() are NOT here: they are the sync completion side and their
# device_get is the one intended blocking read.
DISPATCH_PATH = {
    "dispatch_decode",
    "_staging_set",
    "_dispatch",
    "execute_payload",
    "_optional_device_inputs",
    "_penalty_payload",
    "_seed_payload",
    "_bias_payload",
    "_suppress_payload",
    "_guided_payload",
    "_next_rng",
    "_as_device",
}

_WAIVER = "lint: allow-host-read"


def _tail_name(node: ast.AST) -> str:
    """Rightmost identifier of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _recv_name(node: ast.AST) -> str:
    """Identifier of an Attribute's receiver ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return _tail_name(node.value)
    return ""


def _is_blocking_call(call: ast.Call) -> bool:
    func = call.func
    name = _tail_name(func)
    recv = _recv_name(func)
    if recv == "np" and name in ("asarray", "array"):
        return True
    if name == "device_get":  # jax.device_get or bare import
        return True
    if isinstance(func, ast.Attribute) and name in (
            "block_until_ready", "item"):
        return True
    return False


def _dispatch_path_functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in DISPATCH_PATH:
                yield node


def test_dispatch_path_has_no_blocking_host_reads():
    source = RUNNER.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(RUNNER))
    seen = set()
    violations = []
    for fn in _dispatch_path_functions(tree):
        seen.add(fn.name)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if not _is_blocking_call(node):
                continue
            line = (lines[node.lineno - 1]
                    if node.lineno <= len(lines) else "")
            if _WAIVER in line:
                continue
            violations.append(
                f"{RUNNER.relative_to(ROOT)}:{node.lineno} "
                f"(in {fn.name}): blocking host read on the dispatch "
                f"path: {line.strip()}"
            )
    assert not violations, (
        "Blocking host reads inside the async dispatch path (these "
        "re-serialize the pipeline; move the read to result()/"
        "completion, or add a '# lint: allow-host-read' waiver with "
        "justification):\n" + "\n".join(violations)
    )
    # The list must track reality: a renamed/deleted function here
    # would silently stop being linted.
    missing = DISPATCH_PATH - seen
    assert not missing, (
        f"DISPATCH_PATH names not found in model_runner.py: {missing}"
    )


def test_lint_catches_a_violation():
    """The checker itself must actually flag offending calls."""
    snippet = (
        "def dispatch_decode(self):\n"
        "    x = np.asarray(self._next_rng())\n"
        "    y = jax.device_get(x)\n"
        "    z = sampled.block_until_ready()\n"
        "    return int(x[0])\n"
    )
    tree = ast.parse(snippet)
    fns = list(_dispatch_path_functions(tree))
    assert [f.name for f in fns] == ["dispatch_decode"]
    flagged = [n for n in ast.walk(fns[0])
               if isinstance(n, ast.Call) and _is_blocking_call(n)]
    # np.asarray, device_get, block_until_ready — int() is not one.
    assert len(flagged) == 3
    clean = ast.parse(
        "def dispatch_decode(self):\n"
        "    return jax.device_put(tuple(x))\n"
    )
    assert not [n for n in ast.walk(clean)
                if isinstance(n, ast.Call) and _is_blocking_call(n)]
