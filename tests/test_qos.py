"""QoS under overload (docs/qos.md): priority classes, preempt-to-
offload, per-tenant fairness, and graceful shedding.

Engine side: admission is priority-then-arrival, the preemption victim
is the lowest-priority newest running sequence, and with an offload
tier the victim's committed KV ships out and restores byte-identically
instead of recomputing. Router side: per-tenant token buckets feed the
degrade/shed ladder and the stride-scheduled fair gate.
"""

import asyncio

import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    OffloadConfig,
    QoSConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import (
    SamplingParams,
    SequenceState,
)
from production_stack_tpu.qos import (
    DEFAULT_PRIORITY,
    Priority,
    TokenBucket,
    jain_index,
    parse_priority,
    shed_retry_after_s,
)
from production_stack_tpu.router.qos import (
    FairGate,
    RouterQoS,
    RouterQoSConfig,
)


# ---- shared engine builders ------------------------------------------------

def _make_engine(num_pages, offload=True, preempt_to_offload=True,
                 kv_dtype="auto", max_num_seqs=2):
    return LLMEngine(EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=num_pages,
                          kv_cache_dtype=kv_dtype),
        scheduler=SchedulerConfig(max_num_seqs=max_num_seqs,
                                  max_model_len=256,
                                  prefill_chunk_size=64),
        offload=OffloadConfig(enable=offload,
                              host_pool_bytes=256 * 1024 ** 2),
        qos=QoSConfig(preempt_to_offload=preempt_to_offload),
    ))


def _sampling(n=48):
    return SamplingParams(max_tokens=n, temperature=0.0,
                          ignore_eos=True)


_INTER_PROMPT = list(range(100, 148))
_BG_PROMPT = list(range(500, 548))


def _run_pair_under_pressure(engine):
    """Two unrelated 48-token prompts with long outputs on a cache too
    small for both: the scheduler must preempt mid-decode. Returns the
    full generated suffix per request ('inter'/'bg') — preemption folds
    generated tokens into the prompt, so ``output_token_ids`` alone
    only holds the post-restore tail; ``all_token_ids`` past the
    original prompt is the invariant view."""
    inter = engine.add_request(list(_INTER_PROMPT), _sampling(),
                               priority=int(Priority.INTERACTIVE))
    bg = engine.add_request(list(_BG_PROMPT), _sampling(),
                            priority=int(Priority.BACKGROUND))
    seqs = [engine.sequences[inter], engine.sequences[bg]]
    for _ in range(3000):
        if all(s.state in (SequenceState.FINISHED,
                           SequenceState.ABORTED) for s in seqs):
            break
        engine.step()
    assert all(s.state == SequenceState.FINISHED for s in seqs)
    return {"inter": seqs[0].all_token_ids[len(_INTER_PROMPT):],
            "bg": seqs[1].all_token_ids[len(_BG_PROMPT):]}


@pytest.mark.parametrize("kv_dtype", ["auto", "int8"])
def test_preempt_to_offload_byte_parity_vs_recompute(kv_dtype):
    """The tentpole invariant: a preempted-then-restored victim's
    output is byte-identical whether its KV came back from the offload
    tier or from a full recompute — and identical to an unpressured
    reference. Covers both the full-precision pair payloads and the
    int8 4-tuple (data + scales) payloads."""
    # Unpressured reference (pages for everything, no offload).
    ref = _run_pair_under_pressure(
        _make_engine(num_pages=128, offload=False,
                     preempt_to_offload=False, kv_dtype=kv_dtype))

    # int8 slots are a fraction of the full-precision bytes, so config
    # expands num_pages (3 -> 10 here); shrink the input to land at
    # comparable real pressure (both requests together need ~12 pages,
    # the pressured cache holds fewer).
    pages = 3 if kv_dtype == "int8" else 12

    offl = _make_engine(num_pages=pages, kv_dtype=kv_dtype)
    got_offload = _run_pair_under_pressure(offl)
    assert offl.scheduler.num_preemptions > 0
    assert offl.scheduler.preempt_offload_outcomes["offloaded"] > 0
    assert offl.offload.offloaded_pages > 0

    reco = _make_engine(num_pages=pages, preempt_to_offload=False,
                        kv_dtype=kv_dtype)
    got_recompute = _run_pair_under_pressure(reco)
    assert reco.scheduler.num_preemptions > 0
    assert reco.scheduler.preempt_offload_outcomes["offloaded"] == 0
    assert reco.scheduler.preempt_offload_outcomes["recompute"] > 0

    assert got_offload == ref
    assert got_recompute == ref


def test_preempt_victim_is_lowest_priority_newest():
    """Under pressure the interactive sequence keeps running; the
    background one is the victim (max over (priority, arrival))."""
    engine = _make_engine(num_pages=12)
    inter = engine.add_request(list(range(100, 148)), _sampling(),
                               priority=int(Priority.INTERACTIVE))
    bg = engine.add_request(list(range(500, 548)), _sampling(),
                            priority=int(Priority.BACKGROUND))
    inter_seq = engine.sequences[inter]
    bg_seq = engine.sequences[bg]
    for _ in range(3000):
        if engine.scheduler.num_preemptions > 0:
            break
        engine.step()
    assert engine.scheduler.num_preemptions > 0
    # Only the background sequence was ever folded back (preemption
    # moves generated tokens into the prompt); interactive kept its
    # pages through every pressure event.
    assert inter_seq.num_prior_output_tokens == 0
    assert bg_seq is not inter_seq


def test_abort_while_evicted_releases_everything():
    """Abort a victim parked in AWAITING_KV (its KV already shipped to
    the offload tier): no page leak, no queue residue, and the other
    request still finishes."""
    engine = _make_engine(num_pages=12)
    inter = engine.add_request(list(range(100, 148)), _sampling(),
                               priority=int(Priority.INTERACTIVE))
    bg = engine.add_request(list(range(500, 548)), _sampling(),
                            priority=int(Priority.BACKGROUND))
    bg_seq = engine.sequences[bg]
    parked = False
    for _ in range(3000):
        if bg_seq.state == SequenceState.AWAITING_KV:
            parked = True
            break
        engine.step()
    assert parked, "victim never parked awaiting its offloaded KV"
    engine.abort_request(bg)
    assert bg not in engine.sequences
    # Drain the survivor.
    inter_seq = engine.sequences[inter]
    for _ in range(3000):
        if inter_seq.state == SequenceState.FINISHED:
            break
        engine.step()
    assert inter_seq.state == SequenceState.FINISHED
    assert not engine.has_work()
    assert engine.scheduler.num_waiting == 0
    # Every allocated page is free (or evictable prefix-cache, which
    # num_used_pages already counts as free).
    assert engine.cache_manager.num_used_pages == 0


def test_priority_admission_matrix():
    """Waiting sequences are admitted priority-first, arrival-second —
    regardless of submission order."""
    engine = _make_engine(num_pages=128, offload=False,
                          max_num_seqs=8)
    submitted = [
        engine.add_request(list(range(100 * (i + 1), 100 * (i + 1) + 8)),
                           _sampling(4), priority=int(pri))
        for i, pri in enumerate([
            Priority.BACKGROUND, Priority.BATCH, Priority.INTERACTIVE,
            Priority.BATCH, Priority.INTERACTIVE,
        ])
    ]
    plan = engine.scheduler.plan_step()
    order = [c.seq.seq_id for c in plan.prefill.chunks]
    expect = [submitted[2], submitted[4],   # interactive, by arrival
              submitted[1], submitted[3],   # batch, by arrival
              submitted[0]]                 # background
    # prefill_batch_size may cap the planned rows; whatever was
    # planned must be a prefix of the priority-then-arrival order.
    assert len(order) >= 2
    assert order == expect[:len(order)]


def test_add_request_default_priority():
    engine = _make_engine(num_pages=32, offload=False)
    sid = engine.add_request(list(range(100, 116)), _sampling(2))
    assert engine.sequences[sid].priority == int(DEFAULT_PRIORITY)
    assert engine.default_priority == int(DEFAULT_PRIORITY)


# ---- config validation -----------------------------------------------------

def test_invalid_priority_rejected_everywhere():
    with pytest.raises(ValueError, match="invalid priority"):
        parse_priority("urgent")
    with pytest.raises(ValueError, match="invalid priority"):
        QoSConfig(default_priority="realtime")
    for bad_threshold in (0.0, -0.5, 1.5):
        with pytest.raises(ValueError):
            QoSConfig(shed_threshold=bad_threshold)
    # Valid classes parse, case/space tolerant.
    assert parse_priority(" Interactive ") == Priority.INTERACTIVE
    assert QoSConfig(default_priority="background")


def test_router_qos_flags_validated():
    from production_stack_tpu.router.parser import (
        parse_args,
        validate_args,
    )
    base = ["--service-discovery", "static",
            "--static-backends", "http://e:1",
            "--static-models", "m"]
    validate_args(parse_args(base + ["--qos-tenant-rate", "5"]))
    for flags, msg in [
        (["--qos-tenant-rate", "-1"], "tenant-rate"),
        (["--qos-tenant-rate", "5", "--qos-tenant-burst", "0"],
         "tenant-burst"),
        (["--qos-tenant-rate", "5", "--qos-degrade-max-tokens", "0"],
         "degrade-max-tokens"),
        (["--qos-tenant-rate", "5", "--qos-shed-deficit", "0"],
         "shed-deficit"),
        (["--qos-max-concurrency", "-2"], "max-concurrency"),
    ]:
        with pytest.raises(ValueError, match=msg):
            validate_args(parse_args(base + flags))


# ---- token bucket + ladder -------------------------------------------------

def test_token_bucket_debt_and_recovery():
    b = TokenBucket(rate=1.0, burst=2.0)
    assert b.take(1.0, now=0.0) and b.take(1.0, now=0.0)
    assert not b.take(1.0, now=0.0)
    assert b.deficit(0.0) == 0.0
    b.charge(1.0, now=0.0, max_debt=3.0)
    b.charge(1.0, now=0.0, max_debt=3.0)
    assert b.deficit(0.0) == pytest.approx(2.0)
    # Debt floors at max_debt.
    for _ in range(10):
        b.charge(1.0, now=0.0, max_debt=3.0)
    assert b.deficit(0.0) == pytest.approx(3.0)
    # Refill pays debt down at `rate`; retry hint covers the shortfall.
    assert b.retry_after_s(0.0) == pytest.approx(4.0)
    assert b.deficit(2.0) == pytest.approx(1.0)
    assert b.take(1.0, now=5.0)


def test_shed_retry_after_floor():
    assert shed_retry_after_s(0, 10.0) == 1
    assert shed_retry_after_s(30, 10.0) == 3
    assert shed_retry_after_s(5, 0.0) == 1


def test_ladder_admit_degrade_shed():
    q = RouterQoS(RouterQoSConfig(tenant_rate=1.0, tenant_burst=2.0,
                                  shed_deficit=5.0))
    acts = [q.decide("t", Priority.BATCH, now=0.0).action
            for _ in range(10)]
    assert acts[:2] == ["admit", "admit"]
    assert "degrade" in acts and acts[-1] == "shed"
    assert acts.index("shed") > acts.index("degrade")
    shed = q.decide("t", Priority.BATCH, now=0.0)
    assert shed.retry_after_s >= 1
    # Degrade carries the clamp + spec-off hint.
    q2 = RouterQoS(RouterQoSConfig(tenant_rate=1.0, tenant_burst=1.0,
                                   degrade_max_tokens=32))
    q2.decide("t", Priority.BATCH, now=0.0)
    deg = q2.decide("t", Priority.BATCH, now=0.0)
    assert deg.action == "degrade"
    assert deg.clamp_max_tokens == 32 and deg.spec_off
    # Idle time pays the debt off: back to admit.
    assert q.decide("t", Priority.BATCH, now=60.0).action == "admit"


def test_interactive_never_rate_shed():
    q = RouterQoS(RouterQoSConfig(tenant_rate=1.0, tenant_burst=1.0,
                                  shed_deficit=2.0))
    acts = {q.decide("t", Priority.INTERACTIVE, now=0.0).action
            for _ in range(50)}
    assert "shed" not in acts
    assert q.shed_by_class["interactive"] == 0


def test_jain_fairness_bound_under_adversarial_tenant():
    """One tenant offering 50x the rate of four well-behaved tenants
    must not drag admitted-share fairness below 0.8 — and the
    well-behaved tenants are never throttled at all."""
    q = RouterQoS(RouterQoSConfig(tenant_rate=2.0, tenant_burst=4.0,
                                  shed_deficit=5.0))
    admitted = {f"good-{i}": 0 for i in range(4)}
    admitted["adversary"] = 0
    good_degraded = 0
    for tick in range(1000):  # 10 simulated seconds, 10ms ticks
        now = tick / 100.0
        if tick % 100 == 0:
            for name in list(admitted):
                if name == "adversary":
                    continue
                v = q.decide(name, Priority.INTERACTIVE, now=now)
                if v.action == "admit":
                    admitted[name] += 1
                else:
                    good_degraded += 1
        v = q.decide("adversary", Priority.BATCH, now=now)  # 100/s
        if v.action == "admit":
            admitted["adversary"] += 1
    assert good_degraded == 0
    assert q.shed_by_class["batch"] > 0
    fairness = jain_index(admitted.values())
    assert fairness >= 0.8, (fairness, admitted)


def test_jain_index_extremes():
    assert jain_index([]) == 1.0
    assert jain_index([3, 3, 3]) == pytest.approx(1.0)
    assert jain_index([9, 0, 0]) == pytest.approx(1 / 3)


def test_tenant_identity_and_lru_bound():
    assert RouterQoS.tenant_of({"x-api-key": "abc"}, "1.2.3.4") \
        == "key:abc"
    assert RouterQoS.tenant_of({}, "1.2.3.4") == "ip:1.2.3.4"
    assert RouterQoS.tenant_of({}, None) == "anonymous"
    from production_stack_tpu.router import qos as rq
    q = RouterQoS(RouterQoSConfig())
    for i in range(rq.MAX_TRACKED_TENANTS + 50):
        q._state(f"t{i}")
    assert len(q._tenants) == rq.MAX_TRACKED_TENANTS


def test_fair_gate_weighted_dequeue():
    """With the gate saturated, waiters dequeue by stride: an
    interactive tenant gets ~4x the admissions of a background one."""
    async def run():
        q = RouterQoS(RouterQoSConfig(max_concurrency=1))
        gate = q.gate
        await gate.acquire("warm", Priority.BATCH)  # saturate
        admitted = []

        async def waiter(tenant, priority):
            await gate.acquire(tenant, priority)
            admitted.append(tenant)
            gate.release()

        tasks = []
        for i in range(12):
            tasks.append(asyncio.ensure_future(
                waiter("vip", Priority.INTERACTIVE)))
            tasks.append(asyncio.ensure_future(
                waiter("bulk", Priority.BACKGROUND)))
        await asyncio.sleep(0)  # enqueue everyone
        gate.release()  # open the floodgate; each waiter releases on
        await asyncio.gather(*tasks)
        # In any admission prefix the interactive tenant leads ~4:1.
        first_half = admitted[:12]
        assert first_half.count("vip") >= 8, admitted
        assert gate.queued == 0 and gate.active == 0
    asyncio.run(run())


def test_fair_gate_cancelled_waiter_unlinked():
    async def run():
        q = RouterQoS(RouterQoSConfig(max_concurrency=1))
        gate = q.gate
        await gate.acquire("a", Priority.BATCH)
        task = asyncio.ensure_future(gate.acquire("b", Priority.BATCH))
        await asyncio.sleep(0)
        task.cancel()
        await asyncio.sleep(0)
        assert gate.queued == 0
        gate.release()
        assert gate.active == 0
        # A fresh acquire still works.
        await gate.acquire("c", Priority.BATCH)
        gate.release()
    asyncio.run(run())
