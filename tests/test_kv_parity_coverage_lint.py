"""Static check: every attention impl has bf16-vs-int8 parity tests.

The int8 KV cache (docs/kv_quantization.md) dequantizes inside each
attention implementation — XLA reference and both Pallas kernels. A
new impl that skips the QuantKV branch would pass every full-precision
test and silently serve garbage under ``--kv-cache-dtype int8``, so
this lint walks the registry ``ops.attention.ATTENTION_IMPLS`` and
requires, for each registered function, at least one test function in
``tests/`` that (a) carries ``int8`` in its name and (b) references
the impl by name in its body. Registering the impl is what arms the
check; the companion assertion keeps the registry honest against the
modules it points at.
"""

import ast
import importlib
import pathlib

from production_stack_tpu.ops.attention import ATTENTION_IMPLS

TESTS = pathlib.Path(__file__).resolve().parent


def _referenced_names(fn: ast.AST):
    """Every identifier a test function touches: bare names, attribute
    tails, and string constants (covers indirect references like
    getattr-by-name or parametrize ids)."""
    names = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            names.add(node.id)
        elif isinstance(node, ast.Attribute):
            names.add(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(
                node.value, str):
            names.add(node.value)
    return names


def _int8_test_functions():
    """(test_name, referenced_names) for every int8-named test under
    tests/."""
    out = []
    for path in sorted(TESTS.glob("test_*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not node.name.startswith("test_"):
                continue
            if "int8" not in node.name and "quant" not in node.name:
                continue
            out.append((f"{path.name}::{node.name}",
                        _referenced_names(node)))
    return out


def test_every_attention_impl_has_int8_parity_coverage():
    tests = _int8_test_functions()
    assert tests, "no int8/quant parity tests found under tests/"
    violations = []
    for key, (module, func_name) in sorted(ATTENTION_IMPLS.items()):
        covered = [name for name, refs in tests if func_name in refs]
        if not covered:
            violations.append(
                f"{key} ({module}.{func_name}): no test function "
                "with 'int8'/'quant' in its name references "
                f"{func_name}"
            )
    assert not violations, (
        "Attention impls without bf16-vs-int8 parity coverage (add a "
        "test named test_*int8* that exercises the impl on QuantKV "
        "pages):\n" + "\n".join(violations)
    )


def test_registry_tracks_reality():
    """Every registry entry must resolve to a real callable, and every
    paged-attention entry point in ops/ must be registered — a new
    kernel module cannot dodge the lint by not registering."""
    for key, (module, func_name) in ATTENTION_IMPLS.items():
        fn = getattr(importlib.import_module(module), func_name, None)
        assert callable(fn), f"{key}: {module}.{func_name} missing"

    registered = {m.rsplit(".", 1)[-1]
                  for m, _ in ATTENTION_IMPLS.values()}
    ops_dir = (TESTS.parent / "production_stack_tpu" / "ops")
    for path in ops_dir.glob("*attention*.py"):
        # Only modules exposing a paged entry point read KV pages and
        # therefore need a QuantKV branch (ring_attention consumes raw
        # q/k/v and is gated off from int8 at config level).
        tree = ast.parse(path.read_text(), filename=str(path))
        paged = any(isinstance(n, ast.FunctionDef)
                    and n.name.startswith("paged_")
                    for n in tree.body)
        if paged:
            assert path.stem in registered, (
                f"ops/{path.name} defines a paged_* entry point but "
                "is not in ATTENTION_IMPLS — register it so the int8 "
                "parity lint covers it"
            )


def test_lint_catches_a_missing_impl():
    """The checker itself must flag an unreferenced impl."""
    tests = _int8_test_functions()
    phantom = "paged_attention_that_does_not_exist"
    assert not [name for name, refs in tests if phantom in refs]
    # And a real impl is found by the same mechanism.
    assert [name for name, refs in tests
            if "paged_decode_attention" in refs]
