"""Static check: every attention impl has bf16-vs-int8 parity tests.

The int8 KV cache (docs/kv_quantization.md) dequantizes inside each
attention implementation; a new impl that skips the QuantKV branch
passes every full-precision test and silently serves garbage under
``--kv-cache-dtype int8``.

Since PR 5 the static half is a thin wrapper over the staticcheck
``kv-parity`` rule (production_stack_tpu/staticcheck/analyzers/
kv_parity.py): registry coverage AND the paged_*-module-must-register
check both live there. The importlib half (registry entries resolve
to real callables) stays here — staticcheck deliberately never
imports the code it analyzes. Test names are kept so history stays
comparable.
"""

import importlib
import pathlib

from production_stack_tpu.ops.attention import ATTENTION_IMPLS
from production_stack_tpu.staticcheck import Project, run_rules

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _findings(project):
    return [f for f in run_rules(project, rules=["kv-parity"])
            if f.rule == "kv-parity"]


def test_every_attention_impl_has_int8_parity_coverage():
    findings = _findings(Project.from_root(ROOT))
    assert not findings, (
        "Attention impls without bf16-vs-int8 parity coverage (add a "
        "test named test_*int8* that exercises the impl on QuantKV "
        "pages):\n" + "\n".join(f.render() for f in findings)
    )


def test_registry_tracks_reality():
    """Every registry entry must resolve to a real callable (needs
    imports, so it stays outside staticcheck); the companion check —
    every paged_* module in ops/ is registered — is a kv-parity
    finding covered by the wrapper above."""
    for key, (module, func_name) in ATTENTION_IMPLS.items():
        fn = getattr(importlib.import_module(module), func_name, None)
        assert callable(fn), f"{key}: {module}.{func_name} missing"


def test_lint_catches_a_missing_impl():
    """The checker itself must flag an unreferenced impl and an
    unregistered paged_* module."""
    findings = _findings(Project.from_sources({
        "production_stack_tpu/ops/attention.py":
            'ATTENTION_IMPLS = {\n'
            '    "phantom": ("production_stack_tpu.ops.gone",\n'
            '                "paged_attention_that_does_not_exist"),\n'
            '}\n',
        "production_stack_tpu/ops/rogue_attention.py":
            "def paged_rogue(q):\n"
            "    return q\n",
        "tests/test_int8_parity.py":
            "def test_int8_other():\n"
            "    assert paged_decode_attention\n",
    }))
    messages = "\n".join(f.message for f in findings)
    assert "paged_attention_that_does_not_exist" in messages
    assert "rogue_attention.py defines a paged_* entry point" in messages
