"""Safe fleet rollouts (docs/fleet.md).

Canary-scored rolling upgrades end to end: spec parse/validation for
the revision + rollout knobs, the router's weighted canary split and
in-band migrate-marker relay, watchdog-aware drain escalation, the
operator pause/resume/abort control channel, the slow-exemplar
capture surviving a dead replica, and the two acceptance E2Es over
real fake-engine subprocesses — a good canary promotes fleet-wide
with a long in-flight stream migrated byte-identically across
revisions and zero 5xx, and a fault-injected bad canary is judged,
automatically rolled back behind a latched alarm, and recovers to
full SLO attainment.

Fast lane: fake engines only — no LLMEngine is ever built.
"""

import asyncio
import json
import socket
import sys
import time
from types import SimpleNamespace

import aiohttp
import pytest
from aiohttp import web

from production_stack_tpu.fleet.manager import (
    DRAINING,
    LIVE,
    FleetManager,
    Replica,
)
from production_stack_tpu.fleet.spec import (
    AutoscalerSpec,
    FleetSpec,
    PoolSpec,
    RevisionSpec,
    RolloutSpec,
)
from production_stack_tpu.router.resilience import (
    ResilienceConfig,
    initialize_resilience,
)
from production_stack_tpu.router.service_discovery import (
    EndpointInfo,
    initialize_service_discovery,
)
from production_stack_tpu.router.services import request_service
from production_stack_tpu.router.services.rewriter import (
    initialize_request_rewriter,
)
from production_stack_tpu.router.stats.engine_stats import (
    initialize_engine_stats_scraper,
)
from production_stack_tpu.router.stats.request_stats import (
    initialize_request_stats_monitor,
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _fake_pool_command(speed: float = 200.0, ckpt_every: int = 2):
    return [sys.executable, "-m",
            "production_stack_tpu.testing.fake_engine",
            "--host", "127.0.0.1", "--port", "{port}",
            "--model", "{model}", "--role", "{role}",
            "--speed", str(speed), "--ttft", "0.0",
            "--checkpoint-interval-tokens", str(ckpt_every)]


# ---- spec parse + validation ----------------------------------------------

def test_rollout_spec_parses_and_validates():
    spec = FleetSpec.from_json(json.dumps({
        "rollout_control_path": "/tmp/rollout-ctl.json",
        "pools": [{
            "name": "decode", "max_replicas": 4,
            "revision": {"build_id": "v2",
                         "engine_flags": ["--speed", "50"]},
            "rollout": {"canary_weight": 0.25, "bake_s": 30.0,
                        "max_slo_burn_rate_5m": 2.0,
                        "fail_on_perf_drift": False,
                        "max_crash_streak": 2,
                        "max_server_errors": 3.0,
                        "max_latency_ratio": 2.5,
                        "drain_mode": "wait"},
        }],
    }))
    pool = spec.pools[0]
    assert spec.rollout_control_path == "/tmp/rollout-ctl.json"
    assert pool.revision.build_id == "v2"
    assert pool.revision.key() == ("v2", ("--speed", "50"))
    assert pool.rollout.canary_weight == 0.25
    assert pool.rollout.drain_mode == "wait"
    assert not pool.rollout.fail_on_perf_drift
    # Two revisions are the same iff build id AND flags match.
    assert RevisionSpec(build_id="v2").key() != pool.revision.key()

    with pytest.raises(ValueError, match="canary_weight"):
        RolloutSpec(canary_weight=0.0)
    with pytest.raises(ValueError, match="canary_weight"):
        RolloutSpec(canary_weight=1.5)
    with pytest.raises(ValueError, match="drain_mode"):
        RolloutSpec(drain_mode="teleport")
    with pytest.raises(ValueError, match="bake_s"):
        RolloutSpec(bake_s=-1.0)
    with pytest.raises(ValueError, match="max_crash_streak"):
        RolloutSpec(max_crash_streak=-1)


# ---- router: canary split + migrate marker --------------------------------

def test_canary_split_weighted_dispatch():
    from production_stack_tpu.router.routing import logic

    stable = [EndpointInfo(url="http://s1"), EndpointInfo(url="http://s2")]
    canary = EndpointInfo(url="http://c1")
    eps = stable + [canary]
    logic.set_canary_weights({"http://c1": 0.5})
    try:
        # Deterministic rng: below the weight -> canaries only;
        # above -> stable set only.
        logic._canary_rng = SimpleNamespace(random=lambda: 0.1)
        assert logic.canary_split(eps) == [canary]
        logic._canary_rng = SimpleNamespace(random=lambda: 0.9)
        assert logic.canary_split(eps) == stable
        # Degenerate cases pass through untouched: no canaries in the
        # candidate list, or nothing BUT canaries (failover paths).
        assert logic.canary_split(stable) == stable
        assert logic.canary_split([canary]) == [canary]
    finally:
        logic.set_canary_weights(None)
        logic._canary_rng = __import__("random").Random()
    assert logic.canary_split(eps) == eps


def test_sse_relay_migrate_marker():
    """The in-band ``: migrating`` comment from a migrate-draining
    engine sets the relay's flag and is never forwarded to the
    client; a resume leg resets the flag so a later genuine crash is
    not misclassified as a migration."""
    relay = request_service._SseRelay()
    out = relay.feed(
        b': checkpoint {"a": 1}\n\n'
        b'data: {"choices":[{"delta":{"content":"hi"}}]}\n\n'
        b": migrating\n\n")
    assert relay.migrating
    assert relay.descriptor == {"a": 1}
    assert b"migrating" not in out and b"hi" in out
    assert relay.delivered_chars == 2
    # _pipe_resume resets the flag per leg.
    relay.migrating = False
    relay.feed(b'data: {"choices":[{"delta":{"content":"yo"}}]}\n\n')
    assert not relay.migrating


# ---- satellite: watchdog-aware drain escalation ---------------------------

def _manager_with_stub_replica(drain_timeout_s=5.0):
    t = [1000.0]
    spec = FleetSpec(
        pools=[PoolSpec(name="decode", command=["true"])],
        port_start=9000, port_end=9001,
        drain_timeout_s=drain_timeout_s)
    mgr = FleetManager(spec, clock=lambda: t[0])
    calls = []
    proc = SimpleNamespace(
        terminate=lambda: calls.append("terminate"),
        kill=lambda: calls.append("kill"),
        poll=lambda: None, pid=0)
    replica = Replica(pool="decode", port=9000,
                      url="http://127.0.0.1:9000", process=proc,
                      state=DRAINING, drain_started=0.0)
    return mgr, replica, calls, t


async def test_escalate_drain_waits_for_busy_healthy_replica():
    mgr, replica, calls, _ = _manager_with_stub_replica()

    async def raw(r):
        return 200, {"status": "draining", "active_requests": 2}

    mgr._probe_health_raw = raw
    await mgr._escalate_drain(replica)
    assert calls == []  # never kills a busy, healthy engine


async def test_escalate_drain_escalates_watchdog_wedged_replica():
    """A watchdog-tripped draining replica never reaches idle; without
    the wedged override one stuck replica wedges the whole rollout."""
    mgr, replica, calls, t = _manager_with_stub_replica()

    async def raw(r):
        return 503, {"status": "watchdog", "active_requests": 2,
                     "stuck_step_s": 9.0}

    mgr._probe_health_raw = raw
    await mgr._escalate_drain(replica)
    assert calls == ["terminate"]
    assert replica.sigterm_sent >= 0
    # Ignored SIGTERM escalates to SIGKILL after the grace window.
    t[0] += 60.0
    await mgr._escalate_drain(replica)
    assert calls == ["terminate", "kill"]


async def test_escalate_drain_respects_timeout_clock():
    mgr, replica, calls, _ = _manager_with_stub_replica(
        drain_timeout_s=5000.0)

    async def raw(r):
        return 503, {"status": "watchdog", "active_requests": 1}

    mgr._probe_health_raw = raw
    await mgr._escalate_drain(replica)  # timeout not yet reached
    assert calls == []


# ---- satellite: operator control channel ----------------------------------

async def test_rollout_cli_pause_resume_abort(tmp_path):
    from production_stack_tpu.fleet.__main__ import send_rollout_command

    ctl = tmp_path / "ctl.json"
    spec = FleetSpec(
        pools=[PoolSpec(name="decode", command=["true"])],
        port_start=9100, port_end=9103,
        rollout_control_path=str(ctl))
    mgr = FleetManager(spec)
    st = mgr.rollout._state["decode"]

    send_rollout_command(spec, "pause", pool="decode")
    st.phase = "bake"
    cmd = mgr.rollout._poll_control()
    assert cmd and cmd["cmd"] == "pause"
    assert await mgr.rollout._apply_command(cmd)
    assert st.phase == "paused" and st.paused_from == "bake"
    # The same command file is never applied twice (ts dedupe).
    assert mgr.rollout._poll_control() is None

    send_rollout_command(spec, "resume")
    assert await mgr.rollout._apply_command(mgr.rollout._poll_control())
    assert st.phase == "bake"

    # resume also unlatches a rolled-back pool's alarm.
    st.phase, st.alarm = "rolled_back", True
    send_rollout_command(spec, "resume")
    assert await mgr.rollout._apply_command(mgr.rollout._poll_control())
    assert st.phase == "idle" and not st.alarm and st.target is None

    # abort abandons the target revision for good.
    st.phase = "bake"
    st.target = RevisionSpec(build_id="v9")
    send_rollout_command(spec, "abort", pool="decode")
    assert await mgr.rollout._apply_command(mgr.rollout._poll_control())
    assert st.phase == "idle" and ("v9", ()) in st.abandoned

    spec.rollout_control_path = ""
    with pytest.raises(SystemExit, match="rollout_control_path"):
        send_rollout_command(spec, "pause")
    await mgr.close()


# ---- satellite: slow-exemplar capture vs dead replica ---------------------

async def test_slow_exemplar_archives_router_side_when_replica_gone():
    """The /debug/trace pull racing a drained replica's exit must not
    cost the exemplar: the router-side waterfall archives alone."""
    from production_stack_tpu import obs
    from production_stack_tpu.obs.slow_archive import SlowArchive

    archive = SlowArchive(capacity=4)
    obs.install(archive=archive)
    session = aiohttp.ClientSession(
        timeout=aiohttp.ClientTimeout(total=1.0))
    router_span = {
        "span": "request", "request_id": "req-dead", "model": "m1",
        "path": "/v1/chat/completions", "priority_class": "default",
        "tenant": None, "backend": "http://127.0.0.1:1",
        "arrival_ts": 100.0, "queue_delay_ms": None, "ttft_ms": 900.0,
        "latency_ms": 1000.0, "chunks": 3, "status": "ok",
    }
    entry = {"request_id": "req-dead", "class": "default",
             "model": "m1", "server": "http://127.0.0.1:1",
             "breach": [{"metric": "ttft", "value_s": 0.9,
                         "target_s": 0.5}]}
    try:
        # Port 1 is never listening: the trace fetch fails instantly,
        # which is exactly the drained-and-exited replica race.
        await request_service._capture_slow_exemplar(
            {"backend_session": session}, "http://127.0.0.1:1",
            "req-dead", router_span, entry)
    finally:
        await session.close()
        obs.install()
    assert archive.depth() == 1
    (archived,) = archive.snapshot()
    assert archived["spans"] == [router_span]
    assert "req-dead" in archived["waterfall"]


# ---- E2E rig ---------------------------------------------------------------

async def _rollout_rig(tmp_path, pool: PoolSpec):
    """Router (real socket, so subprocess engines and the relay talk
    to it over HTTP) + fleet manager + dynamic-config watcher."""
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.dynamic_config import (
        initialize_dynamic_config_watcher,
    )
    from production_stack_tpu.router.routing.logic import (
        initialize_routing_logic,
    )

    request_service.stream_resumes_by_outcome.clear()
    request_service._poison_crashes.clear()
    initialize_service_discovery("static", urls=[], models=[], roles=[])
    initialize_request_stats_monitor(60.0)
    initialize_engine_stats_scraper(3600.0)
    initialize_routing_logic("roundrobin")
    initialize_request_rewriter("noop")
    initialize_resilience(ResilienceConfig(
        max_retries=2, backend_connect_timeout=2.0,
        backend_timeout=60.0, health_check_interval=0.0))
    runner = web.AppRunner(build_app())
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    router_url = ("http://127.0.0.1:"
                  f"{site._server.sockets[0].getsockname()[1]}")

    config_path = tmp_path / "dyn.json"
    base = _free_port()
    spec = FleetSpec(
        pools=[pool], port_start=base, port_end=base + 9,
        router_url=router_url, router_config_path=str(config_path),
        drain_timeout_s=30.0)
    mgr = FleetManager(spec)
    watcher = initialize_dynamic_config_watcher(str(config_path), 3600.0)
    session = aiohttp.ClientSession(
        timeout=aiohttp.ClientTimeout(total=60.0))
    return mgr, watcher, session, router_url, runner


async def _stream_one(session, router_url, n_tokens, sink=None):
    rec = {"status": None, "error": None, "text": ""}
    body = {"model": "m1",
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": n_tokens, "stream": True}
    parts = []
    try:
        async with session.post(router_url + "/v1/chat/completions",
                                json=body) as resp:
            rec["status"] = resp.status
            async for raw in resp.content:
                line = raw.decode("utf-8", "replace").strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                event = json.loads(line[len("data: "):])
                if "choices" not in event:
                    rec["error"] = "terminal SSE error"
                    continue
                delta = event["choices"][0].get("delta") or {}
                if delta.get("content"):
                    parts.append(delta["content"])
    except Exception as e:
        rec["error"] = f"{type(e).__name__}: {e}"
    rec["text"] = "".join(parts)
    if sink is not None:
        sink.append(rec)
    return rec


async def _drive_until(mgr, watcher, pred, desc, deadline_s=60.0,
                       traffic=None):
    deadline = time.time() + deadline_s
    i = 0
    while time.time() < deadline:
        await mgr.reconcile_once()
        watcher.check_and_apply()
        if pred():
            return
        if traffic is not None and i % 3 == 0:
            await traffic()
        i += 1
        await asyncio.sleep(0.05)
    raise AssertionError(f"never reached: {desc}")


def _all_on(mgr, build, count=2):
    reps = mgr.replicas["decode"]
    return (mgr.current_revision["decode"].build_id == build
            and len(reps) == count
            and all(r.build_id == build and r.state == LIVE
                    for r in reps))


async def _teardown_rig(mgr, session, runner):
    try:
        await mgr.drain_all()
    finally:
        for reps in mgr.replicas.values():
            for r in reps:
                if r.process.poll() is None:
                    r.process.kill()
        await mgr.close()
        await session.close()
        await runner.cleanup()


# ---- satellite: drain escalation racing an in-flight migration ------------

async def test_migrate_drain_with_sigterm_escalation_keeps_stream(
        tmp_path):
    """SIGTERM escalation racing a migrate-mode drain: the draining
    replica's checkpointed stream must land on a survivor
    byte-identical under the ``migrated`` outcome, not broken."""
    pool = PoolSpec(
        name="decode", role="decode", min_replicas=2, max_replicas=3,
        model="m1", command=_fake_pool_command(speed=200.0),
        autoscaler=AutoscalerSpec(enable=False),
        revision=RevisionSpec(build_id="v1"),
        rollout=RolloutSpec(enable=False))
    mgr, watcher, session, router_url, runner = await _rollout_rig(
        tmp_path, pool)
    # An aggressive escalation deadline: the reconciler fires SIGTERM
    # at the draining replica while its stream is still migrating.
    mgr.spec.drain_timeout_s = 0.05
    try:
        await _drive_until(mgr, watcher, lambda: _all_on(mgr, "v1"),
                           "2x v1 live")
        victim = min(mgr.replicas["decode"], key=lambda r: r.port)
        n = 400  # 2s at speed=200, checkpoint every 2 tokens
        task = asyncio.ensure_future(
            _stream_one(session, router_url, n))
        # Roundrobin visits sorted URLs, so the first request lands on
        # the min-port replica — the one we drain.
        await asyncio.sleep(0.3)
        await mgr._start_drain(victim, migrate=True)
        watcher.check_and_apply()
        deadline = time.time() + 30.0
        while time.time() < deadline and not task.done():
            await mgr.reconcile_once()  # reap + escalate + respawn
            watcher.check_and_apply()
            await asyncio.sleep(0.05)
        rec = await task
        assert rec["error"] is None and rec["status"] == 200
        assert rec["text"] == "".join(f"tok{i} " for i in range(n))
        outcomes = dict(request_service.stream_resumes_by_outcome)
        assert outcomes.get("migrated", 0) >= 1, outcomes
        assert victim.process.poll() is not None
    finally:
        await _teardown_rig(mgr, session, runner)


# ---- acceptance E2E: good canary + bad canary -----------------------------

async def test_rollout_e2e_good_then_bad_canary(tmp_path):
    """The PR's acceptance invariant: a good canary completes the
    roll with every replica on the new revision and one long
    in-flight stream migrated byte-identically across revisions; a
    fault-injected bad canary is judged, automatically rolled back
    (old revision restored, alarm latched), and post-rollback traffic
    is clean — zero 5xx / dropped requests throughout."""
    from production_stack_tpu.fleet.autoscaler import (
        parse_prometheus_text,
    )

    pool = PoolSpec(
        name="decode", role="decode", min_replicas=2, max_replicas=4,
        model="m1", command=_fake_pool_command(speed=200.0),
        autoscaler=AutoscalerSpec(enable=False),
        revision=RevisionSpec(build_id="v1"),
        # No SLO ledger or drift sentinel in this rig: judge on crash
        # streak + canary-vs-stable p99 latency ratio.
        rollout=RolloutSpec(
            enable=True, canary_weight=0.5, bake_s=1.5,
            max_slo_burn_rate_5m=0.0, fail_on_perf_drift=False,
            max_crash_streak=1, max_latency_ratio=3.0,
            drain_mode="migrate"))
    mgr, watcher, session, router_url, runner = await _rollout_rig(
        tmp_path, pool)
    results = []

    async def burst():
        await asyncio.gather(*(
            _stream_one(session, router_url, 16, sink=results)
            for _ in range(4)))

    async def gauge(name):
        async with session.get(router_url + "/metrics") as resp:
            text = await resp.text()
        for mname, labels, value in parse_prometheus_text(text):
            if mname == name and labels.get("pool") == "decode":
                return value
        return -1.0

    try:
        await _drive_until(mgr, watcher, lambda: _all_on(mgr, "v1"),
                           "2x v1 live")

        # -- good canary: long stream in flight across the whole roll
        n = 1600  # 8s at speed=200: outlives canary+bake+judge+roll
        long_task = asyncio.ensure_future(
            _stream_one(session, router_url, n))
        await asyncio.sleep(0.3)
        pool.revision = RevisionSpec(build_id="v2")
        await _drive_until(mgr, watcher, lambda: _all_on(mgr, "v2"),
                           "fleet rolled to v2", deadline_s=90.0,
                           traffic=burst)
        long_rec = await long_task
        assert long_rec["error"] is None and long_rec["status"] == 200
        assert long_rec["text"] == \
            "".join(f"tok{i} " for i in range(n))
        outcomes = dict(request_service.stream_resumes_by_outcome)
        assert outcomes.get("migrated", 0) >= 1, outcomes
        # Every replica reports the new build from /health.
        for replica in mgr.replicas["decode"]:
            payload = await mgr._probe_health(replica)
            assert payload and payload["build_id"] == "v2"
        assert mgr.rollout.status() == {}  # idle again, no alarm

        # -- bad canary: degraded TTFT must fail the latency judge
        pool.rollout.bake_s = 4.0
        pool.revision = RevisionSpec(
            build_id="v3",
            engine_flags=["--fault", "degrade_new_revision",
                          "--slow-ttft-s", "1.0",
                          "--slow-itl-s", "0.05"])

        def rolled_back():
            st = mgr.rollout.status().get("decode") or {}
            return st.get("phase") == "rolled_back"

        await _drive_until(mgr, watcher, rolled_back,
                           "bad canary rolled back", deadline_s=90.0,
                           traffic=burst)
        status = mgr.rollout.status()["decode"]
        assert status["alarm"] and status["rollbacks"] >= 1
        assert "canary" in status["verdict"]
        # Old revision restored; the alarm gauge is latched on
        # /metrics until an operator resumes.
        await _drive_until(mgr, watcher, lambda: _all_on(mgr, "v2"),
                           "stable set restored on v2",
                           deadline_s=60.0)
        assert await gauge("vllm:rollout_alarm") == 1.0
        assert await gauge("vllm:rollout_rollbacks_total") >= 1.0
        # A frozen pool ignores the (still-bad) spec revision.
        await mgr.reconcile_once()
        assert mgr.rollout.status()["decode"]["phase"] == "rolled_back"

        # Post-rollback traffic is clean.
        await burst()
        assert results and all(
            r["status"] == 200 and r["error"] is None
            for r in results), [r for r in results
                                if r["status"] != 200 or r["error"]]
    finally:
        await _teardown_rig(mgr, session, runner)
