"""Request abort paths: client disconnects hit abort_request while a
sequence is waiting, mid-prefill, or mid-decode-burst; pages must be
freed, the batch must keep serving, and terminal outputs must reach
the engine's step() consumers (server streams read finish_reason from
them)."""

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import (
    SamplingParams,
    SequenceState,
)


def _engine(decode_steps=4, num_pages=64):
    return LLMEngine(EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=num_pages),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32,
                                  decode_steps=decode_steps),
    ))


def _sampling(max_tokens=64):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0,
                          ignore_eos=True)


def test_abort_while_waiting_frees_slot():
    eng = _engine()
    sid = eng.add_request(list(range(1, 20)), _sampling())
    assert eng.scheduler.num_waiting == 1
    eng.abort_request(sid)
    assert eng.scheduler.num_waiting == 0
    assert sid not in eng.sequences
    assert not eng.has_work()


def test_abort_mid_decode_frees_pages_and_batch_continues():
    eng = _engine(decode_steps=4)
    free_before = eng.cache_manager.num_free_pages
    victim = eng.add_request(list(range(1, 30)), _sampling())
    survivor = eng.add_request(list(range(40, 60)), _sampling(8))
    seqs = dict(eng.sequences)

    # Run until both are decoding, then abort one mid-stream.
    for _ in range(30):
        eng.step()
        if (seqs[victim].state == SequenceState.RUNNING
                and seqs[survivor].state == SequenceState.RUNNING):
            break
    assert seqs[victim].state == SequenceState.RUNNING
    eng.abort_request(victim)
    assert seqs[victim].state == SequenceState.ABORTED
    assert seqs[victim].pages == []  # KV pages returned

    # The survivor must finish normally with the victim gone.
    while eng.has_work():
        eng.step()
    assert seqs[survivor].state == SequenceState.FINISHED
    assert len(seqs[survivor].output_token_ids) == 8
    # Every page is reclaimable again (committed prefix pages are
    # evictable, which num_free_pages counts).
    assert eng.cache_manager.num_free_pages == free_before
    assert eng.scheduler.num_running == 0


def test_abort_is_idempotent_and_unknown_ids_are_noops():
    eng = _engine()
    sid = eng.add_request(list(range(1, 10)), _sampling(4))
    eng.abort_request(sid)
    eng.abort_request(sid)          # second abort: no-op
    eng.abort_request("no-such-id")  # unknown: no-op
    assert not eng.has_work()


def test_oversized_prompt_rejected_at_admission():
    """Prompts that can never fit are rejected synchronously at
    add_request (the server maps this to an HTTP 4xx), marked ABORTED,
    and leave no scheduler state behind."""
    import pytest

    eng = _engine(num_pages=8)  # 7 usable pages = 112 tokens
    ok = eng.add_request(list(range(1, 20)), _sampling(4))
    with pytest.raises(ValueError, match="cannot fit|max_model_len"):
        eng.add_request(list(range(1, 300)), _sampling(4))
    assert eng.scheduler.num_waiting == 1  # only the ok request

    finished = {}
    while eng.has_work():
        for out in eng.step():
            if out.finished:
                finished[out.seq_id] = out.finish_reason
    assert finished.get(ok) == "length"
