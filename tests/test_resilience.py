"""Router resilience layer: circuit breakers, active health checking,
retry-with-failover, timeouts, and status-code semantics — driven
end-to-end through the fault-injecting fake engine.

The acceptance scenario from the resilience issue lives in
``test_failover_e2e_and_breaker_recovery``: three backends of which one
refuses connections and one returns 500s; every client request must
succeed via failover with zero 502s, both bad endpoints' breakers must
open (visible in /metrics), and traffic must recover through half-open
probes once the faults clear.
"""

import asyncio
import socket
import threading
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.router.resilience import (
    BreakerState,
    CircuitBreaker,
    ResilienceConfig,
    get_resilience,
    initialize_resilience,
)
from production_stack_tpu.router.service_discovery import (
    EndpointInfo,
    K8sServiceDiscovery,
    initialize_service_discovery,
)
from production_stack_tpu.router.services.rewriter import (
    initialize_request_rewriter,
)
from production_stack_tpu.router.stats.engine_stats import (
    initialize_engine_stats_scraper,
)
from production_stack_tpu.router.stats.request_stats import (
    get_request_stats_monitor,
    initialize_request_stats_monitor,
)
from production_stack_tpu.testing.fake_engine import build_fake_engine


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def _breaker_cfg(**overrides):
    defaults = dict(
        breaker_window=10, breaker_min_volume=3, breaker_failure_rate=0.5,
        breaker_open_base_s=1.0, breaker_open_max_s=8.0,
        breaker_jitter=0.0, health_check_interval=0.0,
    )
    defaults.update(overrides)
    return ResilienceConfig(**defaults)


def _free_port_url() -> str:
    """A URL on a port nothing listens on: connection refused."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"http://127.0.0.1:{port}"


# ---- circuit breaker unit tests -------------------------------------------

def test_breaker_opens_on_failure_rate():
    clock = FakeClock()
    br = CircuitBreaker(_breaker_cfg(), clock=clock)
    assert br.state == BreakerState.CLOSED
    br.record_failure()
    br.record_failure()
    assert br.state == BreakerState.CLOSED  # below min volume
    assert br.can_attempt()
    br.record_failure()
    assert br.state == BreakerState.OPEN
    assert not br.can_attempt()
    assert 0 < br.time_until_half_open() <= 1.0


def test_breaker_mixed_outcomes_below_rate_stay_closed():
    clock = FakeClock()
    br = CircuitBreaker(_breaker_cfg(), clock=clock)
    for _ in range(6):
        br.record_success()
    for _ in range(3):
        br.record_failure()
    # 3/9 failures < 0.5 rate: stays closed.
    assert br.state == BreakerState.CLOSED


def test_breaker_half_open_probe_cycle_and_backoff_growth():
    clock = FakeClock()
    br = CircuitBreaker(_breaker_cfg(), clock=clock)
    for _ in range(3):
        br.record_failure()
    assert br.state == BreakerState.OPEN
    first_backoff = br.time_until_half_open()
    assert first_backoff == pytest.approx(1.0)

    # Backoff not yet elapsed: no attempts admitted.
    clock.advance(0.5)
    assert not br.can_attempt()

    # Elapsed: exactly one half-open probe slot.
    clock.advance(0.6)
    assert br.can_attempt()
    br.on_attempt()
    assert br.state == BreakerState.HALF_OPEN
    assert not br.can_attempt()  # probe slot taken

    # Failed probe: reopen with doubled backoff.
    br.record_failure()
    assert br.state == BreakerState.OPEN
    assert br.time_until_half_open() == pytest.approx(2.0)

    # Successful probe closes and resets the backoff ladder.
    clock.advance(2.1)
    br.on_attempt()
    br.record_success()
    assert br.state == BreakerState.CLOSED
    assert br.can_attempt()
    assert br.opens_total == 2


def test_breaker_on_attempt_admission_is_atomic():
    """The half-open cap is enforced at dispatch (on_attempt), not just
    in the advisory can_attempt pre-filter: concurrent requests that all
    saw can_attempt()==True race for the slot and only one wins."""
    clock = FakeClock()
    br = CircuitBreaker(_breaker_cfg(), clock=clock)
    for _ in range(3):
        br.record_failure()
    assert br.state == BreakerState.OPEN
    # Backoff not elapsed: admission (not just the pre-filter) refuses.
    assert not br.on_attempt()
    assert br.state == BreakerState.OPEN

    clock.advance(1.1)
    # Both callers passed can_attempt before either dispatched.
    assert br.can_attempt()
    assert br.can_attempt()
    assert br.on_attempt()       # wins the probe slot
    assert not br.on_attempt()   # loser is turned away atomically
    assert br.state == BreakerState.HALF_OPEN
    assert br._half_open_inflight == 1


def test_breaker_release_attempt_frees_probe_slot():
    """An admitted probe whose request ends with neither success nor
    failure (client disconnect) must release its slot — otherwise the
    breaker wedges in HALF_OPEN forever and the endpoint is blackholed
    until restart."""
    clock = FakeClock()
    br = CircuitBreaker(_breaker_cfg(), clock=clock)
    for _ in range(3):
        br.record_failure()
    clock.advance(1.1)
    assert br.on_attempt()
    assert not br.on_attempt()  # slot taken
    # Client disconnected mid-probe: no verdict on the backend.
    br.release_attempt()
    assert br.state == BreakerState.HALF_OPEN
    assert br._half_open_inflight == 0
    # The next request rides as the probe and can close the breaker.
    assert br.can_attempt()
    assert br.on_attempt()
    br.record_success()
    assert br.state == BreakerState.CLOSED


def test_breaker_release_attempt_noop_when_closed():
    br = CircuitBreaker(_breaker_cfg(), clock=FakeClock())
    assert br.on_attempt()
    br.release_attempt()  # no state to unwind when closed
    assert br.state == BreakerState.CLOSED
    assert br.can_attempt()


def test_client_timeout_bounds_reads_not_total():
    """--backend-timeout is a per-read stall bound, never a total
    deadline: a legitimate generation longer than the flag must not be
    aborted mid-stream (and blamed on a healthy backend)."""
    t = ResilienceConfig(backend_connect_timeout=3.0,
                         backend_timeout=42.0).client_timeout()
    assert t.total is None
    assert t.sock_connect == 3.0
    assert t.sock_read == 42.0
    unbounded = ResilienceConfig(backend_connect_timeout=0.0,
                                 backend_timeout=0.0).client_timeout()
    assert unbounded.total is None
    assert unbounded.sock_connect is None
    assert unbounded.sock_read is None


def test_breaker_backoff_capped():
    clock = FakeClock()
    br = CircuitBreaker(_breaker_cfg(breaker_open_max_s=4.0), clock=clock)
    for round_ in range(6):
        if round_ == 0:
            for _ in range(3):
                br.record_failure()
        else:
            clock.advance(100.0)
            br.on_attempt()
            br.record_failure()
    assert br.time_until_half_open() <= 4.0


# ---- discovery semantics (wildcard fix, probe failure) --------------------

def test_serves_model_wildcard_semantics():
    # Historical wildcard: empty list + wildcard=True serves everything
    # (static discovery without --static-models).
    assert EndpointInfo(url="http://a").serves_model("anything")
    # Authoritative empty list (probed): serves nothing.
    assert not EndpointInfo(url="http://a", wildcard=False).serves_model("m")
    assert EndpointInfo(
        url="http://a", model_names=["m"], wildcard=False
    ).serves_model("m")


def test_probe_models_returns_none_on_failure():
    # A refused connection must yield None ("unknown"), never [] — an
    # empty list would previously wildcard-match every model.
    assert K8sServiceDiscovery._probe_models(_free_port_url()) is None


def _bare_k8s_discovery():
    """A K8sServiceDiscovery with just the state _reprobe_pass touches —
    no kubernetes client, no watch threads."""
    sd = object.__new__(K8sServiceDiscovery)
    sd._endpoints = {}
    sd._pending_probe = {}
    sd._probe_generation = 0
    sd._lock = threading.Lock()
    sd._running = False
    return sd


def test_reprobe_pass_success_promotes_pod():
    sd = _bare_k8s_discovery()
    sd._pending_probe["pod"] = ("http://10.0.0.1:8000", 1, 0.0, 1)
    sd._probe_models = lambda url: ["m1"]
    sd._reprobe_pass(now=10.0)
    assert sd._pending_probe == {}
    ep = sd._endpoints["pod"]
    assert ep.url == "http://10.0.0.1:8000"
    assert ep.model_names == ["m1"] and not ep.wildcard


def test_reprobe_pass_discards_stale_generation():
    """A watch event that re-registers the pod (same URL, generation
    bumped, attempts reset) while a re-probe is in flight must win: the
    stale pass may neither overwrite the fresh attempt count nor evict
    the pod based on its stale one."""
    sd = _bare_k8s_discovery()
    url = "http://10.0.0.1:8000"
    # One failure away from permanent eviction under the old counter.
    sd._pending_probe["pod"] = (
        url, K8sServiceDiscovery._REPROBE_MAX_ATTEMPTS - 1, 0.0, 7)

    def probe(probed_url):
        # Mid-probe, the watch re-registers the same pod URL afresh.
        sd._probe_generation = 8
        sd._pending_probe["pod"] = (url, 0, 9999.0, 8)
        return None  # and this (stale) probe fails

    sd._probe_models = probe
    sd._reprobe_pass(now=10.0)
    # The fresh registration survived untouched: not deleted, attempts
    # still 0, schedule unchanged.
    assert sd._pending_probe["pod"] == (url, 0, 9999.0, 8)
    assert "pod" not in sd._endpoints


def test_reprobe_pass_evicts_after_max_attempts():
    sd = _bare_k8s_discovery()
    sd._pending_probe["pod"] = (
        "http://10.0.0.1:8000",
        K8sServiceDiscovery._REPROBE_MAX_ATTEMPTS - 1, 0.0, 3)
    sd._probe_models = lambda url: None
    sd._reprobe_pass(now=10.0)
    assert sd._pending_probe == {}
    assert sd._endpoints == {}


# ---- health checker -------------------------------------------------------

async def test_health_checker_marks_and_recovers():
    engine = TestServer(build_fake_engine(model="m1", speed=1000, ttft=0.0))
    await engine.start_server()
    url = f"http://127.0.0.1:{engine.port}"
    try:
        discovery = initialize_service_discovery(
            "static", urls=[url], models=["m1"])
        mgr = initialize_resilience(ResilienceConfig(
            health_check_interval=5.0, health_check_timeout=1.0,
            health_failure_threshold=2, health_success_threshold=2,
        ))
        checker = mgr.health
        assert checker is not None

        await checker.probe_all()
        assert checker.is_healthy(url)
        assert [ep.url for ep in discovery.get_endpoint_info()] == [url]

        engine.app["state"].fault = "unhealthy"
        await checker.probe_all()
        assert checker.is_healthy(url)  # one failure < threshold
        await checker.probe_all()
        assert not checker.is_healthy(url)
        # Dead backend left rotation (static discovery too, not just
        # the K8s pod-watch path) but is still discoverable raw.
        assert discovery.get_endpoint_info() == []
        assert [ep.url for ep in
                discovery.get_endpoint_info(include_unhealthy=True)] == [url]
        # The discovery module itself is still healthy.
        assert discovery.get_health()

        engine.app["state"].fault = None
        await checker.probe_all()
        assert not checker.is_healthy(url)  # one success < threshold
        await checker.probe_all()
        assert checker.is_healthy(url)
        assert [ep.url for ep in discovery.get_endpoint_info()] == [url]
    finally:
        await engine.close()


# ---- router stack helper --------------------------------------------------

async def _start_router(urls, models, config):
    """Initialize the router singletons against *urls* and return a
    started TestClient for the router app."""
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.routing.logic import (
        initialize_routing_logic,
    )

    initialize_service_discovery("static", urls=urls, models=models)
    initialize_request_stats_monitor(60.0)
    initialize_engine_stats_scraper(3600.0)
    initialize_routing_logic("roundrobin")
    initialize_request_rewriter("noop")
    initialize_resilience(config)
    client = TestClient(TestServer(build_app()))
    await client.start_server()
    return client


def _chat_body(model, stream=False, max_tokens=3):
    return {
        "model": model,
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": max_tokens,
        "stream": stream,
    }


# ---- status-code semantics ------------------------------------------------

async def test_unknown_model_404_vs_no_capacity_503():
    engine = TestServer(build_fake_engine(model="m1", speed=1000, ttft=0.0))
    await engine.start_server()
    url = f"http://127.0.0.1:{engine.port}"
    client = await _start_router([url], ["m1"], ResilienceConfig(
        health_check_interval=5.0, health_failure_threshold=1,
    ))
    try:
        # Unknown model: 404, not 400 — "wrong request".
        resp = await client.post("/v1/chat/completions",
                                 json=_chat_body("no-such-model"))
        assert resp.status == 404

        # Body problems are still 400s.
        resp = await client.post("/v1/chat/completions", json={"x": 1})
        assert resp.status == 400

        # Known model, healthy endpoint: serves fine.
        resp = await client.post("/v1/chat/completions",
                                 json=_chat_body("m1"))
        assert resp.status == 200

        # Known model but its only endpoint failed health checks:
        # 503 "no capacity" with a Retry-After hint, not 400/502.
        mgr = get_resilience()
        mgr.health.record_probe(url, False)
        resp = await client.post("/v1/chat/completions",
                                 json=_chat_body("m1"))
        assert resp.status == 503
        assert int(resp.headers["Retry-After"]) >= 1
        data = await resp.json()
        assert "m1" in data["error"]["message"]
    finally:
        await client.close()
        await engine.close()


# ---- acceptance: failover, breakers, recovery -----------------------------

async def test_failover_e2e_and_breaker_recovery():
    good = TestServer(build_fake_engine(model="m1", speed=1000, ttft=0.0))
    bad500 = TestServer(build_fake_engine(
        model="m1", speed=1000, ttft=0.0, fault="error500"))
    await good.start_server()
    await bad500.start_server()
    good_url = f"http://127.0.0.1:{good.port}"
    bad500_url = f"http://127.0.0.1:{bad500.port}"
    refused_url = _free_port_url()
    urls = [refused_url, bad500_url, good_url]

    client = await _start_router(urls, ["m1"] * 3, ResilienceConfig(
        max_retries=2,
        backend_connect_timeout=1.0, backend_timeout=10.0,
        health_check_interval=0.0,  # breakers only: deterministic
        breaker_min_volume=2, breaker_window=10,
        breaker_failure_rate=0.5,
        breaker_open_base_s=0.4, breaker_open_max_s=2.0,
        breaker_jitter=0.0,
    ))
    statuses = []
    try:
        # Phase 1: two of three backends are broken. Every request must
        # still succeed by failing over within its retry budget.
        for _ in range(8):
            resp = await client.post("/v1/chat/completions",
                                     json=_chat_body("m1"))
            statuses.append(resp.status)
            await resp.read()
        assert statuses == [200] * 8
        assert good.app["state"].total_served == 8

        # Both bad endpoints' breakers opened, visible in /metrics.
        mgr = get_resilience()
        assert mgr.breaker(refused_url).state == BreakerState.OPEN
        assert mgr.breaker(bad500_url).state == BreakerState.OPEN
        metrics = await (await client.get("/metrics")).text()
        for bad in (refused_url, bad500_url):
            assert (f'vllm:circuit_breaker_state'
                    f'{{server="{bad}"}} 2.0') in metrics
        assert f'vllm:circuit_breaker_state{{server="{good_url}"}} 0.0' \
            in metrics
        assert mgr.retries_total > 0

        # /health surfaces the tripped breakers.
        health = await (await client.get("/health")).json()
        assert set(health["resilience"]["tripped_breakers"]) == {
            refused_url, bad500_url}
        assert health["resilience"]["endpoints_available"] == 1

        # Phase 2: clear the 500 fault and wait out the backoff; traffic
        # must flow back through a successful half-open probe.
        bad500.app["state"].fault = None
        deadline = time.monotonic() + 5.0
        while (mgr.breaker(bad500_url).time_until_half_open() > 0
               and time.monotonic() < deadline):
            await asyncio.sleep(0.05)
        before = bad500.app["state"].total_served
        recovery = []
        for _ in range(8):
            r = await client.post("/v1/chat/completions",
                                  json=_chat_body("m1"))
            recovery.append(r.status)
            await r.read()
        assert recovery == [200] * 8
        assert bad500.app["state"].total_served > before
        assert mgr.breaker(bad500_url).state == BreakerState.CLOSED
        metrics = await (await client.get("/metrics")).text()
        assert (f'vllm:circuit_breaker_state{{server="{bad500_url}"}} 0.0'
                in metrics)
        # Zero 502s across the whole scenario.
        assert 502 not in statuses + recovery
    finally:
        await client.close()
        await good.close()
        await bad500.close()


async def test_all_backends_down_returns_503_retry_after():
    refused_a, refused_b = _free_port_url(), _free_port_url()
    client = await _start_router(
        [refused_a, refused_b], ["m1", "m1"], ResilienceConfig(
            max_retries=2, backend_connect_timeout=0.5,
            health_check_interval=0.0,
            breaker_min_volume=1, breaker_failure_rate=0.1,
            breaker_open_base_s=5.0, breaker_jitter=0.0,
        ))
    try:
        # First request exhausts its budget against dead backends: the
        # breakers trip (min_volume=1) and the error is upstream-shaped.
        resp = await client.post("/v1/chat/completions",
                                 json=_chat_body("m1"))
        assert resp.status in (502, 503)
        # Now every breaker is open: shed with 503 + Retry-After.
        resp = await client.post("/v1/chat/completions",
                                 json=_chat_body("m1"))
        assert resp.status == 503
        assert int(resp.headers["Retry-After"]) >= 1
        mgr = get_resilience()
        assert mgr.shed_requests_total >= 1
    finally:
        await client.close()


async def test_hang_times_out_and_fails_over():
    good = TestServer(build_fake_engine(model="m1", speed=1000, ttft=0.0))
    hang = TestServer(build_fake_engine(
        model="m1", speed=1000, ttft=0.0, fault="hang"))
    await good.start_server()
    await hang.start_server()
    urls = [f"http://127.0.0.1:{hang.port}", f"http://127.0.0.1:{good.port}"]
    client = await _start_router(urls, ["m1", "m1"], ResilienceConfig(
        max_retries=1, backend_connect_timeout=1.0, backend_timeout=0.7,
        health_check_interval=0.0, breaker_min_volume=2,
        breaker_jitter=0.0,
    ))
    try:
        start = time.monotonic()
        # Two requests: round-robin guarantees at least one of them
        # starts on the hanging backend and must time out + fail over.
        for _ in range(2):
            resp = await client.post("/v1/chat/completions",
                                     json=_chat_body("m1"))
            assert resp.status == 200
            await resp.read()
        elapsed = time.monotonic() - start
        assert elapsed < 5.0  # bounded by the 0.7s total timeout, not ∞
        assert good.app["state"].total_served == 2
        assert get_resilience().retries_total >= 1
    finally:
        await client.close()
        await good.close()
        await hang.close()


async def test_midstream_abort_never_retried():
    """A stream that already sent its first byte downstream must not be
    retried on another backend, but the breaker and the request-stats
    kill accounting must both hear about the death."""
    good = TestServer(build_fake_engine(model="m1", speed=2000, ttft=0.0))
    abort = TestServer(build_fake_engine(
        model="m1", speed=2000, ttft=0.0, fault="abort_mid_stream"))
    await good.start_server()
    await abort.start_server()
    good_url = f"http://127.0.0.1:{good.port}"
    abort_url = f"http://127.0.0.1:{abort.port}"
    client = await _start_router(
        [abort_url, good_url], ["m1", "m1"], ResilienceConfig(
            max_retries=2, health_check_interval=0.0,
            breaker_min_volume=5, breaker_jitter=0.0,
        ))
    try:
        bodies = []
        for _ in range(2):
            resp = await client.post(
                "/v1/chat/completions",
                json=_chat_body("m1", stream=True, max_tokens=8))
            assert resp.status == 200  # headers were streamed pre-abort
            try:
                bodies.append(await resp.text())
            except Exception:
                bodies.append("")  # truncated stream may error on read
        # Round-robin sent one request to each engine; the aborted one
        # ends with an honest in-band terminal error (no checkpoint was
        # relayed, so mid-stream failover cannot resume it --
        # docs/crash_recovery.md) followed by [DONE]; the other
        # completed normally.
        assert all(b.rstrip().endswith("data: [DONE]") for b in bodies)
        error_flags = sorted('"type": "upstream_error"' in b for b in bodies)
        assert error_flags == [False, True]
        # No retry happened: each engine saw exactly one request, and
        # the failover counters never moved.
        assert good.app["state"].requests_received == 1
        assert abort.app["state"].requests_received == 1
        mgr = get_resilience()
        assert mgr.retries_total == 0
        assert mgr.failovers_total == 0
        # The breaker heard about the mid-stream death...
        assert mgr.breaker(abort_url)._window.count(False) == 1
        # ...and kill accounting cleaned up the in-flight request.
        stats = get_request_stats_monitor().get_request_stats(time.time())
        assert stats[abort_url].in_prefill_requests == 0
        assert stats[abort_url].in_decoding_requests == 0
    finally:
        await client.close()
        await good.close()
        await abort.close()


async def test_client_disconnect_during_half_open_probe_releases_slot():
    """THE wedge scenario: a client that hangs up during the recovery
    probe (common when clients time out during an outage) must release
    the half-open slot — not leak it and blackhole the endpoint until a
    router restart."""
    engine = TestServer(build_fake_engine(model="m1", speed=5, ttft=0.0))
    await engine.start_server()
    url = f"http://127.0.0.1:{engine.port}"
    client = await _start_router([url], ["m1"], ResilienceConfig(
        max_retries=0, health_check_interval=0.0,
        breaker_min_volume=1, breaker_failure_rate=0.1,
        breaker_open_base_s=0.1, breaker_jitter=0.0,
    ))
    try:
        mgr = get_resilience()
        br = mgr.breaker(url)
        br.record_failure()
        assert br.state == BreakerState.OPEN
        await asyncio.sleep(0.15)  # open backoff elapses

        # The recovery probe: a slow stream whose client walks away.
        resp = await client.post(
            "/v1/chat/completions",
            json=_chat_body("m1", stream=True, max_tokens=50))
        assert resp.status == 200
        await resp.content.readany()
        resp.close()

        # The probe slot must come back; the breaker may not wedge in
        # HALF_OPEN with every future attempt refused.
        deadline = time.monotonic() + 5.0
        while (br._half_open_inflight > 0
               and time.monotonic() < deadline):
            await asyncio.sleep(0.05)
        assert br.state == BreakerState.HALF_OPEN
        assert br._half_open_inflight == 0
        assert br.can_attempt()

        # The next request rides as the probe and closes the breaker.
        r2 = await client.post("/v1/chat/completions",
                               json=_chat_body("m1", max_tokens=2))
        assert r2.status == 200
        await r2.read()
        assert br.state == BreakerState.CLOSED
    finally:
        await client.close()
        await engine.close()


async def test_long_stream_outlives_backend_timeout():
    """--backend-timeout bounds per-read stalls, not the exchange: a
    generation that streams for longer than the flag (with small
    inter-chunk gaps) completes, and the healthy backend is not blamed."""
    engine = TestServer(build_fake_engine(model="m1", speed=10, ttft=0.0))
    await engine.start_server()
    url = f"http://127.0.0.1:{engine.port}"
    client = await _start_router([url], ["m1"], ResilienceConfig(
        max_retries=0, backend_connect_timeout=1.0, backend_timeout=0.3,
        health_check_interval=0.0, breaker_min_volume=1,
        breaker_failure_rate=0.1, breaker_jitter=0.0,
    ))
    try:
        start = time.monotonic()
        resp = await client.post(
            "/v1/chat/completions",
            json=_chat_body("m1", stream=True, max_tokens=10))
        body = await resp.text()
        elapsed = time.monotonic() - start
        assert resp.status == 200
        assert "data: [DONE]" in body
        assert elapsed > 0.3  # stream genuinely outlived the bound
        mgr = get_resilience()
        assert mgr.breaker(url).state == BreakerState.CLOSED
        assert mgr.retries_total == 0
    finally:
        await client.close()
        await engine.close()


# ---- tracing annotation ---------------------------------------------------

def test_span_records_failover_backends():
    import json as json_mod

    from production_stack_tpu.router.tracing import RequestSpan

    span = RequestSpan("rid", "m", "/v1/chat/completions")
    span.on_routed("http://dead:1")
    span.on_routed("http://alive:2")
    span.finish("ok")
    data = json_mod.loads(span.to_json())
    assert data["retries"] == 1
    assert data["tried_backends"] == ["http://dead:1"]
    assert data["backend"] == "http://alive:2"
