"""Tests for the native C++ control-plane agent.

Covers the same surface the reference covers with envtest + controller
tests (src/router-controller/internal/controller/
staticroute_controller_test.go:1-80): spec -> rendered dynamic config,
idempotent re-reconcile, invalid-spec status, router health probing with
thresholds, and k8s-mode ConfigMap/status reconciliation (here against a
fake API server instead of envtest binaries).
"""

import json
import subprocess
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from production_stack_tpu import controlplane


@pytest.fixture(scope="module")
def agent_binary():
    try:
        return controlplane.ensure_built()
    except controlplane.BuildError as e:
        pytest.skip(f"cannot build controlplane agent: {e}")


def write_spec(spec_dir, name, spec):
    spec_dir.mkdir(parents=True, exist_ok=True)
    (spec_dir / f"{name}.json").write_text(json.dumps(spec))


def read_json(path):
    return json.loads(path.read_text())


BASE_SPEC = {
    "routingLogic": "session",
    "sessionKey": "x-user-id",
    "staticBackends": "http://127.0.0.1:9001,http://127.0.0.1:9002",
    "staticModels": ["llama-8b", "opt-125m"],
}


def test_file_mode_renders_dynamic_config(agent_binary, tmp_path):
    write_spec(tmp_path / "specs", "route-a", BASE_SPEC)
    proc = controlplane.run_once(
        spec_dir=str(tmp_path / "specs"), out_dir=str(tmp_path / "out")
    )
    assert proc.returncode == 0, proc.stderr

    config = read_json(
        tmp_path / "out" / "route-a-config" / "dynamic_config.json"
    )
    assert config == {
        "service_discovery": "static",
        "routing_logic": "session",
        "session_key": "x-user-id",
        "static_backends": "http://127.0.0.1:9001,http://127.0.0.1:9002",
        "static_models": "llama-8b,opt-125m",
    }
    status = read_json(tmp_path / "out" / "status" / "route-a.json")
    assert status["conditions"][0]["type"] == "Ready"
    assert status["conditions"][0]["status"] == "True"
    assert status["configMapRef"] == "route-a-config"
    assert "lastAppliedTime" in status


def test_rendered_config_loads_in_router_watcher(agent_binary, tmp_path):
    """The agent's output must satisfy the router's from_json contract."""
    from production_stack_tpu.router.dynamic_config import (
        DynamicRouterConfig,
    )

    write_spec(tmp_path / "specs", "route-w", BASE_SPEC)
    controlplane.run_once(
        spec_dir=str(tmp_path / "specs"), out_dir=str(tmp_path / "out")
    )
    text = (
        tmp_path / "out" / "route-w-config" / "dynamic_config.json"
    ).read_text()
    config = DynamicRouterConfig.from_json(text)
    assert config.routing_logic == "session"
    assert config.static_backends == [
        "http://127.0.0.1:9001",
        "http://127.0.0.1:9002",
    ]
    assert config.static_models == ["llama-8b", "opt-125m"]
    assert config.session_key == "x-user-id"


def test_file_mode_idempotent_and_updates_on_change(agent_binary, tmp_path):
    specs = tmp_path / "specs"
    out = tmp_path / "out"
    write_spec(specs, "r", BASE_SPEC)
    controlplane.run_once(spec_dir=str(specs), out_dir=str(out))
    first = read_json(out / "status" / "r.json")["lastAppliedTime"]
    cfg_path = out / "r-config" / "dynamic_config.json"
    mtime = cfg_path.stat().st_mtime_ns

    # Unchanged spec: config file is not rewritten, applied time kept.
    controlplane.run_once(spec_dir=str(specs), out_dir=str(out))
    assert cfg_path.stat().st_mtime_ns == mtime
    assert read_json(out / "status" / "r.json")["lastAppliedTime"] == first

    # Changed spec: re-rendered.
    changed = dict(BASE_SPEC, routingLogic="llq")
    changed.pop("sessionKey")
    write_spec(specs, "r", changed)
    controlplane.run_once(spec_dir=str(specs), out_dir=str(out))
    assert read_json(cfg_path)["routing_logic"] == "llq"
    assert "session_key" not in read_json(cfg_path)


def test_least_loaded_alias_and_cr_shape(agent_binary, tmp_path):
    """Accepts the reference CRD's least_loaded name and full CR shape."""
    cr = {
        "apiVersion": "production-stack.tpu/v1alpha1",
        "kind": "StaticRoute",
        "metadata": {"name": "cr-named", "namespace": "default"},
        "spec": {
            "routingLogic": "least_loaded",
            "staticBackends": "http://e:8000",
            "staticModels": "m",
            "configMapName": "custom-config",
        },
    }
    write_spec(tmp_path / "specs", "file-name", cr)
    controlplane.run_once(
        spec_dir=str(tmp_path / "specs"), out_dir=str(tmp_path / "out")
    )
    # metadata.name wins over the file name; configMapName wins for output.
    config = read_json(
        tmp_path / "out" / "custom-config" / "dynamic_config.json"
    )
    assert config["routing_logic"] == "llq"
    status = read_json(tmp_path / "out" / "status" / "cr-named.json")
    assert status["configMapRef"] == "custom-config"


@pytest.mark.parametrize(
    "bad_spec,reason_substr",
    [
        ({"staticModels": "m"}, "staticBackends"),
        ({"staticBackends": "http://e:8000"}, "staticModels"),
        (
            dict(BASE_SPEC, routingLogic="banana"),
            "routingLogic",
        ),
        (
            {
                "routingLogic": "session",
                "staticBackends": "http://e:8000",
                "staticModels": "m",
            },
            "sessionKey",
        ),
    ],
)
def test_invalid_specs_report_not_ready(
    agent_binary, tmp_path, bad_spec, reason_substr
):
    write_spec(tmp_path / "specs", "bad", bad_spec)
    proc = controlplane.run_once(
        spec_dir=str(tmp_path / "specs"), out_dir=str(tmp_path / "out")
    )
    assert proc.returncode == 0
    status = read_json(tmp_path / "out" / "status" / "bad.json")
    cond = status["conditions"][0]
    assert cond["status"] == "False"
    assert cond["reason"] == "InvalidSpec"
    assert reason_substr in cond["message"]
    assert not (tmp_path / "out" / "bad-config").exists()


def test_deleted_spec_garbage_collects_config(agent_binary, tmp_path):
    """Removing a spec takes its rendered config out of service (the
    file-mode analogue of the reference's ownerReference GC)."""
    specs = tmp_path / "specs"
    out = tmp_path / "out"
    write_spec(specs, "gone", BASE_SPEC)
    write_spec(specs, "kept", BASE_SPEC)
    controlplane.run_once(spec_dir=str(specs), out_dir=str(out))
    assert (out / "gone-config" / "dynamic_config.json").exists()

    (specs / "gone.json").unlink()
    controlplane.run_once(spec_dir=str(specs), out_dir=str(out))
    assert not (out / "gone-config").exists()
    assert not (out / "status" / "gone.json").exists()
    assert (out / "kept-config" / "dynamic_config.json").exists()
    assert (out / "status" / "kept.json").exists()


def test_invalid_backend_url_rejected(agent_binary, tmp_path):
    """A Ready=True status must imply the router can apply the config;
    URLs the router's parser would reject fail spec validation."""
    bad = dict(BASE_SPEC, staticBackends="engine-0:8000")
    write_spec(tmp_path / "specs", "badurl", bad)
    controlplane.run_once(
        spec_dir=str(tmp_path / "specs"), out_dir=str(tmp_path / "out")
    )
    status = read_json(tmp_path / "out" / "status" / "badurl.json")
    assert status["conditions"][0]["status"] == "False"
    assert "invalid backend URL" in status["conditions"][0]["message"]


class _HealthHandler(BaseHTTPRequestHandler):
    healthy = True

    def do_GET(self):
        code = 200 if type(self).healthy else 503
        body = b'{"status": "ok"}'
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


@pytest.fixture
def health_server():
    server = ThreadingHTTPServer(("127.0.0.1", 0), _HealthHandler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    _HealthHandler.healthy = True
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()


def test_health_probe_success(agent_binary, tmp_path, health_server):
    spec = dict(BASE_SPEC, routerUrl=health_server)
    write_spec(tmp_path / "specs", "hr", spec)
    controlplane.run_once(
        spec_dir=str(tmp_path / "specs"), out_dir=str(tmp_path / "out")
    )
    health = read_json(tmp_path / "out" / "status" / "hr.json")[
        "routerHealth"
    ]
    assert health["healthy"] is True
    assert health["consecutiveSuccesses"] == 1
    assert health["detail"] == "HTTP 200"


def test_health_failure_threshold_across_ticks(
    agent_binary, tmp_path, health_server
):
    """healthy flips to False only after failureThreshold consecutive
    failures, tracked across reconcile ticks in one agent process."""
    _HealthHandler.healthy = False
    spec = dict(
        BASE_SPEC,
        routerUrl=health_server,
        healthCheck={
            "timeoutSeconds": 1,
            "periodSeconds": 1,
            "failureThreshold": 2,
        },
    )
    write_spec(tmp_path / "specs", "ht", spec)
    proc = controlplane.launch(
        spec_dir=str(tmp_path / "specs"),
        out_dir=str(tmp_path / "out"),
        period_s=1,
    )
    try:
        status_path = tmp_path / "out" / "status" / "ht.json"
        deadline = time.time() + 15
        health = None
        while time.time() < deadline:
            if status_path.exists():
                health = read_json(status_path).get("routerHealth")
                if health and health["consecutiveFailures"] >= 2:
                    break
            time.sleep(0.2)
        assert health is not None, "agent never probed"
        assert health["healthy"] is False
        assert health["consecutiveFailures"] >= 2
    finally:
        proc.terminate()
        proc.wait(timeout=10)


# ---------------------------------------------------------------- k8s mode


class _FakeKubeApi(BaseHTTPRequestHandler):
    """Just enough of the Kubernetes API for the agent's k8s mode:
    list StaticRoutes, get/create/update ConfigMaps, put CR status."""

    state = None  # dict injected per-test

    def _send(self, code, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length)) if length else None

    def do_GET(self):
        s = type(self).state
        if self.path.endswith("/staticroutes"):
            self._send(200, {"kind": "StaticRouteList",
                             "items": s["routes"]})
        elif "/configmaps/" in self.path:
            name = self.path.rsplit("/", 1)[1]
            if name in s["configmaps"]:
                self._send(200, s["configmaps"][name])
            else:
                self._send(404, {"kind": "Status", "code": 404})
        else:
            self._send(404, {"kind": "Status", "code": 404})

    def do_POST(self):
        s = type(self).state
        if self.path.endswith("/configmaps"):
            cm = self._body()
            s["configmaps"][cm["metadata"]["name"]] = cm
            self._send(201, cm)
        else:
            self._send(404, {})

    def do_PUT(self):
        s = type(self).state
        if "/configmaps/" in self.path:
            cm = self._body()
            s["configmaps"][cm["metadata"]["name"]] = cm
            self._send(200, cm)
        elif self.path.endswith("/status"):
            obj = self._body()
            s["statuses"][obj["metadata"]["name"]] = obj.get("status")
            self._send(200, obj)
        else:
            self._send(404, {})

    def log_message(self, *a):
        pass


@pytest.fixture
def fake_kube(health_server):
    state = {
        "routes": [
            {
                "apiVersion": "production-stack.tpu/v1alpha1",
                "kind": "StaticRoute",
                "metadata": {
                    "name": "k8s-route",
                    "namespace": "default",
                    "resourceVersion": "1",
                    "uid": "abc-123",
                },
                "spec": {
                    "routingLogic": "roundrobin",
                    "staticBackends": "http://engine-0:8000",
                    "staticModels": "llama-8b",
                    "routerUrl": health_server,
                },
            }
        ],
        "configmaps": {},
        "statuses": {},
    }
    _FakeKubeApi.state = state
    server = ThreadingHTTPServer(("127.0.0.1", 0), _FakeKubeApi)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}", state
    server.shutdown()


def test_k8s_mode_reconciles_configmap_and_status(agent_binary, fake_kube):
    api, state = fake_kube
    proc = controlplane.run_once(kube_api=api, namespace="default")
    assert proc.returncode == 0, proc.stderr
    assert "k8s-route" in proc.stderr

    cm = state["configmaps"]["k8s-route-config"]
    config = json.loads(cm["data"]["dynamic_config.json"])
    assert config["routing_logic"] == "roundrobin"
    assert config["static_backends"] == "http://engine-0:8000"
    assert cm["metadata"]["namespace"] == "default"
    ref = cm["metadata"]["ownerReferences"][0]
    assert ref["kind"] == "StaticRoute" and ref["uid"] == "abc-123"

    status = state["statuses"]["k8s-route"]
    assert status["conditions"][0]["status"] == "True"
    assert status["configMapRef"] == "k8s-route-config"
    assert status["routerHealth"]["healthy"] is True


def test_k8s_mode_idempotent_second_pass(agent_binary, fake_kube):
    api, state = fake_kube
    controlplane.run_once(kube_api=api, namespace="default")
    first_cm = json.dumps(state["configmaps"]["k8s-route-config"],
                          sort_keys=True)
    controlplane.run_once(kube_api=api, namespace="default")
    second_cm = json.dumps(state["configmaps"]["k8s-route-config"],
                           sort_keys=True)
    assert first_cm == second_cm


# ------------------------------------------------- regression: GC safety


def test_transient_invalid_spec_preserves_live_config(agent_binary,
                                                      tmp_path):
    """A spec whose metadata.name differs from its filename must keep its
    rendered config alive through a transient validation error — the
    error status keys off the resource identity, not the filename, so
    GC cannot mistake the route for deleted."""
    specs = tmp_path / "specs"
    out = tmp_path / "out"
    cr = {
        "metadata": {"name": "cr-named"},
        "spec": dict(BASE_SPEC, configMapName="custom-config"),
    }
    write_spec(specs, "file-name", cr)
    controlplane.run_once(spec_dir=str(specs), out_dir=str(out))
    live = out / "custom-config" / "dynamic_config.json"
    assert live.exists()

    # Transient bad edit: parseable JSON, invalid routingLogic.
    bad = {
        "metadata": {"name": "cr-named"},
        "spec": dict(BASE_SPEC, configMapName="custom-config",
                     routingLogic="typo"),
    }
    write_spec(specs, "file-name", bad)
    controlplane.run_once(spec_dir=str(specs), out_dir=str(out))
    assert live.exists(), "GC tore down live config on transient error"
    status = read_json(out / "status" / "cr-named.json")
    assert status["conditions"][0]["reason"] == "InvalidSpec"

    # Fixing the spec restores Ready without ever having lost the config.
    write_spec(specs, "file-name", cr)
    controlplane.run_once(spec_dir=str(specs), out_dir=str(out))
    assert read_json(
        out / "status" / "cr-named.json"
    )["conditions"][0]["status"] == "True"


@pytest.mark.parametrize("field,value", [
    ("configMapName", ".."),
    ("configMapName", "../escape"),
    ("metadataName", "../evil"),
])
def test_path_traversal_names_rejected(agent_binary, tmp_path, field,
                                       value):
    """metadata.name / configMapName become path components; anything
    that could escape the output dir must fail validation."""
    specs = tmp_path / "specs"
    out = tmp_path / "out"
    if field == "metadataName":
        spec = {"metadata": {"name": value}, "spec": dict(BASE_SPEC)}
    else:
        spec = dict(BASE_SPEC, **{field: value})
    write_spec(specs, "trav", spec)
    proc = controlplane.run_once(spec_dir=str(specs), out_dir=str(out))
    assert proc.returncode == 0
    status = read_json(out / "status" / "trav.json")
    assert status["conditions"][0]["reason"] == "InvalidSpec"
    # Nothing may have been written outside out_dir.
    assert not (tmp_path / "dynamic_config.json").exists()
    assert not (tmp_path / "escape").exists()
    assert not (tmp_path / "evil.json").exists()


def test_transition_time_stable_across_runs(agent_binary, tmp_path):
    """k8s condition semantics: lastTransitionTime moves only when the
    Ready condition flips, surviving process restarts via the persisted
    status (the reference gets this from apimachinery's SetStatusCondition)."""
    specs = tmp_path / "specs"
    out = tmp_path / "out"
    write_spec(specs, "tt", BASE_SPEC)
    controlplane.run_once(spec_dir=str(specs), out_dir=str(out))
    first = read_json(out / "status" / "tt.json")["conditions"][0]
    time.sleep(1.1)
    controlplane.run_once(spec_dir=str(specs), out_dir=str(out))
    second = read_json(out / "status" / "tt.json")["conditions"][0]
    assert second["lastTransitionTime"] == first["lastTransitionTime"]

    # A flip to not-Ready re-stamps it.
    time.sleep(1.1)
    write_spec(specs, "tt", dict(BASE_SPEC, routingLogic="typo"))
    controlplane.run_once(spec_dir=str(specs), out_dir=str(out))
    third = read_json(out / "status" / "tt.json")["conditions"][0]
    assert third["status"] == "False"
    assert third["lastTransitionTime"] != first["lastTransitionTime"]
