"""Cluster-wide KV economy (docs/kv_economy.md).

Covers the three layers as one system: the text-domain prefix
summaries engines export at GET /kv/summary (and the router policy
that routes on them, with staleness fallback), the managed shared
cache's admission/eviction state machines (driven by a fake clock),
and the engine-side cold-start probe — a cold prompt whose prefix KV
another engine already shipped restores it from the shared tier
byte-identically (bf16 AND int8) instead of recomputing, and degrades
to compute on miss or tier-down without ever dropping the request.
"""

import asyncio
import socket
import threading
import time

import numpy as np
import pytest
from aiohttp import web

from production_stack_tpu.engine.cache_server import build_cache_server
from production_stack_tpu.kvecon.cluster_cache import ManagedKVStore
from production_stack_tpu.kvecon.summary import (
    PrefixSummaryTracker,
    TOKENS_PER_BLOCK,
    chain_text,
    expected_hit_blocks,
    routable_text,
)
from production_stack_tpu.router.routing.logic import (
    KVStateAwarePolicy,
    PrefixAwarePolicy,
    initialize_routing_logic,
)
from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.router.stats.engine_stats import EngineStats
from production_stack_tpu.router.stats.request_stats import (
    initialize_request_stats_monitor,
)

EPS = [EndpointInfo(url=f"http://e{i}:8000") for i in range(3)]


@pytest.fixture(autouse=True)
def stats_monitor():
    return initialize_request_stats_monitor(60.0)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---- text-domain chains ---------------------------------------------------

def test_chain_text_is_the_policy_chain():
    """Router policy and engine tracker must hash the same domain:
    PrefixAwarePolicy._chain delegates to kvecon.chain_text."""
    text = "x" * 900
    p = PrefixAwarePolicy.__new__(PrefixAwarePolicy)
    assert p._chain(text) == chain_text(text)
    assert len(chain_text(text)) == 4  # ceil(900 / 256) blocks


def test_routable_text_shapes():
    msgs = {"messages": [{"role": "system", "content": "a"},
                         {"role": "user", "content": "b"}]}
    assert routable_text(msgs) == "system\x1fa\x1euser\x1fb"
    assert routable_text({"prompt": "hello"}) == "hello"
    assert routable_text({"prompt": ["a", "b"]}) == "a\x1eb"
    assert routable_text({"prompt": [1, 2, 3]}) is None  # token ids
    assert routable_text({}) is None


def test_expected_hit_blocks_deepest_advertised_hash_wins():
    """Chain hash i commits to the whole prefix through block i, so a
    decayed-out intermediate hash must not truncate the estimate."""
    chains = chain_text("y" * 1024)  # 4 blocks
    assert expected_hit_blocks(chains, set(chains)) == 4
    # Only the deepest hash survives in the hot set: still 4 blocks.
    assert expected_hit_blocks(chains, {chains[-1]}) == 4
    assert expected_hit_blocks(chains, {chains[0]}) == 1
    assert expected_hit_blocks(chains, set()) == 0
    assert expected_hit_blocks([], {1, 2}) == 0


# ---- engine summary tracker ----------------------------------------------

def test_summary_tracker_admit_floor_and_decay():
    clock = FakeClock()
    tr = PrefixSummaryTracker(top_k=8, admit_hits=2, ttl_s=0.0,
                              clock=clock)
    text = "z" * 300  # 2 blocks
    tr.observe_text(text)
    # One sighting is below the admit floor: nothing advertised.
    assert tr.snapshot() == []
    tr.observe_text(text)
    snap = dict(tr.snapshot())
    assert set(snap) == set(chain_text(text))
    assert all(v >= 2 for v in snap.values())
    # One half-life later the decayed count falls below the floor.
    clock.t += PrefixSummaryTracker.HALF_LIFE_S
    assert tr.snapshot() == []
    # ...but the chain is still tracked, so one more hit re-admits.
    tr.observe_text(text)
    assert len(tr.snapshot()) == 2


def test_summary_tracker_ttl_and_capacity():
    clock = FakeClock()
    tr = PrefixSummaryTracker(top_k=2, admit_hits=1, ttl_s=60.0,
                              clock=clock)
    tr.observe_text("a" * 300)
    clock.t = 61.0
    tr.observe_text("b" * 300)  # observe prunes the idle chain
    assert set(dict(tr.snapshot())) == set(chain_text("b" * 300))
    # Bounded memory: tracked chains capped at top_k * CAPACITY_FACTOR.
    for i in range(200):
        tr.observe_text(f"prompt-{i:04d}" + "p" * 260)
    assert len(tr) <= 2 * PrefixSummaryTracker.CAPACITY_FACTOR
    assert len(tr.snapshot()) <= 2


# ---- managed shared cache: admission/eviction -----------------------------

def test_managed_store_admission_by_distinct_requesters():
    clock = FakeClock()
    store = ManagedKVStore(10 ** 6, admit_hits=2, ttl_s=0.0,
                           watermark_high=1.0, watermark_low=1.0,
                           clock=clock)
    # Same requester asking twice is not demand promotion.
    assert store.put("k0", b"x" * 8, chain_id="c", requester="A") is False
    assert store.put("k0", b"x" * 8, chain_id="c", requester="A") is False
    assert store.get("k0", requester="A") is None
    assert store.stats()["rejected_puts"] == 2
    # A second distinct requester promotes the chain; the whole chain
    # is admitted, later pages ride in without re-courting.
    assert store.put("k0", b"x" * 8, chain_id="c", requester="B") is True
    assert store.put("k1", b"y" * 8, chain_id="c", requester="A") is True
    assert store.get("k0", requester="C") == b"x" * 8
    s = store.stats()
    assert s["admissions"] == 1 and s["chains"] == 1 and s["entries"] == 2


def test_managed_store_probe_miss_records_demand():
    """A HEAD miss is a statement of demand: two engines probing for
    the same (bare-key) chain promote it before any PUT lands."""
    clock = FakeClock()
    store = ManagedKVStore(10 ** 6, admit_hits=2, ttl_s=0.0,
                           watermark_high=1.0, watermark_low=1.0,
                           clock=clock)
    assert store.contains("root", requester="engine-a") is False
    assert store.contains("root", requester="engine-b") is False
    assert store.put("root", b"kv", requester="engine-a") is True


def test_managed_store_associate_merges_bare_key_demand():
    """Probe misses only know the page key; the PUT knows the chain.
    associate() folds the courted bare-key demand into the chain so
    the promotion threshold counts both."""
    clock = FakeClock()
    store = ManagedKVStore(10 ** 6, admit_hits=2, ttl_s=0.0,
                           watermark_high=1.0, watermark_low=1.0,
                           clock=clock)
    assert store.contains("page7", requester="engine-b") is False
    store.associate("page7", "chain-root")
    assert store.put("page7", b"kv", chain_id="chain-root",
                     requester="engine-a") is True


def test_managed_store_watermark_evicts_coldest_chain_whole():
    clock = FakeClock()
    store = ManagedKVStore(1000, admit_hits=1, ttl_s=0.0,
                           watermark_high=0.9, watermark_low=0.5,
                           clock=clock)
    store.put("a0", b"x" * 300, chain_id="cold", requester="A")
    clock.t = 1.0
    store.put("a1", b"x" * 300, chain_id="cold", requester="A")
    clock.t = 5.0
    store.put("b0", b"y" * 400, chain_id="hot", requester="A")
    # 1000 stored > 900 high: the cold chain dies WHOLE (both pages),
    # landing at 400 <= 500 low.
    assert store.get("a0") is None and store.get("a1") is None
    assert store.get("b0") is not None
    s = store.stats()
    assert s["evictions"] == 1 and s["bytes"] == 400 and s["chains"] == 1


def test_managed_store_ttl_sweeps_idle_chains():
    clock = FakeClock()
    store = ManagedKVStore(10 ** 6, admit_hits=1, ttl_s=100.0,
                           watermark_high=1.0, watermark_low=1.0,
                           clock=clock)
    store.put("k", b"kv", chain_id="c", requester="A")
    clock.t = 99.0
    assert store.get("k") is not None  # access refreshes last_access
    clock.t = 99.0 + 101.0
    assert store.get("k") is None
    assert store.stats()["evictions"] == 1


def test_managed_store_watermark_validation():
    with pytest.raises(ValueError, match="watermark"):
        ManagedKVStore(100, watermark_high=0.5, watermark_low=0.8)
    with pytest.raises(ValueError, match="watermark"):
        ManagedKVStore(100, watermark_high=1.2, watermark_low=0.8)


# ---- cache server: verdicts over HTTP -------------------------------------

def _wire_body(arr: np.ndarray) -> bytes:
    import msgpack
    return msgpack.packb({"arrays": [
        {"data": arr.tobytes(), "shape": list(arr.shape),
         "dtype": str(arr.dtype)}]})


def test_cache_server_admission_verdicts_and_chain_header():
    """PUT answers 200 + {"admitted": bool}; distinct X-KV-Requester
    identities promote a chain tagged via X-KV-Chain."""
    from aiohttp.test_utils import TestClient, TestServer

    async def run():
        client = TestClient(TestServer(
            build_cache_server(1024 ** 2, admit_hits=2)))
        await client.start_server()
        try:
            body = _wire_body(np.zeros((2, 2), np.float32))
            hdr_a = {"X-KV-Requester": "engine-a", "X-KV-Chain": "root"}
            hdr_b = {"X-KV-Requester": "engine-b", "X-KV-Chain": "root"}
            first = await client.put("/kv/p0", data=body, headers=hdr_a)
            assert first.status == 200
            assert (await first.json()) == {"admitted": False}
            assert (await client.head("/kv/p0",
                                      headers=hdr_a)).status == 404
            second = await client.put("/kv/p0", data=body,
                                      headers=hdr_b)
            assert (await second.json()) == {"admitted": True}
            assert (await client.get("/kv/p0")).status == 200
            stats = await (await client.get("/stats")).json()
            assert stats["rejected_puts"] == 1
            assert stats["admissions"] == 1
            metrics = await (await client.get("/metrics")).text()
            assert "kvcache:rejected_puts_total 1" in metrics
            assert "kvcache:chains 1" in metrics
        finally:
            await client.close()
    asyncio.run(run())


def test_remote_client_treats_rejected_put_as_success():
    """Satellite: {"admitted": false} is a verdict, not an error — the
    client reports success (no retry storm) and counts the rejection."""
    from production_stack_tpu.engine.offload import RemoteKVClient

    url, stop = _serve_app_in_thread(
        build_cache_server(64 * 1024 ** 2, admit_hits=2))
    try:
        client = RemoteKVClient(url, requester="engine-solo")
        payload = (np.ones((2, 2), np.float32),)
        assert client.put("page", payload, chain="root") is True
        assert client.rejections == 1 and client.admissions == 0
        # The same engine retrying stays rejected (demand needs a
        # SECOND identity) and stays a success.
        assert client.put("page", payload, chain="root") is True
        assert client.rejections == 2
        other = RemoteKVClient(url, requester="engine-other")
        assert other.put("page", payload, chain="root") is True
        assert other.admissions == 1 and other.rejections == 0
        got = client.get("page")
        assert got is not None and client.hits == 1
    finally:
        stop()


# ---- KV-state-aware routing -----------------------------------------------

def _fresh_stats(hot_chains=None, free=100, total=128):
    return EngineStats(
        kv_hot_chains=dict.fromkeys(hot_chains or [], 4.0),
        kv_free_page_headroom=float(free),
        kv_total_pages=float(total),
        kv_summary_time=time.time(),
    )


def test_kvstateaware_routes_to_engine_holding_the_prefix():
    policy = initialize_routing_logic("kvstateaware")
    assert isinstance(policy, KVStateAwarePolicy)
    text = "conversation history " * 40  # > 3 blocks
    chain = chain_text(text)
    stats = {
        "http://e0:8000": _fresh_stats(),
        "http://e1:8000": _fresh_stats(hot_chains=chain),
        "http://e2:8000": _fresh_stats(),
    }
    got = policy.route_request(EPS, stats, {}, {}, "r1", 64,
                               prompt_text=text)
    assert got == "http://e1:8000"
    expected = policy.expected_hit_tokens_by_url["http://e1:8000"]
    assert expected == len(chain) * TOKENS_PER_BLOCK


def test_kvstateaware_prefers_headroom_for_cold_prompts():
    """No engine holds the prefix: free-page headroom (which varies
    ~2x with --kv-cache-dtype) breaks the tie."""
    policy = initialize_routing_logic("kvstateaware")
    stats = {
        "http://e0:8000": _fresh_stats(free=4, total=128),
        "http://e1:8000": _fresh_stats(free=120, total=128),
        "http://e2:8000": _fresh_stats(free=30, total=128),
    }
    got = policy.route_request(EPS, stats, {}, {}, "r1", 64,
                               prompt_text="brand new prompt " * 40)
    assert got == "http://e1:8000"


def test_kvstateaware_stale_summaries_fall_back_to_affinity():
    """Engines that predate /kv/summary (kv_summary_time == 0) or a
    scraper outage must not break routing: the policy degrades to
    prefix-affinity and stays sticky per chain."""
    policy = initialize_routing_logic("kvstateaware")
    stale = {url: EngineStats() for url in (ep.url for ep in EPS)}
    text = "stale summary conversation " * 40
    first = policy.route_request(EPS, stale, {}, {}, "r1", 64,
                                 prompt_text=text)
    for i in range(4):
        assert policy.route_request(
            EPS, stale, {}, {}, f"r{i+2}", 64,
            prompt_text=text) == first


def test_kvstateaware_fallback_is_warm_after_fresh_routing():
    """Chains routed while summaries were fresh seed the fallback's
    affinity index — a scraper outage degrades to the SAME placement,
    not a cold shuffle."""
    policy = initialize_routing_logic("kvstateaware")
    text = "keep me warm " * 60
    chain = chain_text(text)
    stats = {
        "http://e0:8000": _fresh_stats(),
        "http://e1:8000": _fresh_stats(),
        "http://e2:8000": _fresh_stats(hot_chains=chain),
    }
    assert policy.route_request(EPS, stats, {}, {}, "r1", 64,
                                prompt_text=text) == "http://e2:8000"
    stale = {url: EngineStats() for url in (ep.url for ep in EPS)}
    assert policy.route_request(EPS, stale, {}, {}, "r2", 64,
                                prompt_text=text) == "http://e2:8000"


def test_kvstateaware_does_not_pollute_policy_singleton():
    """The private PrefixAwarePolicy fallback must not register in
    SingletonMeta: get_routing_logic() must still resolve to the
    configured policy."""
    from production_stack_tpu.router.routing.logic import (
        get_routing_logic,
    )
    policy = initialize_routing_logic("kvstateaware")
    stale = {ep.url: EngineStats() for ep in EPS}
    policy.route_request(EPS, stale, {}, {}, "r1", 64,
                         prompt_text="p" * 600)
    assert get_routing_logic() is policy


# ---- scrape loop + fake engine -------------------------------------------

def _serve_app_in_thread(app: web.Application):
    loop = asyncio.new_event_loop()
    started = threading.Event()
    port_box = {}

    def serve():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)
        loop.run_until_complete(site.start())
        port_box["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())

    thread = threading.Thread(target=serve, daemon=True)
    thread.start()
    assert started.wait(10)

    def stop():
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=10)

    return f"http://127.0.0.1:{port_box['port']}", stop


def test_fake_engine_kv_summary_and_scrape_loop():
    """The fake serves GET /kv/summary (with a POST override for
    tests) and the engine-stats scraper folds it into EngineStats on
    the same pass as /metrics."""
    from production_stack_tpu.router.service_discovery import (
        initialize_service_discovery,
    )
    from production_stack_tpu.router.stats.engine_stats import (
        initialize_engine_stats_scraper,
    )
    from production_stack_tpu.testing.fake_engine import (
        build_fake_engine,
    )

    url, stop = _serve_app_in_thread(build_fake_engine())
    try:
        import requests
        pinned = {"hot_chains": [[123, 5.0], [456, 2.0]],
                  "free_pages": 7, "total_pages": 64,
                  "kv_dtype": "int8"}
        requests.post(f"{url}/kv/summary", json=pinned, timeout=5)
        assert requests.get(f"{url}/kv/summary",
                            timeout=5).json() == pinned
        metrics = requests.get(f"{url}/metrics", timeout=5).text
        assert "vllm:kv_summary_hot_chains 2.0" in metrics
        assert "vllm:kv_free_page_headroom 7.0" in metrics

        initialize_service_discovery(
            "static", urls=[url], models=["fake/model"])
        scraper = initialize_engine_stats_scraper(3600.0)
        try:
            scraper.scrape_once()
            es = scraper.get_engine_stats()[url]
            assert es.kv_hot_chains == {123: 5.0, 456: 2.0}
            assert es.kv_free_page_headroom == 7.0
            assert es.kv_total_pages == 64.0
            assert es.engine_kv_cache_dtype == "int8"
            assert es.kv_summary_time > 0
        finally:
            scraper.close()
    finally:
        stop()


def test_fake_engine_prefix_hot_set_thrashes_at_capacity():
    """The fake's hot set is a CAPPED LRU: pinning more distinct
    prefixes than the capacity on one fake evicts, so a routing
    policy that over-concentrates load measurably loses hit rate."""
    from production_stack_tpu.testing.fake_engine import (
        FakeEngineState,
    )
    s = FakeEngineState("m", 100.0, 0.02, kv_hot_capacity=2)
    bodies = [{"prompt": f"tenant-{i} " * 60} for i in range(3)]
    for b in bodies:
        assert s.observe_prefix(b) == 0.0  # all cold
    # Three distinct chains through capacity 2: the first is gone.
    assert s.observe_prefix(bodies[0]) == 0.0
    s2 = FakeEngineState("m", 100.0, 0.02, kv_hot_capacity=64)
    for b in bodies:
        s2.observe_prefix(b)
    assert all(s2.observe_prefix(b) == 1.0 for b in bodies)
    assert 0.0 < s2.prefix_hit_rate() < 1.0


# ---- engine cold-start probe (slow lane: builds engines) ------------------

def _free_port_url() -> str:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"http://127.0.0.1:{port}"


def _make_engine(remote_url, role="both", kv_dtype="auto",
                 offload=True):
    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        OffloadConfig,
        SchedulerConfig,
        tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    return LLMEngine(EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64,
                          kv_cache_dtype=kv_dtype),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=256,
                                  prefill_chunk_size=64),
        offload=OffloadConfig(enable=offload, remote_url=remote_url,
                              host_pool_bytes=0),
        engine_role=role,
    ))


def _sampling():
    from production_stack_tpu.engine.sequence import SamplingParams
    return SamplingParams(max_tokens=12, temperature=0.0,
                          ignore_eos=True)


def _run_to_finish(engine, sid):
    from production_stack_tpu.engine.sequence import SequenceState
    seq = engine.sequences[sid]
    while seq.state not in (SequenceState.FINISHED,
                            SequenceState.ABORTED):
        engine.step()
    return seq


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", ["auto", "int8"])
def test_cold_start_restores_another_engines_kv(kv_dtype):
    """The tentpole acceptance: engine A computes a prompt's KV and
    ships it to the shared cache; a COLD engine B receiving the same
    prompt parks, probes, restores A's pages through the wire, and
    produces byte-identical greedy output — for bf16 and int8."""
    from production_stack_tpu.engine.sequence import SequenceState

    url, stop = _serve_app_in_thread(
        build_cache_server(256 * 1024 ** 2))
    try:
        prompt = list(range(1, 50))  # 3 full pages + a tail
        ref = _make_engine(None, offload=False,
                           kv_dtype=kv_dtype).generate(
            list(prompt), _sampling())

        pre = _make_engine(url, role="prefill", kv_dtype=kv_dtype)
        sid = pre.add_request(list(prompt), _sampling(),
                              handoff_prefill=True)
        outs = []
        while not outs or not outs[-1].finished:
            outs.extend(pre.step())
        assert outs[-1].finish_reason == "handoff"

        dec = _make_engine(url, kv_dtype=kv_dtype)
        did = dec.add_request(list(prompt), _sampling())
        seq = dec.sequences[did]
        # Parked for the shared-cache probe, with the tri-state flag
        # telling the admission loop this is a cold start.
        assert seq.state == SequenceState.AWAITING_KV
        assert seq.cold_start_probe
        assert dec.stats()["num_requests_waiting"] == 1
        _run_to_finish(dec, did)
        assert seq.output_token_ids == ref.output_token_ids
        # The win was a restore, not a recompute.
        assert dec.offload.restored_pages > 0
        assert dec.offload.remote.hits > 0
        assert dec.offload.stats()["cluster_hits"] > 0
    finally:
        stop()


@pytest.mark.slow
def test_cold_start_miss_computes_without_waiting():
    """Shared tier up but empty: the probe answers a definitive miss
    and the sequence computes on the next admission pass — and the
    recorded demand is what later promotes the chain."""
    url, stop = _serve_app_in_thread(
        build_cache_server(64 * 1024 ** 2))
    try:
        prompt = list(range(201, 250))
        ref = _make_engine(None, offload=False).generate(
            list(prompt), _sampling())
        dec = _make_engine(url)
        did = dec.add_request(list(prompt), _sampling())
        seq = _run_to_finish(dec, did)
        assert seq.output_token_ids == ref.output_token_ids
        assert dec.offload.restored_pages == 0
        assert dec.offload.remote.misses == 0  # probe is HEAD-only
    finally:
        stop()


@pytest.mark.slow
def test_cold_start_tier_down_degrades_immediately():
    """Remote tier unreachable: unlike a disagg handoff (which waits
    out handoff_timeout_s for pages that WERE shipped), a cold-start
    probe has nothing in flight — it must compute on the very first
    admission pass, not park for the timeout."""
    prompt = list(range(61, 110))
    ref = _make_engine(None, offload=False).generate(
        list(prompt), _sampling())
    dec = _make_engine(_free_port_url())
    t0 = time.monotonic()
    did = dec.add_request(list(prompt), _sampling())
    seq = _run_to_finish(dec, did)
    assert time.monotonic() - t0 < dec.config.handoff_timeout_s
    assert seq.output_token_ids == ref.output_token_ids
    assert dec.offload.restored_pages == 0


@pytest.mark.slow
def test_abort_during_cold_start_probe_leaks_no_pages():
    """Regression guard: aborting a request while it is parked for the
    cold-start probe (and aborting one that restored and started
    decoding) must leave zero pages referenced."""
    from production_stack_tpu.engine.sequence import SequenceState

    url, stop = _serve_app_in_thread(
        build_cache_server(256 * 1024 ** 2))
    try:
        prompt = list(range(1, 50))
        pre = _make_engine(url, role="prefill")
        sid = pre.add_request(list(prompt), _sampling(),
                              handoff_prefill=True)
        outs = []
        while not outs or not outs[-1].finished:
            outs.extend(pre.step())

        dec = _make_engine(url)
        # Abort while still parked in AWAITING_KV.
        a = dec.add_request(list(prompt), _sampling())
        assert dec.sequences[a].state == SequenceState.AWAITING_KV
        dec.abort_request(a)
        assert dec.cache_manager.num_used_pages == 0
        assert not dec.scheduler.has_work()
        # Abort mid-flight: probe admitted, restore + prefill ran.
        b = dec.add_request(list(prompt), _sampling())
        for _ in range(3):
            dec.step()
        dec.abort_request(b)
        while dec.has_work():
            dec.step()
        assert dec.cache_manager.num_used_pages == 0
    finally:
        stop()
