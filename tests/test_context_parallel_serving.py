"""Context-parallel SERVING: long prompts prefill in one dispatch with
the sequence sharded over the 'sp' mesh axis (ring attention), then
decode on the standard path — greedy output must match a single-device
engine token for token (round-2 gap: ring attention existed only as a
standalone forward, unreachable from the engine).

Runs on the virtual 8-device CPU mesh (tests/conftest.py)."""

import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    ParallelConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams


def _engine(sp, threshold=64, family="llama", tp=1, quant="none",
            lora=False):
    from production_stack_tpu.engine.config import LoRAConfig
    from production_stack_tpu.parallel.mesh import build_mesh

    model = tiny_model_config(family)
    model.quantization = quant
    config = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=512,
                                  prefill_chunk_size=32,
                                  prefill_batch_size=2),
        parallel=ParallelConfig(context_parallel_size=sp,
                                tensor_parallel_size=tp,
                                long_prefill_threshold=threshold),
        lora=(LoRAConfig(enable=True, max_loras=2, max_lora_rank=4)
              if lora else LoRAConfig()),
    )
    mesh = (build_mesh(context_parallel_size=sp,
                       tensor_parallel_size=tp)
            if sp > 1 or tp > 1 else None)
    engine = LLMEngine(config, mesh=mesh)
    if lora:
        import numpy as np

        from production_stack_tpu.engine.lora import (
            LoRAAdapter,
            target_shapes,
        )
        rs = np.random.RandomState(11)
        pairs = {}
        for tgt, (d_in, d_out) in target_shapes(model).items():
            pairs[tgt] = (
                rs.randn(model.num_hidden_layers, d_in, 4)
                .astype(np.float32) * 0.05,
                rs.randn(model.num_hidden_layers, 4, d_out)
                .astype(np.float32) * 0.05,
            )
        engine.runner.lora_registry.register(LoRAAdapter(
            name="adapter-x", rank=4, scaling=0.5, weights=pairs))
    return engine


def _sampling():
    return SamplingParams(max_tokens=8, temperature=0.0,
                          ignore_eos=True)


def test_sp_prefill_matches_single_device():
    """A prompt 4x the chunk size (>= threshold) at sp=4: whole-prompt
    ring prefill + standard decode reproduces single-device greedy."""
    prompt = list(range(2, 2 + 4 * 32 + 9))  # 137 tokens, not a pow2

    ref = _engine(1).generate(prompt, _sampling()).output_token_ids
    got = _engine(4).generate(prompt, _sampling()).output_token_ids
    assert got == ref


def test_sp_gpt2_prefill_matches_single_device():
    """Second family (round-3 verdict: sp was llama-only): gpt2's
    learned-position/LayerNorm body on the same ring prefill."""
    prompt = list(range(2, 2 + 4 * 32 + 5))

    ref = _engine(1, family="gpt2").generate(
        prompt, _sampling()).output_token_ids
    got = _engine(4, family="gpt2").generate(
        prompt, _sampling()).output_token_ids
    assert got == ref


def test_sp_tp_prefill_matches_single_device():
    """sp=2 x tp=2 (round-5 composition): ring prefill with the heads
    ALSO sliced over 'tp' (GQA — 2 kv heads over tp=2 leaves one kv
    head per device) must reproduce single-device greedy, then decode
    on the standard GSPMD tp path."""
    prompt = list(range(2, 2 + 4 * 32 + 9))

    ref = _engine(1).generate(prompt, _sampling()).output_token_ids
    got = _engine(2, tp=2).generate(prompt,
                                    _sampling()).output_token_ids
    assert got == ref


def test_sp_tp_gpt2_prefill_matches_single_device():
    """sp x tp on the gpt2 body: the biased row-parallel projections
    (wo+bo, fc2+fc2_b) must add their replicated bias exactly once
    after the tp psum."""
    prompt = list(range(2, 2 + 4 * 32 + 5))

    ref = _engine(1, family="gpt2").generate(
        prompt, _sampling()).output_token_ids
    got = _engine(2, family="gpt2", tp=2).generate(
        prompt, _sampling()).output_token_ids
    assert got == ref


def test_sp_tp_mixed_lengths_continuous_batching():
    """Long (sp ring) and short (chunked GSPMD) prompts interleave in
    one sp=2 x tp=2 engine; both prefill paths and tp decode agree
    with single-device greedy."""
    prompts = [
        list(range(2, 2 + 130)),   # sp path
        list(range(3, 3 + 20)),    # chunked path
    ]
    ref_engine = _engine(1)
    ref = [ref_engine.generate(p, _sampling()).output_token_ids
           for p in prompts]

    eng = _engine(2, tp=2)
    seqs = [eng.sequences[eng.add_request(p, _sampling())]
            for p in prompts]
    while eng.has_work():
        eng.step()
    assert [s.output_token_ids for s in seqs] == ref


def test_sp_quantized_matches_single_device():
    """int8 under sp (round-5: the sp+quant guard lifted — the 8B
    int8 long-context config needs exactly this): the single-device
    int8 engine and the sp=4 engine derive IDENTICAL (weight, scale)
    pairs from the same seed, so greedy outputs must agree exactly."""
    prompt = list(range(2, 2 + 4 * 32 + 7))

    ref = _engine(1, quant="int8").generate(
        prompt, _sampling()).output_token_ids
    got = _engine(4, quant="int8").generate(
        prompt, _sampling()).output_token_ids
    assert got == ref


def test_sp_tp_quantized_matches_single_device():
    """sp=2 x tp=2 with int8: column weights carry 'tp'-sliced scales,
    row weights replicated scales that commute with the psum."""
    prompt = list(range(2, 2 + 4 * 32 + 1))

    ref = _engine(1, quant="int8").generate(
        prompt, _sampling()).output_token_ids
    got = _engine(2, tp=2, quant="int8").generate(
        prompt, _sampling()).output_token_ids
    assert got == ref


def test_sp_lora_matches_single_device():
    """sp + LoRA (round-5 widening — the last guarded hole in the
    parallel matrix): the LoRA delta is a per-row map over tokens, so
    the sequence sharding passes through it; adapter rows and
    base-model rows must both reproduce the single-device LoRA
    engine."""
    prompt = list(range(2, 2 + 4 * 32 + 7))

    def serve(engine):
        outs = []
        for name in (None, "adapter-x"):
            seq = engine.generate(prompt, _sampling(), lora_name=name)
            outs.append(seq.output_token_ids)
        return outs

    ref = serve(_engine(1, lora=True))
    got = serve(_engine(4, lora=True))
    assert got == ref


def test_sp_tp_lora_matches_single_device():
    """sp x tp + LoRA: adapter targets shard like their base
    projections (row-parallel A input axis / column-parallel B output
    axis) inside the ring body's shard_map."""
    prompt = list(range(2, 2 + 4 * 32 + 11))

    def serve(engine):
        outs = []
        for name in (None, "adapter-x"):
            seq = engine.generate(prompt, _sampling(), lora_name=name)
            outs.append(seq.output_token_ids)
        return outs

    ref = serve(_engine(1, lora=True))
    got = serve(_engine(2, tp=2, lora=True))
    assert got == ref


def test_sp_only_mesh_without_tp_axis():
    """A caller-built mesh carrying ONLY an 'sp' axis (the runner gate
    requires just that) must still serve: specs fall back to
    replicated and the tp psums are skipped (code-review regression,
    round 5)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    prompt = list(range(2, 2 + 4 * 32 + 3))
    ref = _engine(1).generate(prompt, _sampling()).output_token_ids

    model = tiny_model_config("llama")
    config = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=512,
                                  prefill_chunk_size=32,
                                  prefill_batch_size=2),
        parallel=ParallelConfig(context_parallel_size=4,
                                long_prefill_threshold=64),
    )
    mesh = Mesh(np.asarray(jax.devices()[:4]), axis_names=("sp",))
    got = LLMEngine(config, mesh=mesh).generate(
        prompt, _sampling()).output_token_ids
    assert got == ref


def test_sp_tp_rejects_indivisible_heads():
    import pytest as _pytest
    with _pytest.raises(ValueError, match="sp x tp"):
        _engine(2, tp=4)  # tiny llama: 2 kv heads % 4 != 0


def test_sp_short_prompts_use_chunked_path():
    """Prompts under the threshold stay on the chunked prefill path
    (and still match single-device greedy)."""
    prompt = list(range(5, 5 + 40))  # 40 < threshold 64

    eng = _engine(4)
    ref = _engine(1).generate(prompt, _sampling()).output_token_ids
    seq = eng.generate(prompt, _sampling())
    assert seq.output_token_ids == ref


def test_sp_mixed_lengths_continuous_batching():
    """Long (sp) and short (chunked) prompts interleave in one engine;
    every output matches single-device greedy."""
    prompts = [
        list(range(2, 2 + 130)),   # sp path
        list(range(3, 3 + 20)),    # chunked path
        list(range(4, 4 + 70)),    # sp path
    ]
    ref_engine = _engine(1)
    ref = [ref_engine.generate(p, _sampling()).output_token_ids
           for p in prompts]

    eng = _engine(4)
    seqs = [eng.sequences[eng.add_request(p, _sampling())]
            for p in prompts]
    while eng.has_work():
        eng.step()
    assert [s.output_token_ids for s in seqs] == ref


def test_sp_engine_rejects_bad_configs():
    from production_stack_tpu.parallel.mesh import build_mesh

    model = tiny_model_config("opt")
    with pytest.raises(NotImplementedError,
                       match="context parallelism serves"):
        LLMEngine(EngineConfig(
            model=model,
            cache=CacheConfig(page_size=16, num_pages=64),
            scheduler=SchedulerConfig(max_num_seqs=2,
                                      max_model_len=128,
                                      prefill_chunk_size=32),
            parallel=ParallelConfig(context_parallel_size=2),
        ), mesh=build_mesh(context_parallel_size=2))
    with pytest.raises(ValueError, match="mesh with an 'sp' axis"):
        LLMEngine(EngineConfig(
            model=tiny_model_config("llama"),
            cache=CacheConfig(page_size=16, num_pages=64),
            scheduler=SchedulerConfig(max_num_seqs=2,
                                      max_model_len=128,
                                      prefill_chunk_size=32),
            parallel=ParallelConfig(context_parallel_size=2),
        ), mesh=None)


def test_sp_qwen2_bias_prefill_matches_single_device():
    """Attention-bias (qwen2-style) branch of the sp llama body: the
    three layer-body copies (models/, pipeline_serving, context_serving)
    are kept honest by parity tests per architecture variant."""
    prompt = list(range(2, 2 + 4 * 32))

    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
        tiny_model_config,
    )
    from production_stack_tpu.parallel.mesh import build_mesh

    def bias_engine(sp):
        model = tiny_model_config("llama")
        model.attention_bias = True  # qwen2-style q/k/v biases
        config = EngineConfig(
            model=model,
            cache=CacheConfig(page_size=16, num_pages=128),
            scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=512,
                                      prefill_chunk_size=32,
                                      prefill_batch_size=2),
            parallel=ParallelConfig(context_parallel_size=sp,
                                    long_prefill_threshold=64),
        )
        mesh = build_mesh(context_parallel_size=sp) if sp > 1 else None
        return LLMEngine(config, mesh=mesh)

    ref = bias_engine(1).generate(prompt, _sampling()).output_token_ids
    got = bias_engine(4).generate(prompt, _sampling()).output_token_ids
    assert got == ref
