"""Pipeline parallelism: layers staged over a pp mesh axis must match
the dense single-device forward exactly."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.models import llama
from production_stack_tpu.parallel.pipeline import (
    pipeline_forward,
    shard_params_pipeline,
)


def _config(layers=4, bias=False):
    return ModelConfig(
        name="pp-test",
        architecture="llama",
        vocab_size=128,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=layers,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        dtype="float32",
        attention_bias=bias,
    )


@pytest.mark.parametrize("pp,layers,microbatches", [
    (2, 4, 2), (4, 4, 4), (2, 4, 4),
])
def test_pipeline_matches_dense(pp, layers, microbatches):
    config = _config(layers=layers)
    params = llama.init_params(config, jax.random.PRNGKey(0))
    devices = np.asarray(jax.devices()[:pp])
    mesh = Mesh(devices.reshape(pp), axis_names=("pp",))

    b, t = microbatches * 2, 16
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (b, t)), jnp.int32)

    ref = llama.forward_train(params, config, tokens)
    sharded = shard_params_pipeline(params, config, mesh)
    got = pipeline_forward(sharded, config, tokens, mesh,
                           num_microbatches=microbatches)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_pipeline_with_attention_bias():
    config = _config(layers=4, bias=True)
    params = llama.init_params(config, jax.random.PRNGKey(1))
    # Nonzero biases so the path is actually exercised.
    params["bq"] = 0.1 * jax.random.normal(
        jax.random.PRNGKey(2), params["bq"].shape)
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2),
                axis_names=("pp",))
    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, 128, (4, 8)), jnp.int32)
    ref = llama.forward_train(params, config, tokens)
    got = pipeline_forward(
        shard_params_pipeline(params, config, mesh), config, tokens,
        mesh, num_microbatches=2)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_pipeline_rejects_bad_shapes():
    config = _config(layers=4)
    params = llama.init_params(config, jax.random.PRNGKey(0))
    mesh = Mesh(np.asarray(jax.devices()[:3]).reshape(3),
                axis_names=("pp",))
    tokens = jnp.zeros((4, 8), jnp.int32)
    with pytest.raises(ValueError, match="divide"):
        pipeline_forward(params, config, tokens, mesh)


# ---- serving-path pipeline parallelism (parallel/pipeline_serving.py) ----


def _pp_engine(pp, quant="none"):
    """Full LLMEngine on a (dp=1, pp, tp=1) mesh."""
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
        tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.parallel.mesh import build_mesh

    model = tiny_model_config("llama")
    model.num_hidden_layers = 4  # divisible by every pp size tested
    model.quantization = quant
    config = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=128,
                                  prefill_chunk_size=32,
                                  prefill_batch_size=2),
        parallel=ParallelConfig(pipeline_parallel_size=pp),
    )
    mesh = build_mesh(pipeline_parallel_size=pp) if pp > 1 else None
    return LLMEngine(config, mesh=mesh)


@pytest.mark.parametrize("pp", [2, 4])
def test_pp_engine_serves_and_matches_single_device(pp):
    """--pipeline-parallel-size N is a SERVING feature: the engine
    (chunked prefill + paged KV + continuous batching) runs with layers
    staged over pp and reproduces the single-device greedy output."""
    from production_stack_tpu.engine.sequence import SamplingParams

    sampling = lambda: SamplingParams(  # noqa: E731
        max_tokens=8, temperature=0.0, ignore_eos=True)
    prompts = [list(range(2, 2 + n)) for n in (18, 7, 33)]

    ref_engine = _pp_engine(1)
    ref = [ref_engine.generate(p, sampling()).output_token_ids
           for p in prompts]

    pp_engine = _pp_engine(pp)
    seqs = [pp_engine.sequences[pp_engine.add_request(p, sampling())]
            for p in prompts]
    while pp_engine.has_work():
        pp_engine.step()
    got = [s.output_token_ids for s in seqs]
    assert got == ref


def test_pp_engine_rejects_bad_configs():
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, LoRAConfig, ParallelConfig,
        SchedulerConfig, tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(pipeline_parallel_size=2)
    base = dict(
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=128,
                                  prefill_chunk_size=32),
    )
    with pytest.raises(NotImplementedError, match="pipeline parallelism serves"):
        LLMEngine(EngineConfig(
            model=tiny_model_config("opt"),
            parallel=ParallelConfig(pipeline_parallel_size=2),
            **base), mesh=mesh)
    with pytest.raises(ValueError, match="mesh with a 'pp' axis"):
        LLMEngine(EngineConfig(
            model=tiny_model_config("llama"),
            parallel=ParallelConfig(pipeline_parallel_size=2),
            **base), mesh=None)


def _pp_tp_engine(pp, tp, architecture="llama", quant="none"):
    """Full LLMEngine on a (dp=1, pp, tp) mesh."""
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, ParallelConfig, SchedulerConfig,
        tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.parallel.mesh import build_mesh

    model = tiny_model_config(architecture)
    model.num_hidden_layers = 4
    model.quantization = quant
    config = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=128,
                                  prefill_chunk_size=32,
                                  prefill_batch_size=2),
        parallel=ParallelConfig(pipeline_parallel_size=pp,
                                tensor_parallel_size=tp),
    )
    mesh = (build_mesh(pipeline_parallel_size=pp,
                       tensor_parallel_size=tp)
            if pp * tp > 1 else None)
    return LLMEngine(config, mesh=mesh)


def test_pp_tp_engine_matches_single_device():
    """pp=2 x tp=2 (round-2 gap): stage-local projections sharded
    over tp with in-body psums must reproduce single-device greedy."""
    from production_stack_tpu.engine.sequence import SamplingParams

    sampling = lambda: SamplingParams(  # noqa: E731
        max_tokens=8, temperature=0.0, ignore_eos=True)
    prompts = [list(range(2, 2 + n)) for n in (18, 7, 33)]

    ref = [_pp_tp_engine(1, 1).generate(p, sampling()).output_token_ids
           for p in prompts]
    # One engine instance serves all prompts (continuous batching).
    eng = _pp_tp_engine(2, 2)
    seqs = [eng.sequences[eng.add_request(p, sampling())]
            for p in prompts]
    while eng.has_work():
        eng.step()
    assert [s.output_token_ids for s in seqs] == ref


def test_pp_quantized_engine_matches_single_device():
    """int8 weights staged over pp=2 (round-5: the pp+quant guard
    lifted): the single-device int8 engine and the pp engine derive
    IDENTICAL (weight, scale) pairs from the same seed, so greedy
    outputs must agree token for token — no quantization-noise
    allowance needed."""
    from production_stack_tpu.engine.sequence import SamplingParams

    sampling = lambda: SamplingParams(  # noqa: E731
        max_tokens=8, temperature=0.0, ignore_eos=True)
    prompts = [list(range(2, 2 + n)) for n in (18, 7)]

    ref_engine = _pp_engine(1, quant="int8")
    ref = [ref_engine.generate(p, sampling()).output_token_ids
           for p in prompts]
    eng = _pp_engine(2, quant="int8")
    import jax.numpy as jnp
    w, scale = eng.runner.params["wq"]
    assert w.dtype == jnp.int8  # staged weights really are int8
    seqs = [eng.sequences[eng.add_request(p, sampling())]
            for p in prompts]
    while eng.has_work():
        eng.step()
    assert [s.output_token_ids for s in seqs] == ref


def test_pp_tp_quantized_engine_matches_single_device():
    """pp=2 x tp=2 with int8: exercises the tp-sharded scale spec
    (pipeline_serving lp_spec — column weights carry a 'tp' scale
    slice, row weights a replicated scale that commutes with the
    psum). Same seed -> identical (weight, scale) pairs -> exact
    greedy parity with the single-device int8 engine."""
    from production_stack_tpu.engine.sequence import SamplingParams

    sampling = lambda: SamplingParams(  # noqa: E731
        max_tokens=8, temperature=0.0, ignore_eos=True)
    prompts = [list(range(2, 2 + n)) for n in (18, 7)]

    ref_engine = _pp_tp_engine(1, 1, quant="int8")
    ref = [ref_engine.generate(p, sampling()).output_token_ids
           for p in prompts]
    eng = _pp_tp_engine(2, 2, quant="int8")
    seqs = [eng.sequences[eng.add_request(p, sampling())]
            for p in prompts]
    while eng.has_work():
        eng.step()
    assert [s.output_token_ids for s in seqs] == ref


def test_pp_gpt2_engine_matches_single_device():
    """Second pp family (round-2 gap was llama-only): gpt2's
    layer_norm/learned-positions/gelu body staged over pp=2."""
    from production_stack_tpu.engine.sequence import SamplingParams

    sampling = lambda: SamplingParams(  # noqa: E731
        max_tokens=8, temperature=0.0, ignore_eos=True)
    prompts = [list(range(2, 2 + n)) for n in (18, 9)]

    ref = [_pp_tp_engine(1, 1, "gpt2").generate(
        p, sampling()).output_token_ids for p in prompts]
    eng = _pp_tp_engine(2, 1, "gpt2")
    seqs = [eng.sequences[eng.add_request(p, sampling())]
            for p in prompts]
    while eng.has_work():
        eng.step()
    assert [s.output_token_ids for s in seqs] == ref


def test_pp_pads_batch_to_stage_multiple():
    """3 prompts on pp=4 with prefill_batch_size 2: every dispatch
    width (2- and 4-row programs) hits the padding path (round-2
    weakness: batch % stages != 0 degraded to one microbatch)."""
    from production_stack_tpu.engine.sequence import SamplingParams

    sampling = lambda: SamplingParams(  # noqa: E731
        max_tokens=6, temperature=0.0, ignore_eos=True)
    prompts = [list(range(3, 3 + n)) for n in (11, 21, 5)]

    ref = [_pp_tp_engine(1, 1).generate(p, sampling()).output_token_ids
           for p in prompts]
    eng = _pp_tp_engine(4, 1)
    # max_num_seqs=4, prefill_batch_size=2: decode runs at width 4,
    # prefill at width 2 — 2 % 4 != 0 exercises the row padding.
    seqs = [eng.sequences[eng.add_request(p, sampling())]
            for p in prompts]
    while eng.has_work():
        eng.step()
    assert [s.output_token_ids for s in seqs] == ref


def test_pp_lora_engine_matches_single_device():
    """pp + LoRA (round-3 verdict: the most-requested combo), and
    round-5: pp x tp + LoRA — adapter stacks shard their L axis over
    pp with the other layer params; under tp each target shards like
    its base projection (row-parallel targets shard A's input axis so
    x@A stays local and the existing psum sums base + delta partials;
    column-parallel targets shard B's output axis). Per-row adapter
    selection and base-model rows must reproduce the single-device
    LoRA engine exactly in every layout."""
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, LoRAConfig, ParallelConfig,
        SchedulerConfig, tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.lora import LoRAAdapter, target_shapes
    from production_stack_tpu.engine.sequence import SamplingParams
    from production_stack_tpu.parallel.mesh import build_mesh

    def make_engine(pp, tp=1):
        model = tiny_model_config("llama")
        model.num_hidden_layers = 4
        config = EngineConfig(
            model=model,
            cache=CacheConfig(page_size=16, num_pages=64),
            scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=128,
                                      prefill_chunk_size=32,
                                      prefill_batch_size=2),
            parallel=ParallelConfig(pipeline_parallel_size=pp,
                                    tensor_parallel_size=tp),
            lora=LoRAConfig(enable=True, max_loras=2, max_lora_rank=4),
        )
        mesh = (build_mesh(pipeline_parallel_size=pp,
                           tensor_parallel_size=tp)
                if pp > 1 or tp > 1 else None)
        engine = LLMEngine(config, mesh=mesh)
        rs = np.random.RandomState(11)
        pairs = {}
        for tgt, (d_in, d_out) in target_shapes(config.model).items():
            pairs[tgt] = (
                rs.randn(config.model.num_hidden_layers, d_in, 4)
                .astype(np.float32) * 0.05,
                rs.randn(config.model.num_hidden_layers, 4, d_out)
                .astype(np.float32) * 0.05,
            )
        engine.runner.lora_registry.register(LoRAAdapter(
            name="adapter-x", rank=4, scaling=0.5, weights=pairs))
        return engine

    sampling = lambda: SamplingParams(  # noqa: E731
        max_tokens=8, temperature=0.0, ignore_eos=True)
    prompts = [list(range(2, 2 + n)) for n in (18, 9)]

    def serve(engine):
        seqs = []
        for i, p in enumerate(prompts):
            # Row 0 base model, row 1 through the adapter: both paths
            # in one batch.
            name = "adapter-x" if i % 2 else None
            sid = engine.add_request(p, sampling(), lora_name=name)
            seqs.append(engine.sequences[sid])
        while engine.has_work():
            engine.step()
        return [s.output_token_ids for s in seqs]

    ref = serve(make_engine(1))
    got = serve(make_engine(2))
    assert got == ref
    got_pp_tp = serve(make_engine(2, tp=2))
    assert got_pp_tp == ref
