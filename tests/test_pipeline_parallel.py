"""Pipeline parallelism: layers staged over a pp mesh axis must match
the dense single-device forward exactly."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.models import llama
from production_stack_tpu.parallel.pipeline import (
    pipeline_forward,
    shard_params_pipeline,
)


def _config(layers=4, bias=False):
    return ModelConfig(
        name="pp-test",
        architecture="llama",
        vocab_size=128,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=layers,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=64,
        dtype="float32",
        attention_bias=bias,
    )


@pytest.mark.parametrize("pp,layers,microbatches", [
    (2, 4, 2), (4, 4, 4), (2, 4, 4),
])
def test_pipeline_matches_dense(pp, layers, microbatches):
    config = _config(layers=layers)
    params = llama.init_params(config, jax.random.PRNGKey(0))
    devices = np.asarray(jax.devices()[:pp])
    mesh = Mesh(devices.reshape(pp), axis_names=("pp",))

    b, t = microbatches * 2, 16
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (b, t)), jnp.int32)

    ref = llama.forward_train(params, config, tokens)
    sharded = shard_params_pipeline(params, config, mesh)
    got = pipeline_forward(sharded, config, tokens, mesh,
                           num_microbatches=microbatches)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_pipeline_with_attention_bias():
    config = _config(layers=4, bias=True)
    params = llama.init_params(config, jax.random.PRNGKey(1))
    # Nonzero biases so the path is actually exercised.
    params["bq"] = 0.1 * jax.random.normal(
        jax.random.PRNGKey(2), params["bq"].shape)
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2),
                axis_names=("pp",))
    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, 128, (4, 8)), jnp.int32)
    ref = llama.forward_train(params, config, tokens)
    got = pipeline_forward(
        shard_params_pipeline(params, config, mesh), config, tokens,
        mesh, num_microbatches=2)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_pipeline_rejects_bad_shapes():
    config = _config(layers=4)
    params = llama.init_params(config, jax.random.PRNGKey(0))
    mesh = Mesh(np.asarray(jax.devices()[:3]).reshape(3),
                axis_names=("pp",))
    tokens = jnp.zeros((4, 8), jnp.int32)
    with pytest.raises(ValueError, match="divide"):
        pipeline_forward(params, config, tokens, mesh)
