"""Engine-side /v1/score and /v1/rerank (reference surface:
src/vllm_router/routers/main_router.py:42-84 proxies both; our engine
serves them natively as bi-encoder pooled-embedding relevance)."""

import asyncio

from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.server import EngineServer


def _server():
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32),
    )
    return EngineServer(LLMEngine(config), "tiny-llama")


def _run(fn):
    async def wrapper():
        server = _server()
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            await fn(client)
        finally:
            await client.close()
    asyncio.run(wrapper())


def test_score_single_and_list():
    async def run(client):
        resp = await client.post("/v1/score", json={
            "model": "tiny-llama",
            "text_1": "the quick brown fox",
            "text_2": ["the quick brown fox", "completely different"],
        })
        assert resp.status == 200
        data = await resp.json()
        scores = [d["score"] for d in data["data"]]
        assert len(scores) == 2
        # Identical text must score (near) 1.0 and beat a different one.
        assert scores[0] > 0.999
        assert scores[0] > scores[1]

        resp = await client.post("/score", json={
            "text_1": "abc", "text_2": "abc"})
        assert resp.status == 200

        resp = await client.post("/v1/score", json={"text_1": "x"})
        assert resp.status == 400
    _run(run)


def test_rerank_orders_by_relevance():
    async def run(client):
        docs = ["zzz unrelated text", "alpha beta gamma", "alpha beta"]
        resp = await client.post("/v1/rerank", json={
            "model": "tiny-llama",
            "query": "alpha beta gamma",
            "documents": docs,
        })
        assert resp.status == 200
        data = await resp.json()
        results = data["results"]
        assert len(results) == 3
        # Exact match ranks first; scores are non-increasing.
        assert results[0]["index"] == 1
        rel = [r["relevance_score"] for r in results]
        assert rel == sorted(rel, reverse=True)
        assert results[0]["document"]["text"] == docs[1]

        resp = await client.post("/rerank", json={
            "query": "q", "documents": docs, "top_n": 1})
        data = await resp.json()
        assert len(data["results"]) == 1

        resp = await client.post("/v1/rerank", json={"query": "x"})
        assert resp.status == 400
    _run(run)
