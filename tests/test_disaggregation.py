"""Disaggregated prefill/decode serving (docs/disaggregation.md).

Covers the whole handoff path: config-time role rules, the cache
server's batched GET, the engine-side prefill->ship->park->restore
cycle (token-for-token parity with a monolithic engine, bf16 and
int8), the degrade-to-recompute fallbacks, and the router's two-hop
dispatch with per-hop retry and monolithic fallback driven through
role-carrying fake engines — the acceptance invariant being that a
request that entered the disagg path is never dropped.
"""

import asyncio
import socket
import threading

import numpy as np
import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.engine.cache_server import (
    BATCH_GET_MAX_KEYS,
    build_cache_server,
)
from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    OffloadConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.offload import KV_WIRE_VERSION, RemoteKVClient
from production_stack_tpu.engine.sequence import SamplingParams, SequenceState
from production_stack_tpu.router.resilience import (
    ResilienceConfig,
    initialize_resilience,
)
from production_stack_tpu.router.service_discovery import (
    EndpointInfo,
    K8sServiceDiscovery,
    StaticServiceDiscovery,
    initialize_service_discovery,
)
from production_stack_tpu.router.services import request_service
from production_stack_tpu.router.services.rewriter import (
    initialize_request_rewriter,
)
from production_stack_tpu.router.stats.engine_stats import (
    initialize_engine_stats_scraper,
)
from production_stack_tpu.router.stats.request_stats import (
    initialize_request_stats_monitor,
)
from production_stack_tpu.testing.fake_engine import build_fake_engine


# ---- config contract ------------------------------------------------------

def test_engine_role_value_validated():
    with pytest.raises(ValueError, match="engine_role"):
        EngineConfig(engine_role="compute")


def test_negative_handoff_timeout_rejected():
    with pytest.raises(ValueError, match="handoff_timeout_s"):
        EngineConfig(handoff_timeout_s=-1.0)


def test_engine_role_prefill_rejects_speculative_k():
    """A prefill-role engine never decodes past the first token, so
    speculation is dead weight — config-time error, not a silent lie."""
    with pytest.raises(ValueError, match="engine_role"):
        EngineConfig(engine_role="prefill",
                     scheduler=SchedulerConfig(speculative_k=2))
    # The combination is legal for every other role.
    EngineConfig(engine_role="decode",
                 scheduler=SchedulerConfig(speculative_k=2))


def test_engine_role_prefill_accepts_async_scheduling():
    """role x async is a dissolved exclusivity rule
    (docs/unified_step.md): async on a prefill-role engine is legal
    but inert — there are no decode steps to overlap, so the loop
    never dispatches ahead. The server's 'auto' still resolves it
    off (test_async_pipeline.test_server_auto_resolution)."""
    EngineConfig(engine_role="prefill",
                 scheduler=SchedulerConfig(async_scheduling=True))
    EngineConfig(engine_role="both",
                 scheduler=SchedulerConfig(async_scheduling=True))


# ---- shared fixtures ------------------------------------------------------

def _serve_app_in_thread(app: web.Application):
    """Run an aiohttp app on a real socket in a daemon thread (the
    sync RemoteKVClient and engine offload tier need real HTTP).
    Returns (base_url, stop_fn)."""
    loop = asyncio.new_event_loop()
    started = threading.Event()
    port_box = {}

    def serve():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)
        loop.run_until_complete(site.start())
        port_box["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())

    thread = threading.Thread(target=serve, daemon=True)
    thread.start()
    assert started.wait(10)

    def stop():
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=10)

    return f"http://127.0.0.1:{port_box['port']}", stop


@pytest.fixture(scope="module")
def cache_server_url():
    """One live cache server shared by the module: keys are
    content-addressed and dtype-namespaced, so tests cannot collide."""
    url, stop = _serve_app_in_thread(build_cache_server(256 * 1024 ** 2))
    yield url
    stop()


def _free_port_url() -> str:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"http://127.0.0.1:{port}"


def _make_engine(remote_url, role="both", kv_dtype="auto", offload=True,
                 handoff_timeout_s=30.0):
    return LLMEngine(EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64,
                          kv_cache_dtype=kv_dtype),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=256,
                                  prefill_chunk_size=64),
        # host_pool_bytes=0: remote-only tier, so every restore is a
        # real cross-process fetch like a disaggregated deployment.
        offload=OffloadConfig(enable=offload, remote_url=remote_url,
                              host_pool_bytes=0),
        engine_role=role,
        handoff_timeout_s=handoff_timeout_s,
    ))


def _sampling():
    return SamplingParams(max_tokens=12, temperature=0.0, ignore_eos=True)


def _run_prefill_handoff(engine, prompt, sampling):
    """Drive a prefill-role engine to handoff; returns (first_token,
    descriptor info dict)."""
    sid = engine.add_request(list(prompt), sampling, handoff_prefill=True)
    outs = []
    while not outs or not outs[-1].finished:
        outs.extend(engine.step())
    assert outs[-1].finish_reason == "handoff"
    return outs[-1].new_token, engine.take_handoff_info(sid)


def _run_decode_handoff(engine, prompt, first_token, sampling):
    """Drive a decode-role engine from a handoff to completion;
    returns the full output token list (first token included)."""
    did = engine.add_handoff(list(prompt), first_token, sampling)
    seq = engine.sequences[did]
    while seq.state not in (SequenceState.FINISHED,
                            SequenceState.ABORTED):
        engine.step()
    assert seq.state == SequenceState.FINISHED
    return [first_token] + seq.output_token_ids


# ---- engine E2E: handoff parity -------------------------------------------

@pytest.mark.parametrize("kv_dtype", ["auto", "int8"])
def test_disagg_parity_with_monolithic(cache_server_url, kv_dtype):
    """The acceptance invariant: prefill on one engine + decode on
    another (KV through the shared cache server) produces exactly the
    monolithic engine's greedy tokens — for bf16 and int8 KV pages."""
    prompt = list(range(1, 50))  # 3 full pages + a tail
    ref = _make_engine(cache_server_url, offload=False,
                       kv_dtype=kv_dtype).generate(
        list(prompt), _sampling())

    pre = _make_engine(cache_server_url, role="prefill",
                       kv_dtype=kv_dtype)
    first, info = _run_prefill_handoff(pre, prompt, _sampling())
    assert info is not None
    assert info["num_pages"] == 3  # 48 of 49 prompt tokens are paged
    assert info["kv_bytes"] > 0 and len(info["page_keys"]) == 3
    stats = pre.stats()
    assert stats["disagg_prefill_requests_total"] == 1
    assert stats["disagg_kv_bytes_shipped_total"] == info["kv_bytes"]
    # The prefill engine retired the sequence: pages free, no work.
    assert not pre.scheduler.has_work()

    dec = _make_engine(cache_server_url, role="decode",
                       kv_dtype=kv_dtype)
    did = dec.add_handoff(list(prompt), first, _sampling())
    seq = dec.sequences[did]
    assert seq.state == SequenceState.AWAITING_KV
    assert dec.stats()["disagg_awaiting_kv_requests"] == 1
    assert dec.stats()["num_requests_waiting"] == 1
    while seq.state not in (SequenceState.FINISHED,
                            SequenceState.ABORTED):
        dec.step()
    got = [first] + seq.output_token_ids
    assert got == ref.output_token_ids
    # Decode restored the shipped pages instead of recomputing.
    assert dec.offload.restored_pages > 0
    assert dec.stats()["disagg_decode_requests_total"] == 1
    assert dec.stats()["disagg_awaiting_kv_requests"] == 0


def test_handoff_kv_miss_recomputes_exactly(cache_server_url):
    """Pages never shipped (definitive tier miss): the decode engine
    degrades to a local recompute immediately and still produces the
    monolithic output — degraded, never dropped."""
    prompt = list(range(101, 150))
    ref = _make_engine(cache_server_url, offload=False).generate(
        list(prompt), _sampling())
    dec = _make_engine(cache_server_url, role="decode")
    got = _run_decode_handoff(dec, prompt, ref.output_token_ids[0],
                              _sampling())
    assert got == ref.output_token_ids
    assert dec.offload.restored_pages == 0


def test_handoff_tier_unreachable_times_out_to_recompute():
    """Remote tier down (probe returns no verdict): the sequence waits
    in AWAITING_KV up to handoff_timeout_s, then recomputes. With a
    zero timeout the first admission pass degrades immediately."""
    prompt = list(range(11, 60))
    ref = _make_engine(None, offload=False).generate(
        list(prompt), _sampling())
    dec = _make_engine(_free_port_url(), role="decode",
                       handoff_timeout_s=0.0)
    got = _run_decode_handoff(dec, prompt, ref.output_token_ids[0],
                              _sampling())
    assert got == ref.output_token_ids
    assert dec.offload.restored_pages == 0


def test_awaiting_kv_abort_releases_nothing_and_clears_depth(
        cache_server_url):
    """Regression: aborting a handoff parked in AWAITING_KV must drop
    it from the waiting queue and the depth gauge without leaking KV
    pages (a parked sequence holds none yet)."""
    dec = _make_engine(cache_server_url, role="decode")
    # Pin the sequence in AWAITING_KV: the tier never gives a verdict
    # and the (default 30s) timeout never fires within the test.
    dec.offload.handoff_ready = lambda page_hash: None
    free_before = dec.cache_manager.num_free_pages
    did = dec.add_handoff(list(range(1, 50)), 7, _sampling())
    seq = dec.sequences[did]
    for _ in range(3):
        dec.step()
    assert seq.state == SequenceState.AWAITING_KV
    assert dec.stats()["disagg_awaiting_kv_requests"] == 1
    assert dec.stats()["num_requests_waiting"] == 1
    assert dec.cache_manager.num_free_pages == free_before

    dec.abort_request(did)
    assert did not in dec.sequences
    assert dec.stats()["disagg_awaiting_kv_requests"] == 0
    assert dec.stats()["num_requests_waiting"] == 0
    assert dec.cache_manager.num_free_pages == free_before
    assert not dec.scheduler.has_work()


# ---- cache server: POST /kv/batch_get -------------------------------------

def _wire_body(arrays):
    import msgpack
    return msgpack.packb({
        "version": KV_WIRE_VERSION,
        "arrays": [
            {"data": a.tobytes(), "shape": list(a.shape),
             "dtype": str(a.dtype)}
            for a in arrays
        ],
    })


async def test_batch_get_hits_misses_and_validation():
    import msgpack
    client = TestClient(TestServer(build_cache_server(1024 ** 2)))
    await client.start_server()
    try:
        a = np.arange(32, dtype=np.float32).reshape(2, 16)
        int8_page = (np.ones((2, 2), np.int8), np.ones((2, 2), np.int8),
                     np.ones((2,), np.float32), np.ones((2,), np.float32))
        assert (await client.put("/kv/pa",
                                 data=_wire_body((a, a)))).status == 200
        assert (await client.put("/kv/pb",
                                 data=_wire_body(int8_page))).status == 200

        resp = await client.post(
            "/kv/batch_get",
            data=msgpack.packb({"keys": ["pa", "missing", "pb"]}))
        assert resp.status == 200
        blobs = msgpack.unpackb(await resp.read())["blobs"]
        assert len(blobs) == 3
        assert blobs[1] is None  # order-aligned nil for the miss
        got_a = msgpack.unpackb(blobs[0])["arrays"][0]
        np.testing.assert_array_equal(
            np.frombuffer(got_a["data"], np.float32).reshape(2, 16), a)
        assert len(msgpack.unpackb(blobs[2])["arrays"]) == 4

        # Malformed requests 400 instead of crashing or storing junk.
        bad = [
            b"\x00junk not msgpack",
            msgpack.packb({"nope": 1}),
            msgpack.packb({"keys": "pa"}),
            msgpack.packb({"keys": [1, 2]}),
            msgpack.packb({"keys": ["k"] * (BATCH_GET_MAX_KEYS + 1)}),
        ]
        for body in bad:
            assert (await client.post("/kv/batch_get",
                                      data=body)).status == 400
    finally:
        await client.close()


def test_remote_client_batch_get_and_probe(cache_server_url):
    client = RemoteKVClient(cache_server_url)
    payloads = {
        f"bg{i}": (np.full((2, 4), i, np.float32),
                   np.full((2, 4), -i, np.float32))
        for i in range(3)
    }
    for key, payload in payloads.items():
        assert client.put(key, payload)
    got = client.batch_get(list(payloads) + ["bg-missing"])
    assert set(got) == set(payloads)
    for key, payload in payloads.items():
        for want, have in zip(payload, got[key]):
            assert have.dtype == want.dtype
            np.testing.assert_array_equal(want, have)
    assert client.batch_get([]) == {}
    # Probe tri-state: definitive hit / definitive miss / no verdict.
    assert client.probe("bg0") is True
    assert client.probe("bg-missing") is False
    dead = RemoteKVClient(_free_port_url(), timeout_s=0.5)
    assert dead.probe("bg0") is None
    assert dead.batch_get(["bg0"]) == {}


def test_batch_get_falls_back_to_sequential_on_old_server():
    """A pre-batch_get cache server answers 404/405 on the endpoint;
    RemoteKVClient must degrade to per-key GETs transparently."""
    store = {}

    async def put_kv(request):
        store[request.match_info["key"]] = await request.read()
        return web.Response(status=200)

    async def get_kv(request):
        blob = store.get(request.match_info["key"])
        if blob is None:
            return web.Response(status=404)
        return web.Response(body=blob)

    app = web.Application()
    app.router.add_put("/kv/{key}", put_kv)
    app.router.add_get("/kv/{key}", get_kv)
    url, stop = _serve_app_in_thread(app)
    try:
        client = RemoteKVClient(url)
        payload = (np.arange(8, dtype=np.float32),
                   np.arange(8, dtype=np.float32) * 2)
        assert client.put("old0", payload)
        got = client.batch_get(["old0", "old-missing"])
        assert set(got) == {"old0"}
        np.testing.assert_array_equal(got["old0"][0], payload[0])
    finally:
        stop()


# ---- role discovery -------------------------------------------------------

def test_filter_by_role_and_static_roles():
    from production_stack_tpu.router.routing.logic import filter_by_role
    eps = [EndpointInfo(url="http://p", role="prefill"),
           EndpointInfo(url="http://d", role="decode"),
           EndpointInfo(url="http://b")]
    assert [ep.url for ep in filter_by_role(eps, "prefill")] == ["http://p"]
    assert [ep.url for ep in filter_by_role(eps, "decode")] == ["http://d"]

    disc = StaticServiceDiscovery(
        urls=["http://p", "http://d"], models=["m1", "m1"],
        roles=["prefill", "decode"])
    assert [ep.role for ep in disc.get_endpoint_info()] == [
        "prefill", "decode"]
    with pytest.raises(ValueError):
        StaticServiceDiscovery(urls=["http://p"], models=["m1"],
                               roles=["prefill", "decode"])
    with pytest.raises(ValueError):
        StaticServiceDiscovery(urls=["http://p"], models=["m1"],
                               roles=["gpu"])


def test_parser_validates_static_roles():
    from production_stack_tpu.router.parser import parse_args
    ok = parse_args([
        "--service-discovery", "static",
        "--static-backends", "http://a,http://b",
        "--static-models", "m1,m1",
        "--static-roles", "prefill,decode",
    ])
    assert ok.static_roles == "prefill,decode"
    with pytest.raises(ValueError, match="static-roles"):
        parse_args([
            "--service-discovery", "static",
            "--static-backends", "http://a,http://b",
            "--static-models", "m1,m1",
            "--static-roles", "prefill",
        ])
    with pytest.raises(ValueError, match="prefill, decode or both"):
        parse_args([
            "--service-discovery", "static",
            "--static-backends", "http://a",
            "--static-models", "m1",
            "--static-roles", "gpu",
        ])


def test_k8s_role_probe_reads_health():
    """K8s discovery learns the role from GET /health; anything that
    fails or reports an unknown role is treated as 'both'."""
    url, stop = _serve_app_in_thread(
        build_fake_engine(model="m1", role="prefill"))
    try:
        assert K8sServiceDiscovery._probe_role(url) == "prefill"
    finally:
        stop()
    assert K8sServiceDiscovery._probe_role(_free_port_url()) == "both"


# ---- router two-hop dispatch (fake engines) -------------------------------

async def _start_disagg_router(backends):
    """backends: [(url, model, role)]. Initializes the router
    singletons with role-aware static discovery and returns a started
    TestClient."""
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.routing.logic import (
        initialize_routing_logic,
    )
    request_service.disagg_handoffs_total = 0
    request_service.disagg_fallbacks_total = 0
    initialize_service_discovery(
        "static",
        urls=[b[0] for b in backends],
        models=[b[1] for b in backends],
        roles=[b[2] for b in backends],
    )
    initialize_request_stats_monitor(60.0)
    initialize_engine_stats_scraper(3600.0)
    initialize_routing_logic("roundrobin")
    initialize_request_rewriter("noop")
    initialize_resilience(ResilienceConfig(
        max_retries=2, backend_connect_timeout=1.0, backend_timeout=10.0,
        health_check_interval=0.0,
    ))
    client = TestClient(TestServer(build_app()))
    await client.start_server()
    return client


def _chat_body(model, stream=False, max_tokens=3):
    return {
        "model": model,
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": max_tokens,
        "stream": stream,
    }


async def _start_fakes(*roles, fault=None):
    """One fake engine per role; returns the started TestServers."""
    servers = [
        TestServer(build_fake_engine(model="m1", speed=1000, ttft=0.0,
                                     role=role,
                                     fault=fault.get(i) if fault else None))
        for i, role in enumerate(roles)
    ]
    for server in servers:
        await server.start_server()
    return servers


def _url(server: TestServer) -> str:
    return f"http://127.0.0.1:{server.port}"


def _sse_contents(text: str):
    """Delta contents of an SSE chat stream, in order."""
    import json
    contents = []
    for line in text.splitlines():
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        delta = json.loads(line[len("data: "):])["choices"][0]["delta"]
        if delta.get("content"):
            contents.append(delta["content"])
    return contents


async def test_router_two_hop_matches_monolithic():
    """Happy path: prefill fake emits the descriptor, decode fake
    streams — the client sees exactly what a monolithic backend would
    have produced, and both hops are accounted."""
    pre, dec, mono = await _start_fakes("prefill", "decode", "both")
    mono_client = TestClient(mono)
    client = await _start_disagg_router([
        (_url(pre), "m1", "prefill"),
        (_url(dec), "m1", "decode"),
    ])
    try:
        ref = await mono_client.post("/v1/chat/completions",
                                     json=_chat_body("m1"))
        ref_content = (await ref.json())[
            "choices"][0]["message"]["content"]

        resp = await client.post("/v1/chat/completions",
                                 json=_chat_body("m1"))
        assert resp.status == 200
        data = await resp.json()
        assert data["choices"][0]["message"]["content"] == ref_content
        assert pre.app["state"].disagg_prefills == 1
        assert dec.app["state"].disagg_decodes == 1
        assert request_service.disagg_handoffs_total == 1
        assert request_service.disagg_fallbacks_total == 0

        # Streaming: same delta sequence as the monolithic stream.
        ref_stream = await mono_client.post(
            "/v1/chat/completions", json=_chat_body("m1", stream=True))
        want = _sse_contents(await ref_stream.text())
        resp = await client.post("/v1/chat/completions",
                                 json=_chat_body("m1", stream=True))
        assert resp.status == 200
        assert _sse_contents(await resp.text()) == want
        assert dec.app["state"].disagg_decodes == 2

        # Ineligible requests (n > 1) never engage the disagg path.
        body = _chat_body("m1")
        body["n"] = 2
        resp = await client.post("/v1/chat/completions", json=body)
        assert resp.status == 200
        assert pre.app["state"].disagg_prefills == 2  # unchanged
        assert dec.app["state"].disagg_decodes == 2  # unchanged
    finally:
        await client.close()
        await mono_client.close()
        for server in (pre, dec, mono):
            await server.close()


@pytest.mark.parametrize("failure", ["dead", "error500"])
async def test_router_retries_decode_hop_on_backend_failure(failure):
    """The acceptance kill test: the decode backend chosen for hop 2
    is gone (connection refused) or broken (500) — the router retries
    the other decode-role backend and the client still gets a 200,
    never a 5xx."""
    pre, d1, d2 = await _start_fakes("prefill", "decode", "decode")
    # Hop 2 picks the least-loaded decode backend, tie-broken by URL:
    # break exactly the one it will try first.
    first, second = sorted((d1, d2), key=_url)
    if failure == "dead":
        await first.close()  # port now refuses connections
    else:
        first.app["state"].fault = "error500"
    client = await _start_disagg_router([
        (_url(pre), "m1", "prefill"),
        (_url(first), "m1", "decode"),
        (_url(second), "m1", "decode"),
    ])
    try:
        resp = await client.post("/v1/chat/completions",
                                 json=_chat_body("m1"))
        assert resp.status == 200
        data = await resp.json()
        assert data["choices"][0]["message"]["content"]
        assert second.app["state"].disagg_decodes == 1
        assert request_service.disagg_handoffs_total == 1
    finally:
        await client.close()
        for server in (pre, d1, d2):
            await server.close()


@pytest.mark.parametrize("poisoned", ["prefill", "decode"])
async def test_router_kv_missing_falls_back_monolithic(poisoned):
    """KV not restorable (poisoned descriptor from the prefill fake,
    or the decode fake's own kv_missing fault): the decode hop answers
    409, the router stops retrying the decode pool and completes the
    request monolithically — degraded, never dropped, never a 5xx."""
    fault = {0: "kv_missing"} if poisoned == "prefill" else {1: "kv_missing"}
    pre, dec, mono = await _start_fakes("prefill", "decode", "both",
                                        fault=fault)
    client = await _start_disagg_router([
        (_url(pre), "m1", "prefill"),
        (_url(dec), "m1", "decode"),
        (_url(mono), "m1", "both"),
    ])
    try:
        resp = await client.post("/v1/chat/completions",
                                 json=_chat_body("m1"))
        assert resp.status == 200
        data = await resp.json()
        assert data["choices"][0]["message"]["content"]
        assert dec.app["state"].disagg_decodes == 0  # 409ed, never streamed
        assert request_service.disagg_handoffs_total == 0
        assert request_service.disagg_fallbacks_total == 1
    finally:
        await client.close()
        for server in (pre, dec, mono):
            await server.close()


async def test_router_empty_prefill_pool_serves_monolithic():
    """Decode-only fleet (no prefill pool): the disagg path never
    engages and requests serve monolithically off the decode pods."""
    (dec,) = await _start_fakes("decode")
    client = await _start_disagg_router([(_url(dec), "m1", "decode")])
    try:
        resp = await client.post("/v1/chat/completions",
                                 json=_chat_body("m1"))
        assert resp.status == 200
        assert dec.app["state"].disagg_decodes == 0
        assert request_service.disagg_handoffs_total == 0
        # Never entered the two-hop path, so no fallback either.
        assert request_service.disagg_fallbacks_total == 0
    finally:
        await client.close()
        await dec.close()
