"""Compiled Mosaic lowering checks for the Pallas attention kernels.

Round-2 lesson: ``interpret=True`` parity tests validate numerics but
none of Mosaic's tiling/layout rules — the prefill kernel passed every
interpret test and then failed to compile on the real chip (a (1, T)
int32 VMEM block violates the (8, 128) tiling rule; BENCH_r02
``pallas_error``). These tests cross-lower the kernels for the TPU
platform from the CPU host (no chip needed): the Pallas→Mosaic lowering
rules — including the BlockSpec tiling checks that failed on hardware —
run in Python during lowering, so the exact class of bug that slipped
through round 2 now fails in CI.

This validates lowering (tiling, layouts, scalar prefetch plumbing),
not Mosaic's final machine-code pass; the bench still reports which
impl actually served on the chip.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402


def _lower_for_tpu(fn, *args):
    """Lower ``fn(*args)`` for the TPU platform from any host."""
    traced = jax.jit(fn).trace(*args)
    return traced.lower(lowering_platforms=("tpu",))


def _decode_args(b=8, num_pages=64, page_size=128, kv_heads=8,
                 q_heads=32, head_dim=64, max_pages=16):
    rng = np.random.RandomState(0)
    q = jnp.asarray(
        rng.randn(b, q_heads, head_dim), jnp.bfloat16)
    kc = jnp.asarray(
        rng.randn(kv_heads, num_pages, head_dim, page_size),
        jnp.bfloat16)
    vc = jnp.asarray(
        rng.randn(kv_heads, num_pages, head_dim, page_size),
        jnp.bfloat16)
    pt = jnp.zeros((b, max_pages), jnp.int32)
    kl = jnp.full((b,), 100, jnp.int32)
    return q, kc, vc, pt, kl


def _prefill_args(b=4, t=512, num_pages=64, page_size=128, kv_heads=8,
                  q_heads=32, head_dim=64, max_pages=64):
    rng = np.random.RandomState(0)
    q = jnp.asarray(
        rng.randn(b, t, q_heads, head_dim), jnp.bfloat16)
    kc = jnp.asarray(
        rng.randn(kv_heads, num_pages, head_dim, page_size),
        jnp.bfloat16)
    vc = jnp.asarray(
        rng.randn(kv_heads, num_pages, head_dim, page_size),
        jnp.bfloat16)
    pt = jnp.zeros((b, max_pages), jnp.int32)
    pos = jnp.broadcast_to(
        jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    kl = jnp.full((b,), t, jnp.int32)
    return q, kc, vc, pt, pos, kl


def test_decode_kernel_lowers_for_tpu():
    from production_stack_tpu.ops.paged_attention_pallas import (
        paged_decode_attention,
    )
    _lower_for_tpu(paged_decode_attention, *_decode_args())


def test_prefill_kernel_lowers_for_tpu():
    """The exact bench-shape prefill program (B=4, T=512) — the shape
    that failed Mosaic compilation in round 2."""
    from production_stack_tpu.ops.prefill_attention_pallas import (
        paged_prefill_attention,
    )
    _lower_for_tpu(paged_prefill_attention, *_prefill_args())


@pytest.mark.parametrize("t", [16, 64, 256])
def test_prefill_kernel_lowers_every_bucket(t):
    """All prefill buckets the model runner can emit must lower."""
    from production_stack_tpu.ops.prefill_attention_pallas import (
        paged_prefill_attention,
    )
    _lower_for_tpu(paged_prefill_attention, *_prefill_args(t=t))


def test_decode_kernel_lowers_small_group():
    """GQA group 1 (MHA): the group axis pads to 8 sublanes."""
    from production_stack_tpu.ops.paged_attention_pallas import (
        paged_decode_attention,
    )
    _lower_for_tpu(
        paged_decode_attention,
        *_decode_args(kv_heads=8, q_heads=8))


def test_full_model_step_lowers_for_tpu():
    """End-to-end: the llama forward with attention_impl=pallas (both
    kernels inside the layer scan) lowers for TPU."""
    from production_stack_tpu.engine.config import tiny_model_config
    from production_stack_tpu.models.llama import forward, init_params

    config = tiny_model_config("llama")
    config.attention_impl = "pallas"
    params = init_params(config, jax.random.PRNGKey(0))

    b, t = 2, 64
    page_size, num_pages, max_pages = 128, 32, 8
    tokens = jnp.zeros((b, t), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(t)[None], (b, t))
    page_table = jnp.zeros((b, max_pages), jnp.int32)
    kv_lens = jnp.full((b,), t, jnp.int32)
    valid = jnp.ones((b, t), bool)
    cache_shape = (config.num_hidden_layers,
                   config.num_key_value_heads, num_pages,
                   config.head_dim, page_size)
    k_cache = jnp.zeros(cache_shape, config.jax_dtype)
    v_cache = jnp.zeros(cache_shape, config.jax_dtype)

    def step(params, tokens, positions, page_table, kv_lens, valid,
             k_cache, v_cache):
        return forward(params, config, tokens, positions, page_table,
                       kv_lens, valid, k_cache, v_cache)

    _lower_for_tpu(step, params, tokens, positions, page_table,
                   kv_lens, valid, k_cache, v_cache)


def test_decode_burst_program_lowers_for_tpu():
    """The fused K-step decode burst (lax.scan over the pallas-decode
    forward, with donation-style carries, on-device budgets/stops)
    must lower for TPU as one program — kernel-level lowering alone
    misses scan/carry interactions."""
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, SchedulerConfig, tiny_model_config,
    )
    from production_stack_tpu.engine.model_runner import ModelRunner

    model = tiny_model_config("llama")
    model.attention_impl = "pallas"
    config = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=128, num_pages=32),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256,
                                  prefill_chunk_size=64,
                                  decode_steps=8),
    )
    runner = ModelRunner(config)
    b = 4
    args = (
        runner.params, runner.k_cache, runner.v_cache,
        jnp.zeros((b, 1), jnp.int32), jnp.zeros((b, 1), jnp.int32),
        jnp.zeros((b, runner.max_pages_per_seq), jnp.int32),
        jnp.zeros((b,), jnp.int32), jnp.zeros((b,), bool),
        jnp.zeros((b,), jnp.int32),
        jnp.full((b, 16), -1, jnp.int32),
        jnp.zeros((b,), jnp.float32), jnp.ones((b,), jnp.float32),
        jnp.zeros((b,), jnp.int32), jax.random.PRNGKey(0),
        None, None,   # lora, lora_ids
        None, None,   # penalties, seeding
        None, None, None,  # bias, suppress, fsm
    )
    traced = jax.jit(
        runner._decode_burst_impl, static_argnames=("num_steps",)
    ).trace(*args, num_steps=8)
    traced.lower(lowering_platforms=("tpu",))


def _ragged_args(r=8, w=512, num_pages=64, page_size=128, kv_heads=8,
                 q_heads=32, head_dim=64, max_pages=64):
    rng = np.random.RandomState(0)
    q = jnp.asarray(
        rng.randn(r, w, q_heads, head_dim), jnp.bfloat16)
    kc = jnp.asarray(
        rng.randn(kv_heads, num_pages, head_dim, page_size),
        jnp.bfloat16)
    vc = jnp.asarray(
        rng.randn(kv_heads, num_pages, head_dim, page_size),
        jnp.bfloat16)
    pt = jnp.zeros((r, max_pages), jnp.int32)
    kv = jnp.full((r,), w, jnp.int32)
    li = jnp.full((r,), w - 1, jnp.int32)
    dl = jnp.zeros((r,), jnp.int32)
    return q, kc, vc, pt, kv, li, dl


def test_ragged_kernel_lowers_for_tpu():
    """The fused unified-step kernel at a serving-shape [R, W]
    block."""
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    _lower_for_tpu(paged_ragged_attention, *_ragged_args())


@pytest.mark.parametrize("w", [16, 64, 256])
def test_ragged_kernel_lowers_every_width(w):
    """Every W bucket the mixed planner can emit must lower (the
    model runner's _ragged_lowering_error matrix)."""
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    _lower_for_tpu(paged_ragged_attention, *_ragged_args(w=w))


def test_ragged_kernel_lowers_small_head_thin_rows():
    """head_dim=64 with a thin row block: the q/o blocks are not
    naturally (8, 128)-divisible and must pad to true tile multiples
    — the class of shape that lowered cross-platform and then failed
    Mosaic's machine-code pass on chip in BENCH_r02."""
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    _lower_for_tpu(
        paged_ragged_attention,
        *_ragged_args(r=4, w=4, kv_heads=8, q_heads=8, head_dim=64))


def test_prefill_kernel_lowers_small_head_thin_rows():
    """The BENCH_r02 failing class for the prefill kernel: MHA
    (group 1) at a thin verify-style chunk with head_dim=64 — the
    whole-array block escape hatch the Python lowering rules allow is
    NOT honored by the machine-code pass, so the kernel now pads to
    true (8, 128) multiples; this shape is also in the model runner's
    probe matrix via the spec/unified probes."""
    from production_stack_tpu.ops.prefill_attention_pallas import (
        paged_prefill_attention,
    )
    _lower_for_tpu(
        paged_prefill_attention,
        *_prefill_args(b=8, t=4, kv_heads=8, q_heads=8, head_dim=64))


def _quantize_lowering_cache(cache):
    from production_stack_tpu.ops.quant_kv import QuantKV, quantize_kv
    perm = (0, 1, 3, 2)
    q, scale = quantize_kv(jnp.transpose(cache, perm))
    return QuantKV(jnp.transpose(q, perm), scale)


def test_decode_kernel_int8_lowers_for_tpu():
    """paged_decode_attention over int8 QuantKV pages (extra scale
    DMAs + VMEM scratch) must pass the Mosaic lowering rules."""
    from production_stack_tpu.ops.paged_attention_pallas import (
        paged_decode_attention,
    )
    q, kc, vc, pt, kl = _decode_args()
    _lower_for_tpu(
        paged_decode_attention, q,
        _quantize_lowering_cache(kc), _quantize_lowering_cache(vc),
        pt, kl)


def test_prefill_kernel_int8_lowers_for_tpu():
    from production_stack_tpu.ops.prefill_attention_pallas import (
        paged_prefill_attention,
    )
    q, kc, vc, pt, pos, kl = _prefill_args()
    _lower_for_tpu(
        paged_prefill_attention, q,
        _quantize_lowering_cache(kc), _quantize_lowering_cache(vc),
        pt, pos, kl)


def test_ragged_kernel_int8_lowers_for_tpu():
    """paged_ragged_attention over int8 QuantKV pages (scale DMAs
    through the shared pipeline) must pass the Mosaic lowering
    rules."""
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    q, kc, vc, pt, kv, li, dl = _ragged_args()
    _lower_for_tpu(
        paged_ragged_attention, q,
        _quantize_lowering_cache(kc), _quantize_lowering_cache(vc),
        pt, kv, li, dl)


def test_decode_burst_program_int8_lowers_for_tpu():
    """The fused decode burst with --kv-cache-dtype int8 and pallas
    attention: quantize-on-commit + in-kernel dequant + QuantKV
    carries through lax.scan must lower as one TPU program."""
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, SchedulerConfig, tiny_model_config,
    )
    from production_stack_tpu.engine.model_runner import ModelRunner

    model = tiny_model_config("llama")
    model.attention_impl = "pallas"
    config = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=128, num_pages=32,
                          kv_cache_dtype="int8"),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256,
                                  prefill_chunk_size=64,
                                  decode_steps=8),
    )
    runner = ModelRunner(config)
    assert runner.kv_quantized
    b = 4
    args = (
        runner.params, runner.k_cache, runner.v_cache,
        jnp.zeros((b, 1), jnp.int32), jnp.zeros((b, 1), jnp.int32),
        jnp.zeros((b, runner.max_pages_per_seq), jnp.int32),
        jnp.zeros((b,), jnp.int32), jnp.zeros((b,), bool),
        jnp.zeros((b,), jnp.int32),
        jnp.full((b, 16), -1, jnp.int32),
        jnp.zeros((b,), jnp.float32), jnp.ones((b,), jnp.float32),
        jnp.zeros((b,), jnp.int32), jax.random.PRNGKey(0),
        None, None,   # lora, lora_ids
        None, None,   # penalties, seeding
        None, None, None,  # bias, suppress, fsm
    )
    traced = jax.jit(
        runner._decode_burst_impl, static_argnames=("num_steps",)
    ).trace(*args, num_steps=8)
    traced.lower(lowering_platforms=("tpu",))
