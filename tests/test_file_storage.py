"""Async FileStorage round-trip (test model: reference
src/tests/test_file_storage.py)."""

import pytest

from production_stack_tpu.router.services.files import (
    FileStorage,
    initialize_storage,
)


@pytest.fixture
def storage(tmp_path):
    return FileStorage(str(tmp_path))


async def test_save_and_get_roundtrip(storage):
    file = await storage.save_file("alice", "data.jsonl", b"hello world")
    assert file.bytes == 11
    assert file.filename == "data.jsonl"

    meta = await storage.get_file("alice", file.id)
    assert meta.id == file.id
    assert meta.bytes == 11

    content = await storage.get_file_content("alice", file.id)
    assert content == b"hello world"


async def test_user_isolation(storage):
    file = await storage.save_file("alice", "a.txt", b"secret")
    with pytest.raises(FileNotFoundError):
        await storage.get_file("bob", file.id)


async def test_list_and_delete(storage):
    f1 = await storage.save_file("u", "one.txt", b"1")
    f2 = await storage.save_file("u", "two.txt", b"22")
    files = await storage.list_files("u")
    assert {f.id for f in files} == {f1.id, f2.id}

    await storage.delete_file("u", f1.id)
    files = await storage.list_files("u")
    assert {f.id for f in files} == {f2.id}
    with pytest.raises(FileNotFoundError):
        await storage.get_file_content("u", f1.id)


async def test_missing_file_raises(storage):
    with pytest.raises(FileNotFoundError):
        await storage.get_file("u", "file-nope")


def test_initialize_storage_factory(tmp_path):
    s = initialize_storage("local_file", str(tmp_path))
    assert isinstance(s, FileStorage)
    with pytest.raises(ValueError):
        initialize_storage("s3", str(tmp_path))


async def test_path_traversal_blocked(tmp_path):
    storage = FileStorage(str(tmp_path / "base"))
    file = await storage.save_file("..", "evil.txt", b"x")
    # Content must land inside the base dir, not its parent.
    import os
    for root, _, files in os.walk(str(tmp_path / "base")):
        if file.id in files:
            break
    else:
        raise AssertionError("file not stored under base dir")
    with pytest.raises(FileNotFoundError):
        await storage.get_file_content("victim", "../../etc/passwd")
