"""HTTP-level tests of the engine server (OpenAI surface + /metrics).

Test model: the reference's fake-openai-server-based e2e rig
(src/tests/perftest + router-e2e-test.yml), but against the REAL engine
with a tiny model — no TPU required.
"""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.server import EngineServer


def make_server() -> EngineServer:
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4, max_model_len=256,
                                  prefill_chunk_size=64),
    )
    engine = LLMEngine(config)
    return EngineServer(engine, "tiny-llama")


async def _with_client(fn):
    server = make_server()
    client = TestClient(TestServer(server.build_app()))
    await client.start_server()
    try:
        await fn(client)
    finally:
        await client.close()


def test_models_health_version():
    async def run(client):
        resp = await client.get("/v1/models")
        assert resp.status == 200
        data = await resp.json()
        assert data["data"][0]["id"] == "tiny-llama"
        assert (await client.get("/health")).status == 200
        assert (await client.get("/version")).status == 200
    asyncio.run(_with_client(run))


def test_chat_completion_non_streaming():
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 8,
            "temperature": 0,
            "ignore_eos": True,
        })
        assert resp.status == 200
        data = await resp.json()
        assert data["object"] == "chat.completion"
        assert data["choices"][0]["finish_reason"] == "length"
        assert data["usage"]["completion_tokens"] == 8
        assert isinstance(
            data["choices"][0]["message"]["content"], str
        )
    asyncio.run(_with_client(run))


def test_chat_completion_streaming():
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 6,
            "temperature": 0,
            "ignore_eos": True,
            "stream": True,
        })
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith(
            "text/event-stream"
        )
        events = []
        async for line in resp.content:
            line = line.decode().strip()
            if line.startswith("data: "):
                events.append(line[len("data: "):])
        assert events[-1] == "[DONE]"
        first = json.loads(events[0])
        assert first["choices"][0]["delta"].get("role") == "assistant"
        finishes = [json.loads(e)["choices"][0]["finish_reason"]
                    for e in events[:-1]]
        assert finishes[-1] == "length"
    asyncio.run(_with_client(run))


def test_completions_endpoint():
    async def run(client):
        resp = await client.post("/v1/completions", json={
            "model": "tiny-llama",
            "prompt": "abc",
            "max_tokens": 4,
            "temperature": 0,
            "ignore_eos": True,
        })
        assert resp.status == 200
        data = await resp.json()
        assert data["object"] == "text_completion"
        assert data["usage"]["completion_tokens"] == 4
    asyncio.run(_with_client(run))


def test_metrics_exposition_names():
    async def run(client):
        # Generate some load first.
        await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 2, "temperature": 0, "ignore_eos": True,
        })
        resp = await client.get("/metrics")
        text = await resp.text()
        # The names the router scrapes (engine_stats.py contract).
        for name in (
            "vllm:num_requests_running",
            "vllm:num_requests_waiting",
            "vllm:gpu_cache_usage_perc",
            "vllm:gpu_prefix_cache_hit_rate",
        ):
            assert name in text, f"missing {name}"
    asyncio.run(_with_client(run))


def test_concurrent_requests_batched():
    async def run(client):
        async def one(i):
            resp = await client.post("/v1/chat/completions", json={
                "model": "tiny-llama",
                "messages": [{"role": "user", "content": f"req {i}"}],
                "max_tokens": 5, "temperature": 0, "ignore_eos": True,
            })
            assert resp.status == 200
            data = await resp.json()
            assert data["usage"]["completion_tokens"] == 5
        await asyncio.gather(*(one(i) for i in range(6)))
    asyncio.run(_with_client(run))


def test_oversized_prompt_rejected_with_400():
    async def run(client):
        resp = await client.post("/v1/completions", json={
            "model": "tiny-llama",
            "prompt": list(range(1, 400)),  # > max_model_len=256
            "max_tokens": 4,
        })
        assert resp.status == 400
        data = await resp.json()
        assert "max_model_len" in data["error"]["message"]
    asyncio.run(_with_client(run))


def test_malformed_json_rejected_with_400():
    async def run(client):
        resp = await client.post(
            "/v1/chat/completions", data=b"{nope",
            headers={"content-type": "application/json"},
        )
        assert resp.status == 400
    asyncio.run(_with_client(run))


def test_null_sampling_params_use_openai_defaults():
    from production_stack_tpu.engine.server import _sampling_from_body
    sp = _sampling_from_body(
        {"temperature": None, "top_p": None, "max_tokens": 4}, 256
    )
    assert sp.temperature == 1.0
    assert sp.top_p == 1.0
    sp = _sampling_from_body({"temperature": 0, "max_tokens": 4}, 256)
    assert sp.temperature == 0.0


def test_engine_latency_histograms_after_traffic():
    """/metrics exposes vLLM-parity TTFT/ITL/e2e histograms and token
    counters once requests have completed."""
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 6,
        })
        assert resp.status == 200
        await resp.json()
        text = await (await client.get("/metrics")).text()
        assert 'vllm:time_to_first_token_seconds_count 1' in text
        assert 'vllm:e2e_request_latency_seconds_count 1' in text
        # TTFT decomposition: queue wait vs prefill compute.
        assert 'vllm:request_queue_time_seconds_count 1' in text
        assert 'vllm:request_prefill_time_seconds_count 1' in text
        assert 'vllm:time_per_output_token_seconds_bucket' in text
        assert 'vllm:generation_tokens_total 6' in text
        assert 'vllm:request_success_total{finished_reason="length"} 1' \
            in text
    asyncio.run(_with_client(run))


def test_chat_template_override():
    """--chat-template Jinja source takes priority over the default
    role-tagged rendering (reference chart's chatTemplate knob)."""
    from production_stack_tpu.engine.tokenizer import (
        ByteTokenizer,
        render_chat_prompt,
    )
    tok = ByteTokenizer()
    messages = [{"role": "user", "content": "hi"}]
    tpl = "{% for m in messages %}[{{ m.role }}]{{ m.content }}{% endfor %}>>"
    ids = render_chat_prompt(tok, messages, chat_template=tpl)
    assert tok.decode(ids) == "[user]hi>>"
    # A broken template falls back to the default rendering (loudly).
    bad = render_chat_prompt(tok, messages,
                             chat_template="{{ undefined_fn() }}")
    default = render_chat_prompt(tok, messages, chat_template=None)
    assert bad == default and tok.decode(bad) != ""


def test_bench_tokenizer_full_vocab_decode():
    """BenchTokenizer: every id >= 258 decodes to one printable char —
    a random-weights bench server must stream a non-empty delta per
    generated token (the ByteTokenizer dropped ids >= 256, so the
    round-5 QPS sweep saw zero TTFT signal and gen_tokens == 0)."""
    from production_stack_tpu.engine.tokenizer import (
        BenchTokenizer,
        get_tokenizer,
    )
    tok = get_tokenizer("bench")
    assert isinstance(tok, BenchTokenizer)
    # Byte-range behavior identical to ByteTokenizer.
    assert tok.encode("hi") == [tok.BOS, 104, 105]
    assert tok.decode([104, 105]) == "hi"
    # Specials stay invisible; everything else is one printable char.
    assert tok.decode([tok.BOS, tok.EOS]) == ""
    for tid in (258, 1000, 32127):
        s = tok.decode([tid])
        assert len(s) == 1 and s.isprintable(), (tid, s)
    # Mixed byte-range + high ids interleave in order.
    assert tok.decode([104, 5000, 105]) == (
        "h" + chr(33 + (5000 - 258) % 94) + "i")


def test_n_choices_non_streaming():
    """n > 1 returns n independent choices with summed usage."""
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 6, "temperature": 0.0, "n": 3,
        })
        assert resp.status == 200
        data = await resp.json()
        assert [c["index"] for c in data["choices"]] == [0, 1, 2]
        # Greedy: all choices identical (and thus provably complete).
        texts = {c["message"]["content"] for c in data["choices"]}
        assert len(texts) == 1
        assert data["usage"]["completion_tokens"] == 18
    asyncio.run(_with_client(run))


def test_n_rejected_out_of_range():
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "x"}],
            "n": 0,
        })
        assert resp.status == 400
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "x"}],
            "n": "many",
        })
        assert resp.status == 400
    asyncio.run(_with_client(run))


def test_n_choices_streaming_indexes_chunks():
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 4, "temperature": 0.0, "n": 2,
            "stream": True,
        })
        assert resp.status == 200
        raw = (await resp.read()).decode()
        assert raw.strip().endswith("data: [DONE]")
        finishes = set()
        for line in raw.splitlines():
            if line.startswith("data: {"):
                payload = json.loads(line[len("data: "):])
                choice = payload["choices"][0]
                if choice.get("finish_reason"):
                    finishes.add(choice["index"])
        assert finishes == {0, 1}
    asyncio.run(_with_client(run))


def test_stop_string_truncates_and_aborts():
    """A stop sequence ends generation early and is not returned."""
    async def run(client):
        # Learn the greedy continuation first.
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 12, "temperature": 0.0,
        })
        full = (await resp.json())["choices"][0]["message"]["content"]
        # Use a mid-text fragment as the stop string.
        assert len(full) > 4
        stop = full[2:4]
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 12, "temperature": 0.0, "stop": stop,
        })
        data = await resp.json()
        text = data["choices"][0]["message"]["content"]
        assert stop not in text
        assert text == full[:full.find(stop)]
        assert data["choices"][0]["finish_reason"] == "stop"
    asyncio.run(_with_client(run))


def test_stop_string_streaming_holds_back_partial_match():
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 12, "temperature": 0.0,
        })
        full = (await resp.json())["choices"][0]["message"]["content"]
        stop = full[2:4]
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 12, "temperature": 0.0, "stop": stop,
            "stream": True,
        })
        raw = (await resp.read()).decode()
        text = ""
        for line in raw.splitlines():
            if line.startswith("data: {"):
                payload = json.loads(line[len("data: "):])
                text += payload["choices"][0]["delta"].get(
                    "content", "")
        assert stop not in text
        assert text == full[:full.find(stop)]
    asyncio.run(_with_client(run))


def test_penalties_change_sampling():
    """A strong presence penalty must change greedy output whenever
    the unpenalized continuation repeats a token."""
    async def run(client):
        body = {
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 16, "temperature": 0.0,
        }
        r1 = await (await client.post(
            "/v1/chat/completions", json=body)).json()
        body2 = dict(body, presence_penalty=2.0,
                     frequency_penalty=1.5)
        r2 = await (await client.post(
            "/v1/chat/completions", json=body2)).json()
        assert r2["choices"][0]["finish_reason"] in ("stop", "length")
        # Both runs completed; the penalty request exercised the
        # penalized compiled path end to end (output may or may not
        # differ depending on whether greedy repeats tokens).
        assert r1["usage"]["completion_tokens"] == 16
        assert r2["usage"]["completion_tokens"] >= 1
    asyncio.run(_with_client(run))


def test_chat_logprobs():
    """logprobs + top_logprobs return per-token entries whose sampled
    logprob appears among the tops for greedy decoding."""
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 5, "temperature": 0.0,
            "logprobs": True, "top_logprobs": 3,
        })
        assert resp.status == 200
        data = await resp.json()
        content = data["choices"][0]["logprobs"]["content"]
        assert len(content) == 5
        for entry in content:
            assert entry["logprob"] <= 0.0
            assert len(entry["top_logprobs"]) == 3
            # Greedy: the sampled token IS the top-1 alternative.
            assert entry["top_logprobs"][0]["token"] == entry["token"]
            assert (abs(entry["top_logprobs"][0]["logprob"]
                        - entry["logprob"]) < 1e-4)


    asyncio.run(_with_client(run))


def test_completions_legacy_logprobs():
    async def run(client):
        resp = await client.post("/v1/completions", json={
            "model": "tiny-llama", "prompt": "hello world",
            "max_tokens": 4, "temperature": 0.0, "logprobs": 2,
        })
        assert resp.status == 200
        lp = (await resp.json())["choices"][0]["logprobs"]
        assert len(lp["tokens"]) == 4
        assert len(lp["token_logprobs"]) == 4
        # Text-keyed dicts may collapse ids that decode identically
        # (byte-fallback chars in the tiny vocab).
        assert all(1 <= len(t) <= 2 for t in lp["top_logprobs"])
    asyncio.run(_with_client(run))


def test_logprobs_streaming_chunks():
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 4, "temperature": 0.0,
            "logprobs": True, "top_logprobs": 2, "stream": True,
        })
        raw = (await resp.read()).decode()
        entries = []
        for line in raw.splitlines():
            if line.startswith("data: {"):
                payload = json.loads(line[len("data: "):])
                lp = payload["choices"][0].get("logprobs")
                if lp:
                    entries.extend(lp["content"])
        assert len(entries) == 4
    asyncio.run(_with_client(run))


def test_stop_string_drops_truncated_logprob_entries():
    """logprobs.content must align with the truncated text when a stop
    string hits: entries for held-back/truncated tokens are dropped."""
    async def run(client):
        base = {
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 10, "temperature": 0.0,
            "logprobs": True, "top_logprobs": 1,
        }
        full = await (await client.post(
            "/v1/chat/completions", json=base)).json()
        full_text = full["choices"][0]["message"]["content"]
        full_entries = full["choices"][0]["logprobs"]["content"]
        assert len(full_entries) == 10
        stop = full_text[3:6]
        resp = await (await client.post(
            "/v1/chat/completions",
            json=dict(base, stop=stop))).json()
        text = resp["choices"][0]["message"]["content"]
        entries = resp["choices"][0]["logprobs"]["content"]
        assert stop not in text
        # Released entries' token texts reassemble exactly the
        # returned (truncated) text — no phantom trailing entries.
        assert "".join(e["token"] for e in entries) == text
    asyncio.run(_with_client(run))


def test_top_logprobs_without_logprobs_rejected():
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama",
            "messages": [{"role": "user", "content": "x"}],
            "logprobs": False, "top_logprobs": 2,
        })
        assert resp.status == 400
    asyncio.run(_with_client(run))


def test_best_of_returns_top_n():
    """best_of generates extra candidates and returns the n best by
    mean token logprob, without leaking internal logprobs."""
    async def run(client):
        resp = await client.post("/v1/completions", json={
            "model": "tiny-llama", "prompt": "hello world",
            "max_tokens": 6, "temperature": 0.9, "seed": 11,
            "n": 2, "best_of": 4,
        })
        assert resp.status == 200
        data = await resp.json()
        assert [c["index"] for c in data["choices"]] == [0, 1]
        assert all(c["logprobs"] is None for c in data["choices"])
        # All 4 candidates' tokens count toward usage.
        assert data["usage"]["completion_tokens"] == 24

        # Legacy integer logprobs:0 ("sampled logprob, no
        # alternatives") must survive best_of's internal forcing.
        resp = await client.post("/v1/completions", json={
            "model": "tiny-llama", "prompt": "hello world",
            "max_tokens": 4, "temperature": 0.9, "seed": 3,
            "n": 1, "best_of": 2, "logprobs": 0,
        })
        data = await resp.json()
        lp = data["choices"][0]["logprobs"]
        assert lp is not None and len(lp["token_logprobs"]) == 4

        # Streaming with best_of > n is rejected.
        resp = await client.post("/v1/completions", json={
            "model": "tiny-llama", "prompt": "x", "n": 1,
            "best_of": 2, "stream": True,
        })
        assert resp.status == 400
        # best_of < n is rejected.
        resp = await client.post("/v1/completions", json={
            "model": "tiny-llama", "prompt": "x", "n": 3,
            "best_of": 2,
        })
        assert resp.status == 400
    asyncio.run(_with_client(run))


def test_completions_echo_and_suffix():
    async def run(client):
        resp = await client.post("/v1/completions", json={
            "model": "tiny-llama", "prompt": "hello world",
            "max_tokens": 3, "temperature": 0.0, "echo": True,
        })
        data = await resp.json()
        text = data["choices"][0]["text"]
        prompt_text = "hello world"
        # Echo prepends the (detokenized) prompt; round-tripping the
        # tiny tokenizer reproduces the input string exactly.
        assert text.startswith(prompt_text)
        assert len(text) > len(prompt_text)

        resp = await client.post("/v1/completions", json={
            "model": "tiny-llama", "prompt": "x", "suffix": "tail",
        })
        assert resp.status == 400
        resp = await client.post("/v1/completions", json={
            "model": "tiny-llama", "prompt": "x", "echo": True,
            "logprobs": 1,
        })
        assert resp.status == 400
    asyncio.run(_with_client(run))


def test_completions_echo_streaming():
    async def run(client):
        resp = await client.post("/v1/completions", json={
            "model": "tiny-llama", "prompt": "hello world",
            "max_tokens": 3, "temperature": 0.0, "echo": True,
            "stream": True, "n": 2,
        })
        assert resp.status == 200
        raw = (await resp.read()).decode()
        texts = {0: "", 1: ""}
        for line in raw.splitlines():
            if line.startswith("data: {"):
                payload = json.loads(line[len("data: "):])
                c = payload["choices"][0]
                texts[c["index"]] += c.get("text", "")
        assert texts[0].startswith("hello world")
        assert texts[1].startswith("hello world")
        assert len(texts[0]) > len("hello world")
    asyncio.run(_with_client(run))


def test_stream_options_include_usage():
    """OpenAI stream_options.include_usage: a final pre-[DONE] chunk
    with empty choices and aggregate usage."""
    async def run(client):
        resp = await client.post("/v1/chat/completions", json={
            "model": "tiny-llama", "stream": True,
            "stream_options": {"include_usage": True},
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 4, "ignore_eos": True,
        })
        assert resp.status == 200
        chunks = []
        async for line in resp.content:
            line = line.decode().strip()
            if line.startswith("data: ") and line != "data: [DONE]":
                chunks.append(json.loads(line[len("data: "):]))
        usage_chunks = [c for c in chunks if c.get("usage")]
        assert len(usage_chunks) == 1
        assert usage_chunks[0]["choices"] == []
        u = usage_chunks[0]["usage"]
        assert u["completion_tokens"] == 4
        assert u["total_tokens"] == u["prompt_tokens"] + 4
        # Usage chunk is the LAST data chunk before [DONE].
        assert chunks[-1].get("usage")
    asyncio.run(_with_client(run))
