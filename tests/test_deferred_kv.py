"""Deferred per-burst KV writes (SchedulerConfig.deferred_kv_writes):
the tail-buffer burst must generate exactly what the per-step-write
burst and single-step decoding generate.

Motivation (benchmarks/results/round5_notes.md, round-5 on-chip
ablation): per-step paged scatters cost ~5.1 of 11.1 ms/token-step
for ~1 MB of writes; deferring them to one batched write per layer
per burst removes that cost. Correctness risks covered here: tail
attention masking (positional), mid-burst row freeze (stop/budget),
page-boundary crossings inside a burst, flush-then-continue across
bursts, seeded sampling, and the capability guards.
"""

import numpy as np
import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import SamplingParams


def _engine(decode_steps, deferred=False, max_num_seqs=4, arch="llama",
            quantization=None, cache_layout="auto"):
    model = tiny_model_config(arch)
    if quantization:
        model.quantization = quantization
    config = EngineConfig(
        model=model,
        cache=CacheConfig(page_size=16, num_pages=128,
                          cache_layout=cache_layout),
        scheduler=SchedulerConfig(max_num_seqs=max_num_seqs,
                                  max_model_len=256,
                                  prefill_chunk_size=32,
                                  decode_steps=decode_steps,
                                  deferred_kv_writes=deferred),
    )
    return LLMEngine(config)


def _gen(engine, prompts, **kw):
    sampling = dict(max_tokens=12, temperature=0.0, ignore_eos=True)
    sampling.update(kw)
    seqs = []
    for p in prompts:
        sid = engine.add_request(p, SamplingParams(**sampling))
        seqs.append(engine.sequences[sid])
    while engine.has_work():
        engine.step()
    return [s.output_token_ids for s in seqs]


def _prompts(sizes=(7, 20, 41), hi=500, seed=1):
    rs = np.random.RandomState(seed)
    return [[int(x) for x in rs.randint(1, hi, size=n)] for n in sizes]


def test_deferred_matches_single_step_greedy():
    prompts = _prompts()
    expected = _gen(_engine(decode_steps=1), prompts)
    got = _gen(_engine(decode_steps=4, deferred=True), prompts)
    assert got == expected
    assert all(len(t) == 12 for t in got)


def test_deferred_matches_eager_burst_multi_burst():
    """20 tokens at K=4 = 5 flush/continue cycles; page_size 16 puts
    page-boundary crossings inside bursts for every row."""
    prompts = _prompts(sizes=(15, 31, 16, 47))
    eager = _gen(_engine(decode_steps=4), prompts, max_tokens=20)
    deferred = _gen(_engine(decode_steps=4, deferred=True), prompts,
                    max_tokens=20)
    assert deferred == eager


def test_deferred_stop_token_mid_burst():
    """A row hitting its stop set mid-burst freezes; its tail slots
    must not pollute the flush (valid = emitted count)."""
    prompts = _prompts(sizes=(9, 12))
    ref = _gen(_engine(decode_steps=1), prompts, max_tokens=16,
               ignore_eos=False)
    # Use each row's 3rd greedy token as its stop token so the stop
    # fires mid-burst deterministically.
    stops = [r[2] for r in ref]
    eager, deferred = (
        [_gen(_engine(decode_steps=8, deferred=d), [p],
              max_tokens=16, stop_token_ids=[s], ignore_eos=False)[0]
         for p, s in zip(prompts, stops)]
        for d in (False, True))
    assert deferred == eager
    # The stop fired mid-burst: output ends at the stop token, short
    # of the 16-token budget.
    for t, s in zip(deferred, stops):
        assert t[-1] == s and len(t) < 16


def test_deferred_seeded_sampling_parity():
    """Seeded stochastic sampling depends only on (seed, emitted
    index), so deferred and eager bursts must sample identically."""
    prompts = _prompts(sizes=(11, 23))
    kw = dict(temperature=0.9, seed=1234, max_tokens=10)
    eager = _gen(_engine(decode_steps=4), prompts, **kw)
    deferred = _gen(_engine(decode_steps=4, deferred=True), prompts,
                    **kw)
    assert deferred == eager


def test_deferred_int8_and_stacked_layout():
    prompts = _prompts(sizes=(10, 33))
    for layout in ("per_layer", "stacked"):
        eager = _gen(_engine(decode_steps=4, cache_layout=layout,
                             quantization="int8"), prompts)
        deferred = _gen(_engine(decode_steps=4, deferred=True,
                                cache_layout=layout,
                                quantization="int8"), prompts)
        assert deferred == eager, layout


def test_deferred_penalties_and_logprobs_parity():
    """Penalties and logprob extraction run in the shared burst step
    body (_burst_sample_step) — pin that the deferred path reproduces
    the eager path's outputs AND per-token logprob records exactly."""
    prompts = _prompts(sizes=(13, 27))
    kw = dict(max_tokens=10, presence_penalty=0.8,
              frequency_penalty=0.3, logprobs=True, top_logprobs=3)

    def run(deferred):
        engine = _engine(decode_steps=4, deferred=deferred)
        seqs, lps = [], {}
        for p in prompts:
            sid = engine.add_request(p, SamplingParams(
                temperature=0.0, ignore_eos=True, **kw))
            seqs.append(engine.sequences[sid])
            lps[sid] = []
        while engine.has_work():
            for out in engine.step():
                if out.logprobs is not None:
                    lps[out.seq_id].append(out.logprobs)
        return [(s.output_token_ids, lps[s.seq_id]) for s in seqs]

    eager = run(False)
    deferred = run(True)
    for (et, elp), (dt, dlp) in zip(eager, deferred):
        assert dt == et
        assert len(dlp) == len(elp) == 10
        for (es, etop), (ds, dtop) in zip(elp, dlp):
            assert abs(es - ds) < 1e-3
            assert [t for t, _ in etop] == [t for t, _ in dtop]


def test_deferred_guards():
    with pytest.raises(ValueError, match="decode_steps"):
        _engine(decode_steps=1, deferred=True)
    with pytest.raises(NotImplementedError, match="llama family"):
        _engine(decode_steps=4, deferred=True, arch="gpt2")
