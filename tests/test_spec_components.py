"""Unit tests for the speculative-decoding building blocks: the
prompt-lookup proposer, the vectorized acceptance rule, config/feature
gating, and the metrics surfaces. Fast lane — no engine end-to-end
runs here (those live in test_spec_decode.py, slow lane)."""

from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from production_stack_tpu.engine.spec import NgramProposer


def _seq(tokens, seq_id="s0"):
    return SimpleNamespace(seq_id=seq_id, all_token_ids=list(tokens))


# ---- NgramProposer ---------------------------------------------------------


def test_proposer_basic_lookup():
    # ... 7 8 9 10 ... 7 8 -> continuation 9 10
    p = NgramProposer(k=4, min_match=2)
    drafts = p.propose(_seq([1, 7, 8, 9, 10, 2, 3, 7, 8]), 4)
    assert drafts[:2] == [9, 10]


def test_proposer_no_match_returns_empty():
    p = NgramProposer(k=4, min_match=2)
    assert p.propose(_seq([1, 2, 3, 4, 5, 6]), 4) == []


def test_proposer_short_history_returns_empty():
    p = NgramProposer(k=4, min_match=2)
    assert p.propose(_seq([1, 2]), 4) == []
    assert p.propose(_seq([1, 2, 3]), 0) == []


def test_proposer_clamps_to_k_and_budget():
    p = NgramProposer(k=3, min_match=2)
    hist = [5, 6, 7, 8, 9, 5, 6]
    assert len(p.propose(_seq(hist, "a"), 10)) <= 3
    assert len(p.propose(_seq(hist, "b"), 1)) == 1


def test_proposer_periodic_self_continuation():
    """A looping tail must draft FULL-length, wrapping around the
    period — not stop at the end of recorded history. This is the
    case speculation pays most for, and where a naive slice yields
    one token per step."""
    p = NgramProposer(k=8, min_match=2)
    loop = [11, 12, 13]
    drafts = p.propose(_seq(loop * 6), 8)
    assert len(drafts) == 8
    # History ends ...11 12 13; the continuation keeps looping.
    expect = [loop[i % 3] for i in range(8)]
    assert drafts == expect


def test_proposer_period_one_loop():
    p = NgramProposer(k=6, min_match=2)
    drafts = p.propose(_seq([3, 9, 9, 9, 9, 9]), 6)
    assert drafts == [9] * 6


def test_proposer_prefers_longer_backward_match():
    """Two occurrences of the tail bigram with different
    continuations: the one whose preceding context also matches
    (max-match) wins even though the other is more recent."""
    p = NgramProposer(k=2, min_match=2)
    #       [ctx-match]            [recent, no ctx]
    hist = [40, 41, 1, 2, 77, 77, 50, 1, 2, 88, 88, 40, 41, 1, 2]
    assert p.propose(_seq(hist), 2) == [77, 77]


def test_proposer_candidate_scan_is_capped():
    """A constant-token history indexes O(n) occurrences of the same
    gram; proposal must stay cheap (MAX_CANDIDATES scored, and the
    capped backward scan short-circuits on the first max hit)."""
    p = NgramProposer(k=4, min_match=2)
    drafts = p.propose(_seq([7] * 5000), 4)
    assert drafts == [7, 7, 7, 7]


def test_proposer_drop_releases_index():
    p = NgramProposer(k=4, min_match=2)
    p.propose(_seq([1, 2, 3, 1, 2], "gone"), 4)
    assert "gone" in p._index
    p.drop("gone")
    assert "gone" not in p._index
    p.drop("never-indexed")  # idempotent


def test_proposer_validates_args():
    with pytest.raises(ValueError):
        NgramProposer(k=0)
    with pytest.raises(ValueError):
        NgramProposer(k=2, min_match=0)


# ---- spec_verify acceptance rule ------------------------------------------


def _point_logits(targets, vocab=16, scale=50.0):
    """[1, S, V] logits whose argmax (and ~all mass) at offset j is
    targets[j]."""
    s = len(targets)
    out = np.zeros((1, s, vocab), np.float32)
    for j, t in enumerate(targets):
        out[0, j, t] = scale
    return jnp.asarray(out)


def _verify(logits, drafts, lens, temps):
    from production_stack_tpu.ops.sampling import spec_verify

    b = logits.shape[0]
    return np.asarray(spec_verify(
        logits, jnp.asarray(drafts, jnp.int32),
        jnp.asarray(lens, jnp.int32),
        jnp.asarray(temps, jnp.float32),
        jnp.ones((b,), jnp.float32), jnp.zeros((b,), jnp.int32),
        jax.random.PRNGKey(0)))


def test_verify_greedy_partial_accept():
    logits = _point_logits([3, 5, 7, 9])
    out = _verify(logits, [[3, 5, 2]], [3], [0.0])
    # Drafts 3,5 match the argmax chain; 2 != 7 rejects, the
    # correction is the target argmax at the rejection offset.
    assert out.tolist() == [[3, 5, 7, -1]]


def test_verify_greedy_full_accept_emits_bonus():
    logits = _point_logits([3, 5, 7, 9])
    out = _verify(logits, [[3, 5, 7]], [3], [0.0])
    assert out.tolist() == [[3, 5, 7, 9]]


def test_verify_greedy_zero_drafts_is_plain_decode():
    logits = _point_logits([3, 5, 7, 9])
    out = _verify(logits, [[-1, -1, -1]], [0], [0.0])
    assert out.tolist() == [[3, -1, -1, -1]]


def test_verify_greedy_first_reject_stops_acceptance():
    # A later "match" after a rejection must not count.
    logits = _point_logits([3, 5, 7, 9])
    out = _verify(logits, [[4, 5, 7]], [3], [0.0])
    assert out.tolist() == [[3, -1, -1, -1]]


def test_verify_stochastic_point_mass_accepts():
    """With near-point-mass target distributions, rejection sampling
    accepts drafts equal to the mass point w.p. ~1 and the bonus
    sample is the mass point."""
    logits = _point_logits([3, 5, 7, 9])
    out = _verify(logits, [[3, 5, 7]], [3], [1.0])
    assert out.tolist() == [[3, 5, 7, 9]]


def test_verify_stochastic_rejects_off_mass_draft():
    logits = _point_logits([3, 5, 7, 9])
    out = _verify(logits, [[4, 5, 7]], [3], [1.0])
    row = out[0].tolist()
    # Rejected at offset 0; exactly one emitted token drawn from the
    # residual (draft token 4 removed) — the mass point 3.
    assert row == [3, -1, -1, -1]


def test_verify_mixed_batch_keeps_greedy_rows_exact():
    """A stochastic row in the batch must not perturb a greedy row's
    byte-exact acceptance (the whole-batch stochastic branch still
    applies the greedy rule per-row)."""
    targets = [3, 5, 7, 9]
    logits = jnp.concatenate(
        [_point_logits(targets), _point_logits(targets)])
    out = _verify(logits, [[3, 5, 2], [3, 5, 7]], [3, 3], [0.0, 1.0])
    assert out[0].tolist() == [3, 5, 7, -1]
    assert out[1].tolist()[:3] == [3, 5, 7]


# ---- config + feature gating ----------------------------------------------


def _sched(**kw):
    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        SchedulerConfig,
        tiny_model_config,
    )

    return EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32, **kw),
    )


def test_config_spec_composes_with_decode_steps():
    cfg = _sched(speculative_k=4, decode_steps=4)
    assert cfg.scheduler.speculative_k == 4


def test_config_spec_rejects_deferred_kv():
    with pytest.raises(ValueError, match="deferred_kv"):
        _sched(speculative_k=4, decode_steps=4, deferred_kv_writes=True)


def test_config_spec_rejects_bad_min_match():
    with pytest.raises(ValueError, match="min_match"):
        _sched(speculative_k=4, speculative_min_match=0)


def test_deferred_kv_eligibility_excludes_spec():
    from production_stack_tpu.engine.model_runner import (
        deferred_kv_eligible,
    )

    base = dict(architecture="llama", decode_steps=4,
                attention_impl="xla")
    assert deferred_kv_eligible(**base)
    assert not deferred_kv_eligible(**base, speculative_k=4)


# ---- metrics surfaces ------------------------------------------------------


def test_metrics_render_spec_counters():
    from production_stack_tpu.engine.metrics import EngineMetrics

    m = EngineMetrics()
    m.on_spec_step(drafted=8, accepted=5)
    m.on_spec_step(drafted=4, accepted=4)
    text = "\n".join(m.render())
    assert "vllm:spec_decode_num_draft_tokens_total 12" in text
    assert "vllm:spec_decode_num_accepted_tokens_total 9" in text


def test_router_scrapes_spec_counters():
    from production_stack_tpu.router.stats.engine_stats import (
        EngineStats,
    )

    text = "\n".join([
        "# TYPE vllm:num_requests_running gauge",
        "vllm:num_requests_running 2.0",
        "# TYPE vllm:gpu_prefix_cache_hit_rate gauge",
        "vllm:gpu_prefix_cache_hit_rate 0.25",
        "# TYPE vllm:spec_decode_num_draft_tokens_total counter",
        "vllm:spec_decode_num_draft_tokens_total 120.0",
        "# TYPE vllm:spec_decode_num_accepted_tokens_total counter",
        "vllm:spec_decode_num_accepted_tokens_total 90.0",
        "",
    ])
    stats = EngineStats.from_prometheus_text(text)
    assert stats.spec_decode_num_draft_tokens == 120.0
    assert stats.spec_decode_num_accepted_tokens == 90.0


def test_router_reexports_scraped_spec_gauges():
    """refresh_gauges surfaces the scraped engine counters on the
    router's own /metrics exposition, labeled per server."""
    from production_stack_tpu.router.services import metrics_service
    from production_stack_tpu.router.stats.engine_stats import (
        EngineStats,
        initialize_engine_stats_scraper,
    )
    from production_stack_tpu.router.stats.request_stats import (
        initialize_request_stats_monitor,
    )

    initialize_request_stats_monitor(60.0)
    scraper = initialize_engine_stats_scraper(scrape_interval=3600.0)
    try:
        with scraper._lock:
            scraper._stats = {"http://e1:8000": EngineStats(
                kv_cache_hit_rate=0.5,
                spec_decode_num_draft_tokens=40.0,
                spec_decode_num_accepted_tokens=30.0)}
        metrics_service.refresh_gauges()
        g = metrics_service.spec_decode_num_draft_tokens
        assert g.labels(server="http://e1:8000")._value.get() == 40.0
        g = metrics_service.spec_decode_num_accepted_tokens
        assert g.labels(server="http://e1:8000")._value.get() == 30.0
        g = metrics_service.engine_prefix_cache_hit_rate
        assert g.labels(server="http://e1:8000")._value.get() == 0.5
    finally:
        scraper.close()
