"""Cross-hop trace stitching (production_stack_tpu/traceview.py,
docs/observability.md).

Two layers: a golden merge over hand-written span lines with fixed
timestamps (exact waterfall ordering, no live servers), and the
acceptance path — a greedy streaming request over the router's
disaggregated two-hop dispatch with span logging on everywhere, whose
three span lines (router, prefill engine, decode engine) must stitch
into one waterfall with non-negative phase durations, populated hop
fields, and zero failover retries.
"""

import json

from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.router import tracing as router_tracing
from production_stack_tpu.router.resilience import (
    ResilienceConfig,
    initialize_resilience,
)
from production_stack_tpu.router.service_discovery import (
    initialize_service_discovery,
)
from production_stack_tpu.router.services import request_service
from production_stack_tpu.router.services.rewriter import (
    initialize_request_rewriter,
)
from production_stack_tpu.router.stats.engine_stats import (
    initialize_engine_stats_scraper,
)
from production_stack_tpu.router.stats.request_stats import (
    initialize_request_stats_monitor,
)
from production_stack_tpu.testing.fake_engine import build_fake_engine
from production_stack_tpu.traceview import (
    load_spans,
    main as traceview_main,
    render_waterfall,
    stitch,
)


# ---- golden merge ------------------------------------------------------

_ROUTER_LINE = {
    "span": "request", "request_id": "rid-g", "model": "m1",
    "path": "/v1/chat/completions", "backend": "http://dec:1",
    "arrival_ts": 1000.0, "queue_delay_ms": 8.0, "ttft_ms": 20.0,
    "latency_ms": 30.0, "chunks": 3, "status": "ok", "retries": 0,
    "tried_backends": [], "prefill_backend": "http://pre:1",
    "handoff_ms": 2.0,
}

_PREFILL_LINE = {
    "span": "engine_request", "request_id": "rid-g", "seq_id": "seq-p",
    "role": "prefill", "arrival_ts": 1000.001,
    "finish_reason": "handoff", "prompt_tokens": 8, "output_tokens": 1,
    "queue_ms": 0.5, "ttft_ms": 3.0, "decode_ms": 0.0,
    "latency_ms": 3.5,
    "events": [
        {"event": "enqueue", "ts": 1000.001, "prompt_tokens": 8},
        {"event": "prefill_chunk", "ts": 1000.003, "start": 0,
         "tokens": 8, "last": True},
        {"event": "first_token", "ts": 1000.004, "token": 7},
        {"event": "handoff_ship", "ts": 1000.0045, "num_pages": 1,
         "kv_bytes": 4096},
        {"event": "finish", "ts": 1000.005, "reason": "handoff"},
    ],
}

_DECODE_LINE = {
    "span": "engine_request", "request_id": "rid-g", "seq_id": "seq-d",
    "role": "decode", "arrival_ts": 1000.008, "finish_reason": "stop",
    "prompt_tokens": 8, "output_tokens": 3, "queue_ms": 0.2,
    "ttft_ms": 1.0, "decode_ms": 10.0, "latency_ms": 11.0,
    "events": [
        {"event": "enqueue", "ts": 1000.008, "prompt_tokens": 8},
        {"event": "awaiting_kv_park", "ts": 1000.0085},
        {"event": "awaiting_kv_restore", "ts": 1000.009,
         "waited_ms": 0.5, "outcome": "ready"},
        {"event": "first_token", "ts": 1000.0095, "token": 7},
        {"event": "finish", "ts": 1000.019, "reason": "stop"},
    ],
}


def _write_lines(path, *objs):
    with open(path, "w") as f:
        for obj in objs:
            f.write(json.dumps(obj) + "\n")


def test_traceview_golden_merge(tmp_path):
    router_log = str(tmp_path / "router.jsonl")
    engines_log = str(tmp_path / "engines.jsonl")
    _write_lines(router_log, _ROUTER_LINE)
    # Engine file also carries a plain log line and a foreign request
    # that must both be ignored.
    with open(engines_log, "w") as f:
        f.write("INFO some ordinary log line\n")
        f.write(json.dumps(_PREFILL_LINE) + "\n")
        f.write(json.dumps(_DECODE_LINE) + "\n")
        f.write(json.dumps({**_DECODE_LINE, "request_id": "other"})
                + "\n")

    spans = load_spans([router_log, engines_log])
    assert len(spans) == 4
    mine = stitch(spans, "rid-g")
    assert len(mine) == 3
    assert mine[0]["span"] == "request"  # router span leads

    text = render_waterfall(spans, "rid-g")
    lines = text.splitlines()
    assert lines[0] == "request rid-g  (3 spans)"

    def row_index(source_frag, event):
        for i, line in enumerate(lines):
            if source_frag in line and f" {event}" in line:
                return i
        raise AssertionError(f"no row {source_frag}/{event}:\n{text}")

    # The acceptance waterfall: router arrival -> prefill engine chunk
    # -> handoff ship -> decode engine restore -> first token ->
    # finish, in that order.
    order = [
        row_index("router", "arrival"),
        row_index("engine[prefill seq-p]", "prefill_chunk"),
        row_index("engine[prefill seq-p]", "handoff_ship"),
        row_index("engine[decode seq-d]", "awaiting_kv_restore"),
        row_index("engine[decode seq-d]", "first_token"),
        row_index("engine[decode seq-d]", "finish"),
    ]
    assert order == sorted(order)
    # Offsets are anchored at the earliest row: all non-negative.
    for line in lines[1:]:
        assert float(line.split("t+")[1].split("ms")[0]) >= 0
    # Hop details surface in the router rows.
    assert "prefill_backend=http://pre:1" in text
    assert "handoff_ms=2.0" in text


def test_traceview_cli(tmp_path, capsys):
    log = str(tmp_path / "all.jsonl")
    _write_lines(log, _ROUTER_LINE, _PREFILL_LINE, _DECODE_LINE)
    assert traceview_main([log, "--request-id", "rid-g"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("request rid-g")
    # No --request-id: render every id found.
    assert traceview_main([log]) == 0
    # Empty input errors.
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert traceview_main([empty]) == 1


def test_traceview_unknown_request(tmp_path):
    log = str(tmp_path / "r.jsonl")
    _write_lines(log, _ROUTER_LINE)
    assert "no spans for request nope" in render_waterfall(
        load_spans([log]), "nope")


# ---- live disagg two-hop stitch (acceptance) ---------------------------


async def _start_disagg_router(backends):
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.routing.logic import (
        initialize_routing_logic,
    )
    request_service.disagg_handoffs_total = 0
    request_service.disagg_fallbacks_total = 0
    initialize_service_discovery(
        "static",
        urls=[b[0] for b in backends],
        models=[b[1] for b in backends],
        roles=[b[2] for b in backends],
    )
    initialize_request_stats_monitor(60.0)
    initialize_engine_stats_scraper(3600.0)
    initialize_routing_logic("roundrobin")
    initialize_request_rewriter("noop")
    initialize_resilience(ResilienceConfig(
        max_retries=2, backend_connect_timeout=1.0,
        backend_timeout=10.0, health_check_interval=0.0,
    ))
    # build_app() with no args: the singletons above (with engine
    # roles) stay in force, and the span logger is installed directly.
    client = TestClient(TestServer(build_app()))
    await client.start_server()
    return client


async def test_disagg_two_hop_stitched_waterfall(tmp_path):
    """A greedy streaming request over the two-hop path leaves three
    span lines that stitch into one waterfall."""
    router_log = str(tmp_path / "router.jsonl")
    pre_log = str(tmp_path / "prefill.jsonl")
    dec_log = str(tmp_path / "decode.jsonl")

    pre = TestServer(build_fake_engine(
        model="m1", speed=1000, ttft=0.0, role="prefill",
        span_log=pre_log))
    dec = TestServer(build_fake_engine(
        model="m1", speed=1000, ttft=0.0, role="decode",
        span_log=dec_log))
    await pre.start_server()
    await dec.start_server()
    pre_url = f"http://127.0.0.1:{pre.port}"
    dec_url = f"http://127.0.0.1:{dec.port}"
    router_tracing.initialize_span_logger(router_log)
    client = None
    try:
        client = await _start_disagg_router([
            (pre_url, "m1", "prefill"),
            (dec_url, "m1", "decode"),
        ])
        resp = await client.post(
            "/v1/chat/completions",
            json={"model": "m1",
                  "messages": [{"role": "user", "content": "hi"}],
                  "max_tokens": 3, "stream": True,
                  "temperature": 0.0})
        assert resp.status == 200
        body = await resp.text()
        assert "tok0" in body and "data: [DONE]" in body
        assert request_service.disagg_handoffs_total == 1
        assert request_service.disagg_fallbacks_total == 0
    finally:
        if client is not None:
            await client.close()
        router_tracing.initialize_span_logger(None)
        await pre.close()
        await dec.close()

    router_span = json.loads(open(router_log).read().splitlines()[0])
    rid = router_span["request_id"]
    # Hop attribution, not failover: two-hop dispatch counts no
    # retries, and both hop fields are populated.
    assert router_span["status"] == "ok"
    assert router_span["retries"] == 0
    assert router_span["tried_backends"] == []
    assert router_span["prefill_backend"] == pre_url
    assert router_span["backend"] == dec_url
    assert router_span["handoff_ms"] is not None
    assert router_span["handoff_ms"] >= 0

    spans = load_spans([router_log, pre_log, dec_log])
    mine = stitch(spans, rid)
    assert len(mine) == 3
    roles = {s.get("role") for s in mine if s["span"] == "engine_request"}
    assert roles == {"prefill", "decode"}
    for span in mine:
        if span["span"] == "engine_request":
            for key in ("queue_ms", "ttft_ms", "latency_ms"):
                assert span[key] is not None and span[key] >= 0

    text = render_waterfall(spans, rid)
    lines = text.splitlines()
    assert lines[0] == f"request {rid}  (3 spans)"

    def row_index(source_frag, event):
        for i, line in enumerate(lines):
            if source_frag in line and f" {event}" in line:
                return i
        raise AssertionError(f"no row {source_frag}/{event}:\n{text}")

    order = [
        row_index("router", "arrival"),
        row_index("engine[prefill", "prefill_chunk"),
        row_index("engine[prefill", "handoff_ship"),
        row_index("engine[decode", "awaiting_kv_restore"),
        row_index("engine[decode", "first_token"),
        row_index("engine[decode", "finish"),
    ]
    assert order == sorted(order)
    for line in lines[1:]:
        assert float(line.split("t+")[1].split("ms")[0]) >= 0
