"""Request-span tracing (SURVEY.md §5 aux-parity: structured spans for
the router lifecycle) and the engine's JAX profiler hook."""

import asyncio
import json

from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.router import tracing


def test_span_json_fields():
    span = tracing.RequestSpan("rid-1", "m", "/v1/chat/completions")
    span.on_routed("http://e:8000")
    span.on_chunk()
    span.on_chunk()
    span.finish("ok")
    data = json.loads(span.to_json())
    assert data["span"] == "request"
    assert data["request_id"] == "rid-1"
    assert data["backend"] == "http://e:8000"
    assert data["chunks"] == 2
    assert data["status"] == "ok"
    assert data["queue_delay_ms"] is not None
    assert data["ttft_ms"] >= 0
    assert data["latency_ms"] >= data["ttft_ms"]


def test_span_logger_file_sink(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    tracing.initialize_span_logger(path)
    try:
        span = tracing.start_span("rid-2", "m", "/v1/completions")
        assert span is not None
        span.finish()
        tracing.get_span_logger().emit(span)
        lines = open(path).read().splitlines()
        assert len(lines) == 1
        assert json.loads(lines[0])["request_id"] == "rid-2"
    finally:
        tracing.initialize_span_logger(None)


def test_span_disabled_is_free():
    tracing.initialize_span_logger(None)
    assert tracing.start_span("x", "m", "/p") is None
    assert tracing.get_span_logger() is None


def test_router_emits_spans_through_proxy(tmp_path):
    """End-to-end: fake engine + router with span logging enabled ->
    one span line per request with a ttft and the chosen backend."""
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.parser import parse_args
    from production_stack_tpu.testing.fake_engine import (
        build_fake_engine,
    )

    path = str(tmp_path / "spans.jsonl")

    async def run():
        fake = TestServer(
            build_fake_engine(model="m1", speed=1000, ttft=0.0))
        await fake.start_server()
        try:
            args = parse_args([
                "--service-discovery", "static",
                "--static-backends",
                f"http://127.0.0.1:{fake.port}",
                "--static-models", "m1",
                "--routing-logic", "roundrobin",
                "--request-span-log", path,
            ])
            client = TestClient(TestServer(build_app(args)))
            await client.start_server()
            try:
                resp = await client.post(
                    "/v1/chat/completions",
                    json={"model": "m1",
                          "messages": [{"role": "user", "content": "x"}],
                          "max_tokens": 4},
                )
                assert resp.status == 200
                await resp.read()
            finally:
                await client.close()
        finally:
            await fake.close()

    try:
        asyncio.run(run())
        lines = open(path).read().splitlines()
        assert len(lines) == 1
        data = json.loads(lines[0])
        assert data["model"] == "m1"
        assert data["status"] == "ok"
        assert data["backend"].startswith("http://127.0.0.1:")
        assert data["chunks"] >= 1
    finally:
        from production_stack_tpu.router.tracing import (
            initialize_span_logger,
        )
        initialize_span_logger(None)


# ---- engine-side spans (engine/tracing.py) -----------------------------


def test_engine_span_schema_roundtrip(tmp_path):
    import time as _time

    from production_stack_tpu.engine.tracing import (
        SPAN_EVENTS, EngineTracer,
    )

    path = str(tmp_path / "engine-spans.jsonl")
    tracer = EngineTracer(span_log_path=path, ring_size=4,
                          role="prefill")
    t0 = _time.time()
    tracer.start("seq-1", request_id="rid-9", prompt_tokens=7)
    tracer.event("seq-1", "prefill_chunk", start=0, tokens=7, last=True)
    tracer.event("seq-1", "first_token", token=3)
    tracer.finish("seq-1", reason="stop", arrival_ts=t0,
                  first_scheduled_ts=t0 + 0.001,
                  first_token_ts=t0 + 0.002, finish_ts=t0 + 0.003,
                  prompt_tokens=7, output_tokens=4)

    lines = open(path).read().splitlines()
    assert len(lines) == 1
    data = json.loads(lines[0])
    assert data["span"] == "engine_request"
    assert data["request_id"] == "rid-9"
    assert data["seq_id"] == "seq-1"
    assert data["role"] == "prefill"
    assert [e["event"] for e in data["events"]] == [
        "enqueue", "prefill_chunk", "first_token", "finish"]
    assert all(e["event"] in SPAN_EVENTS for e in data["events"])
    assert data["finish_reason"] == "stop"
    assert data["queue_ms"] == 1.0
    assert data["ttft_ms"] == 2.0
    assert data["decode_ms"] == 1.0
    assert data["latency_ms"] == 3.0

    # Lookup by router id or engine seq id; unknown ids miss.
    assert tracer.lookup("rid-9")["spans"][0]["seq_id"] == "seq-1"
    assert tracer.lookup("seq-1") is not None
    assert tracer.lookup("nope") is None
    # finish is idempotent: the abort/drain race emits one line.
    tracer.finish("seq-1", reason="abort")
    assert len(open(path).read().splitlines()) == 1


def _tiny_engine():
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, SchedulerConfig, tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine

    return LLMEngine(EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32),
    ))


def _greedy_run(engine, request_id=None):
    from production_stack_tpu.engine.sequence import SamplingParams

    sid = engine.add_request(
        [5, 6, 7] * 15, SamplingParams(temperature=0.0, max_tokens=6,
                                       ignore_eos=True),
        request_id=request_id)
    seq = engine.sequences[sid]
    for _ in range(200):
        engine.step()
        if not engine.has_work():
            break
    assert not engine.has_work()
    return list(seq.output_token_ids)


def test_engine_tracer_default_none_and_output_identical():
    """The overhead guard: a library-constructed engine has no tracer,
    and installing one changes nothing about what gets generated."""
    from production_stack_tpu.engine.tracing import EngineTracer

    plain = _tiny_engine()
    assert plain.tracer is None
    baseline = _greedy_run(plain)

    traced = _tiny_engine()
    traced.tracer = EngineTracer(ring_size=8)
    assert traced.scheduler.tracer is traced.tracer
    tokens = _greedy_run(traced, request_id="rid-trace")
    assert tokens == baseline

    found = traced.tracer.lookup("rid-trace")
    assert found is not None
    events = [e["event"] for e in found["spans"][0]["events"]]
    assert events[0] == "enqueue"
    assert "prefill_chunk" in events
    assert "first_token" in events
    assert events[-1] == "finish"
    assert events.index("prefill_chunk") < events.index("first_token")
    # 45-token prompt with chunk 32 -> two prefill chunks.
    assert events.count("prefill_chunk") == 2
    summary = found["spans"][0]
    assert summary["finish_reason"] == "length"
    assert summary["output_tokens"] == 6
    for key in ("queue_ms", "ttft_ms", "decode_ms", "latency_ms"):
        assert summary[key] is not None and summary[key] >= 0

    # The step flight recorder saw both prefill and decode steps.
    steps = traced.tracer.recent_steps()
    kinds = {s.get("kind") for s in steps}
    assert "prefill" in kinds
    assert "decode" in kinds
    for s in steps:
        assert s["host_ms"] >= 0
        assert "row_bucket" in s


def test_engine_debug_endpoints():
    from production_stack_tpu.engine.server import EngineServer
    from production_stack_tpu.engine.tracing import EngineTracer

    engine = _tiny_engine()
    engine.tracer = EngineTracer(ring_size=8)
    engine.tracer.start("seq-dbg", request_id="rid-dbg",
                        prompt_tokens=3)
    engine.tracer.on_step(host_ms=1.0, kind="decode")
    server = EngineServer(engine, "tiny-llama")

    async def run():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            resp = await client.get("/debug/trace/rid-dbg")
            assert resp.status == 200
            data = await resp.json()
            assert data["spans"][0]["seq_id"] == "seq-dbg"

            resp = await client.get("/debug/trace/seq-dbg")
            assert resp.status == 200

            resp = await client.get("/debug/trace/unknown-id")
            assert resp.status == 404

            resp = await client.get("/debug/steps?limit=5")
            assert resp.status == 200
            steps = (await resp.json())["steps"]
            assert steps and steps[-1]["kind"] == "decode"

            resp = await client.get("/debug/steps?limit=bogus")
            assert resp.status == 400

            engine.tracer = None
            resp = await client.get("/debug/trace/rid-dbg")
            assert resp.status == 404
            resp = await client.get("/debug/steps")
            assert resp.status == 404
        finally:
            await client.close()

    asyncio.run(run())


def test_fake_engine_spans_and_trace_endpoint(tmp_path):
    """The fake engine mirrors the real server's tracing surface:
    x-request-id echo, engine-span lines, /debug/trace/{id}."""
    from production_stack_tpu.testing.fake_engine import (
        build_fake_engine,
    )

    path = str(tmp_path / "fake-spans.jsonl")

    async def run():
        client = TestClient(TestServer(build_fake_engine(
            model="m1", speed=1000, ttft=0.0, span_log=path)))
        await client.start_server()
        try:
            resp = await client.post(
                "/v1/chat/completions",
                json={"model": "m1",
                      "messages": [{"role": "user", "content": "x"}],
                      "max_tokens": 3},
                headers={"x-request-id": "rid-fake"})
            assert resp.status == 200
            assert resp.headers.get("x-request-id") == "rid-fake"
            await resp.read()

            resp = await client.get("/debug/trace/rid-fake")
            assert resp.status == 200
            data = await resp.json()
            events = [e["event"] for e in data["spans"][0]["events"]]
            assert events == ["enqueue", "prefill_chunk",
                              "first_token", "finish"]
        finally:
            await client.close()

    asyncio.run(run())
    lines = [json.loads(ln) for ln in open(path).read().splitlines()]
    assert len(lines) == 1
    assert lines[0]["span"] == "engine_request"
    assert lines[0]["request_id"] == "rid-fake"


def test_engine_profiler_endpoints(tmp_path):
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, SchedulerConfig, tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.server import EngineServer

    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=32),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=64,
                                  prefill_chunk_size=32),
    )
    server = EngineServer(LLMEngine(config), "tiny-llama")

    async def run():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            trace_dir = str(tmp_path / "trace")
            resp = await client.post(
                f"/debug/profiler/start?dir={trace_dir}")
            assert resp.status == 200
            # Double-start conflicts.
            resp = await client.post(
                f"/debug/profiler/start?dir={trace_dir}")
            assert resp.status == 409
            resp = await client.post("/debug/profiler/stop")
            assert resp.status == 200
            resp = await client.post("/debug/profiler/stop")
            assert resp.status == 409
        finally:
            await client.close()

    asyncio.run(run())
