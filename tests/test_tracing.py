"""Request-span tracing (SURVEY.md §5 aux-parity: structured spans for
the router lifecycle) and the engine's JAX profiler hook."""

import asyncio
import json

from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.router import tracing


def test_span_json_fields():
    span = tracing.RequestSpan("rid-1", "m", "/v1/chat/completions")
    span.on_routed("http://e:8000")
    span.on_chunk()
    span.on_chunk()
    span.finish("ok")
    data = json.loads(span.to_json())
    assert data["span"] == "request"
    assert data["request_id"] == "rid-1"
    assert data["backend"] == "http://e:8000"
    assert data["chunks"] == 2
    assert data["status"] == "ok"
    assert data["queue_delay_ms"] is not None
    assert data["ttft_ms"] >= 0
    assert data["latency_ms"] >= data["ttft_ms"]


def test_span_logger_file_sink(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    tracing.initialize_span_logger(path)
    try:
        span = tracing.start_span("rid-2", "m", "/v1/completions")
        assert span is not None
        span.finish()
        tracing.get_span_logger().emit(span)
        lines = open(path).read().splitlines()
        assert len(lines) == 1
        assert json.loads(lines[0])["request_id"] == "rid-2"
    finally:
        tracing.initialize_span_logger(None)


def test_span_disabled_is_free():
    tracing.initialize_span_logger(None)
    assert tracing.start_span("x", "m", "/p") is None
    assert tracing.get_span_logger() is None


def test_router_emits_spans_through_proxy(tmp_path):
    """End-to-end: fake engine + router with span logging enabled ->
    one span line per request with a ttft and the chosen backend."""
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.parser import parse_args
    from production_stack_tpu.testing.fake_engine import (
        build_fake_engine,
    )

    path = str(tmp_path / "spans.jsonl")

    async def run():
        fake = TestServer(
            build_fake_engine(model="m1", speed=1000, ttft=0.0))
        await fake.start_server()
        try:
            args = parse_args([
                "--service-discovery", "static",
                "--static-backends",
                f"http://127.0.0.1:{fake.port}",
                "--static-models", "m1",
                "--routing-logic", "roundrobin",
                "--request-span-log", path,
            ])
            client = TestClient(TestServer(build_app(args)))
            await client.start_server()
            try:
                resp = await client.post(
                    "/v1/chat/completions",
                    json={"model": "m1",
                          "messages": [{"role": "user", "content": "x"}],
                          "max_tokens": 4},
                )
                assert resp.status == 200
                await resp.read()
            finally:
                await client.close()
        finally:
            await fake.close()

    try:
        asyncio.run(run())
        lines = open(path).read().splitlines()
        assert len(lines) == 1
        data = json.loads(lines[0])
        assert data["model"] == "m1"
        assert data["status"] == "ok"
        assert data["backend"].startswith("http://127.0.0.1:")
        assert data["chunks"] >= 1
    finally:
        from production_stack_tpu.router.tracing import (
            initialize_span_logger,
        )
        initialize_span_logger(None)


def test_engine_profiler_endpoints(tmp_path):
    from production_stack_tpu.engine.config import (
        CacheConfig, EngineConfig, SchedulerConfig, tiny_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.server import EngineServer

    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=32),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=64,
                                  prefill_chunk_size=32),
    )
    server = EngineServer(LLMEngine(config), "tiny-llama")

    async def run():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            trace_dir = str(tmp_path / "trace")
            resp = await client.post(
                f"/debug/profiler/start?dir={trace_dir}")
            assert resp.status == 200
            # Double-start conflicts.
            resp = await client.post(
                f"/debug/profiler/start?dir={trace_dir}")
            assert resp.status == 409
            resp = await client.post("/debug/profiler/stop")
            assert resp.status == 200
            resp = await client.post("/debug/profiler/stop")
            assert resp.status == 409
        finally:
            await client.close()

    asyncio.run(run())
