"""BASELINE config 4 (Llama-3-70B tensor-parallel across a v5e-8
slice via ICI): the serving programs must LOWER with the intended
GSPMD shardings at the real 70B geometry.

A 70B checkpoint (140 GB bf16) cannot execute in CI or on the 16 GB
dev chip, but sharding validity is a compile-time property: this test
traces and lowers the engine's forward at full 70B shapes on the
8-device CPU mesh using jax.ShapeDtypeStruct inputs — no weight
materialization, no execution. What it proves: the head geometry
divides (nh=64, nkv=8 over tp=8 -> 8 q / 1 kv head per device), the
param/cache PartitionSpecs (parallel/mesh.py) are consistent at this
scale, and both the prefill-chunk and decode-step programs lower.
Reference workload: /root/reference helm values modelSpec with
tensorParallelSize (deployment-vllm-multi.yaml argv rendering).
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from production_stack_tpu.engine.config import ModelConfig


def llama3_70b_config() -> ModelConfig:
    return ModelConfig(
        name="llama-3-70b-class",
        architecture="llama",
        vocab_size=128256,
        hidden_size=8192,
        intermediate_size=28672,
        num_hidden_layers=80,
        num_attention_heads=64,
        num_key_value_heads=8,
        head_dim=128,
        max_position_embeddings=8192,
        dtype="bfloat16",
    )


@pytest.mark.slow
def test_70b_tp8_serving_programs_lower():
    from production_stack_tpu.models import llama
    from production_stack_tpu.parallel.mesh import (
        build_mesh,
        cache_spec,
        param_specs,
    )

    m = llama3_70b_config()
    mesh = build_mesh(tensor_parallel_size=8)
    specs = param_specs(m)
    # Guard against silent replicated fallback: the spec table must
    # actually cover the model's params with tp-sharded entries.
    init_shapes_names = set(jax.eval_shape(
        lambda key: llama.init_params(m, key),
        jax.random.PRNGKey(0)).keys())
    tp_specced = {k for k in init_shapes_names
                  if "tp" in tuple(specs.get(k, P()))}
    assert len(tp_specced) >= 5, (
        f"param_specs covers only {sorted(tp_specced)} with tp")

    # Abstract weights with their serving shardings (no allocation).
    init_shapes = jax.eval_shape(
        lambda key: llama.init_params(m, key), jax.random.PRNGKey(0))
    params = {
        k: jax.ShapeDtypeStruct(
            v.shape, v.dtype,
            sharding=NamedSharding(mesh, specs.get(k, P())))
        for k, v in init_shapes.items()
    }

    kv, d, ps, pages = m.num_key_value_heads, m.head_dim, 128, 64
    c_sharding = NamedSharding(mesh, cache_spec(mesh))
    cache = jax.ShapeDtypeStruct(
        (m.num_hidden_layers, kv, pages, d, ps), jnp.bfloat16,
        sharding=c_sharding)

    b, t_prefill, max_pages = 4, 512, 8
    repl = NamedSharding(mesh, P())

    def arg(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=repl)

    def run(tok_shape):
        bb, tt = tok_shape
        lowered = jax.jit(llama.forward, static_argnums=(1,)).lower(
            params, m,
            arg((bb, tt), jnp.int32),      # tokens
            arg((bb, tt), jnp.int32),      # positions
            arg((bb, max_pages), jnp.int32),  # page table
            arg((bb,), jnp.int32),         # kv_lens
            arg((bb, tt), jnp.bool_),      # valid
            cache, cache,
        )
        text = lowered.as_text()
        # A replicated fallback (e.g. a param-name drift making every
        # specs.get() miss) would still contain the word "sharding" —
        # require a non-replicated tp annotation in the module, in
        # either representation (Shardy '{"tp"}' / GSPMD 'devices=[').
        assert '{"tp"}' in text or "devices=[" in text, (
            "no non-replicated sharding annotation in lowered 70B "
            "program")
        return lowered

    # Prefill chunk and decode step both lower at 70B scale.
    run((b, t_prefill))
    run((b, 1))


@pytest.mark.slow
def test_70b_head_geometry_divides():
    m = llama3_70b_config()
    for tp in (2, 4, 8):
        assert m.num_attention_heads % tp == 0
        assert m.num_key_value_heads % tp == 0
