"""Static check: no unbounded network waits under the router tree.

Every outbound network call in ``production_stack_tpu/router/`` must
carry an explicit timeout — the resilience layer's bounded-wait
guarantee (docs/resilience.md) regresses silently otherwise. Flags:

- ``requests.<verb>(...)`` without a ``timeout=`` keyword,
- ``aiohttp.ClientSession(...)`` / ``ClientSession(...)`` constructors
  without a ``timeout=`` keyword (session default),
- ``<anything named *session*>.<verb>(...)`` without ``timeout=``.

A call that is intentionally unbounded can carry a
``# lint: allow-no-timeout`` comment on the call line, which must be
rare and justified in review.
"""

import ast
import pathlib

ROOT = pathlib.Path(__file__).resolve().parent.parent
ROUTER_DIR = ROOT / "production_stack_tpu" / "router"

_HTTP_VERBS = {"get", "post", "put", "patch", "delete", "head", "request"}
_WAIVER = "lint: allow-no-timeout"


def _has_timeout_kw(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords) or any(
        kw.arg is None for kw in call.keywords  # **kwargs: trust it
    )


def _tail_name(node: ast.AST) -> str:
    """Rightmost identifier of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_network_call(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Name):
        return func.id == "ClientSession"
    if not isinstance(func, ast.Attribute):
        return False
    recv = _tail_name(func.value)
    if recv == "requests" and func.attr in _HTTP_VERBS:
        return True
    if recv == "aiohttp" and func.attr == "ClientSession":
        return True
    if "session" in recv.lower() and func.attr in _HTTP_VERBS:
        return True
    return False


def test_router_network_calls_have_explicit_timeouts():
    violations = []
    for path in sorted(ROUTER_DIR.rglob("*.py")):
        source = path.read_text()
        lines = source.splitlines()
        tree = ast.parse(source, filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not _is_network_call(node):
                continue
            if _has_timeout_kw(node):
                continue
            line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if _WAIVER in line:
                continue
            violations.append(
                f"{path.relative_to(ROOT)}:{node.lineno}: "
                f"network call without explicit timeout: {line.strip()}"
            )
    assert not violations, (
        "Unbounded network calls under production_stack_tpu/router/ "
        "(add an explicit timeout=, or a '# lint: allow-no-timeout' "
        "waiver with justification):\n" + "\n".join(violations)
    )


def test_lint_catches_a_violation(tmp_path):
    """The checker itself must actually flag an offending call."""
    snippet = "import requests\nrequests.get('http://x')\n"
    tree = ast.parse(snippet)
    calls = [n for n in ast.walk(tree) if isinstance(n, ast.Call)]
    assert len(calls) == 1
    assert _is_network_call(calls[0])
    assert not _has_timeout_kw(calls[0])
    ok = ast.parse("import requests\nrequests.get('http://x', timeout=5)\n")
    call = next(n for n in ast.walk(ok) if isinstance(n, ast.Call))
    assert _has_timeout_kw(call)
