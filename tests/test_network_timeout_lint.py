"""Static check: no unbounded network waits under the router tree.

Every outbound network call in ``production_stack_tpu/router/`` must
carry an explicit timeout — the resilience layer's bounded-wait
guarantee (docs/resilience.md) regresses silently otherwise.

Since PR 5 this is a thin wrapper over the staticcheck ``no-timeout``
rule (production_stack_tpu/staticcheck/analyzers/network_timeout.py);
the AST walker that used to live here IS the rule now. Test names are
kept so history stays comparable. Waivers: ``# lint: allow-no-timeout``
on the call line, rare and justified in review.
"""

import pathlib

from production_stack_tpu.staticcheck import Project, run_rules

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _findings(project):
    return [f for f in run_rules(project, rules=["no-timeout"])
            if f.rule == "no-timeout"]


def test_router_network_calls_have_explicit_timeouts():
    findings = _findings(Project.from_root(ROOT))
    assert not findings, (
        "Unbounded network calls under production_stack_tpu/router/ "
        "(add an explicit timeout=, or a '# lint: allow-no-timeout' "
        "waiver with justification):\n"
        + "\n".join(f.render() for f in findings)
    )


def test_lint_catches_a_violation():
    """The checker itself must actually flag an offending call."""
    findings = _findings(Project.from_sources({
        "production_stack_tpu/router/planted.py":
            "import requests\n"
            "requests.get('http://x')\n",
    }))
    assert len(findings) == 1
    assert findings[0].line == 2
    # And the bounded version passes.
    assert not _findings(Project.from_sources({
        "production_stack_tpu/router/planted.py":
            "import requests\n"
            "requests.get('http://x', timeout=5)\n",
    }))
