"""Topology-aware mesh planning (docs/parallelism.md): slice
discovery on the forced CPU harness, MeshPlan placement validation
(slice-as-replica, ICI-straddle rejection), the loud unknown-axis
error in param spec resolution, and the multihost step bridge over
the in-process fake transport (follower step ordering, per-slice
liveness, dead-follower detection).

Runs on the virtual 8-device CPU mesh (tests/conftest.py)."""

import threading
import time

import numpy as np
import pytest

import jax

from production_stack_tpu.parallel.topology import (
    AXIS_ORDER,
    DEFAULT_PLACEMENT,
    MeshPlan,
    discover_topology,
    parse_placement,
)


# ---- discovery ---------------------------------------------------------


def test_forced_slices_partition_evenly():
    topo = discover_topology(num_slices=2)
    assert topo.source == "forced"
    assert topo.num_slices == 2
    assert topo.slice_size == 4
    assert topo.devices == tuple(jax.devices()[:8])
    # Slice-major: first half of the device order is slice 0.
    assert topo.slice_of(jax.devices()[0]) == 0
    assert topo.slice_of(jax.devices()[7]) == 1


def test_forced_slices_env_var(monkeypatch):
    monkeypatch.setenv("PSTPU_NUM_SLICES", "4")
    topo = discover_topology()
    assert (topo.source, topo.num_slices) == ("forced", 4)


def test_forced_slices_must_divide():
    with pytest.raises(ValueError, match="evenly divide"):
        discover_topology(num_slices=3)


def test_flat_topology_is_one_slice():
    topo = discover_topology()
    assert topo.source == "flat"
    assert topo.num_slices == 1
    assert topo.slice_size == len(jax.devices())


# ---- placement parsing -------------------------------------------------


def test_parse_placement_auto_and_overrides():
    assert parse_placement("auto") == DEFAULT_PLACEMENT
    assert parse_placement("")["tp"] == "ici"
    got = parse_placement("pp=ici, dp=any")
    assert got["pp"] == "ici" and got["tp"] == "ici"
    with pytest.raises(ValueError, match="axis 'ep' unknown"):
        parse_placement("ep=ici")
    with pytest.raises(ValueError, match="must be 'ici' or 'any'"):
        parse_placement("tp=dcn")


# ---- MeshPlan validation + build ---------------------------------------


def test_plan_rejects_tp_straddling_a_slice():
    """The tentpole rule: tp confined to one ICI domain. tp=8 over
    two 4-wide slices is rejected at config time, not discovered as a
    slow DCN collective at step time."""
    topo = discover_topology(num_slices=2)
    with pytest.raises(ValueError, match="straddle a slice boundary"):
        MeshPlan(tp=8).validate(topo)
    # Same size placed 'any' is allowed (operator opted into DCN).
    MeshPlan(tp=8, placement={**DEFAULT_PLACEMENT,
                              "tp": "any"}).validate(topo)


def test_slice_as_replica_build():
    """dp == num_slices + slice-major devices => each dp replica is
    exactly one slice's device set."""
    topo = discover_topology(num_slices=2)
    mesh = MeshPlan(dp=2, tp=4).build(topo)
    assert mesh.axis_names == AXIS_ORDER
    assert mesh.devices.shape == (2, 1, 1, 4)
    for replica in range(2):
        replica_devices = set(mesh.devices[replica].flatten().tolist())
        assert replica_devices == set(topo.slices[replica])


def test_plan_rejects_oversubscription_and_bad_axes():
    topo = discover_topology(num_slices=2)
    with pytest.raises(ValueError, match="needs 16 devices"):
        MeshPlan(dp=2, tp=8, placement={
            **DEFAULT_PLACEMENT, "tp": "any"}).validate(topo)
    with pytest.raises(ValueError, match="must be >= 1"):
        MeshPlan(tp=0)
    with pytest.raises(ValueError, match="placement axis"):
        MeshPlan(placement={"ep": "ici"})


def test_build_mesh_delegates_to_plan():
    """The legacy flat entrypoint now validates topology: a tp size
    that straddles forced slices raises through build_mesh too."""
    from production_stack_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(tensor_parallel_size=2, num_slices=2)
    assert mesh.shape["tp"] == 2
    with pytest.raises(ValueError, match="straddle"):
        build_mesh(tensor_parallel_size=8, num_slices=2)


def test_parallel_config_validates_topology_fields():
    from production_stack_tpu.engine.config import ParallelConfig

    ParallelConfig(num_slices=2, mesh_placement="tp=ici")
    with pytest.raises(ValueError, match="num_slices"):
        ParallelConfig(num_slices=-1)
    with pytest.raises(ValueError, match="mesh_placement"):
        ParallelConfig(mesh_placement="bogus=ici")


# ---- unknown-axis regression (satellite fix) ---------------------------


def test_on_mesh_unknown_axis_is_loud():
    """_on_mesh used to silently replicate specs naming a misspelled
    axis; now it is a ValueError naming the axis."""
    from jax.sharding import Mesh, PartitionSpec as P

    from production_stack_tpu.parallel.mesh import _on_mesh

    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2),
                axis_names=("tp",))
    with pytest.raises(ValueError, match="'tpu' is neither"):
        _on_mesh(P(None, "tpu"), mesh)
    # Known axes absent from a subset mesh still degrade to
    # replication (legal: an ('sp',)-only mesh sees 'tp' specs).
    assert _on_mesh(P(None, "sp"), mesh) == P(None, None)
    assert _on_mesh(P(None, "tp"), mesh) == P(None, "tp")


# ---- multihost bridge over the fake transport --------------------------


class _StubRunner:
    """Just enough runner surface for _payload_template +
    execute_payload recording."""

    prefill_width = 2
    decode_width = 2
    max_pages_per_seq = 4
    unified_rows = 4
    unified_span = 4
    lora_registry = None

    def __init__(self):
        self.executed = []

    def execute_payload(self, kind, payload, t):
        self.executed.append((kind, t, payload))


def _bridge_pair(num_slices=2, timeout_s=10.0):
    from production_stack_tpu.parallel.distributed import (
        FakeTransport,
        MultihostStepBridge,
    )

    transport = FakeTransport(2)
    leader = MultihostStepBridge(
        _StubRunner(), endpoint=transport.endpoint(0),
        num_slices=num_slices, liveness_timeout_s=timeout_s)
    follower = MultihostStepBridge(
        _StubRunner(), endpoint=transport.endpoint(1),
        num_slices=num_slices, liveness_timeout_s=timeout_s)
    return leader, follower


def test_follower_mirrors_step_order_and_values():
    from production_stack_tpu.parallel.distributed import (
        KIND_DECODE,
        KIND_PREFILL,
    )

    leader, follower = _bridge_pair()
    worker = threading.Thread(target=follower.worker_loop)
    worker.start()

    prefill = leader._payload_template(KIND_PREFILL, 8)
    prefill["tokens"][:] = 7
    decode = leader._payload_template(KIND_DECODE, 1)
    decode["kv_lens"][:] = 3
    with leader.lock:
        leader.publish(KIND_PREFILL, 8, prefill)
    with leader.lock:
        leader.publish(KIND_DECODE, 1, decode)
    leader.shutdown()
    worker.join(timeout=30)
    assert not worker.is_alive()

    executed = follower.runner.executed
    assert [(k, t) for k, t, _ in executed] == [(KIND_PREFILL, 8),
                                               (KIND_DECODE, 1)]
    assert (executed[0][2]["tokens"] == 7).all()
    assert (executed[1][2]["kv_lens"] == 3).all()
    # Both slices acked/live: leader heartbeats its own slice on
    # publish, the follower's acks cover slice 1.
    assert leader.check_liveness() == {0: True, 1: True}


def test_follower_rejects_template_drift():
    """A payload whose structure disagrees with what the follower
    derives from the header is a loud error, not silent divergence."""
    from production_stack_tpu.parallel.distributed import (
        FakeTransport,
        _template_mismatch,
    )

    a = {"tokens": np.zeros((2, 8), np.int32)}
    assert _template_mismatch(a, {"tokens": np.zeros((2, 8),
                                                     np.int32)}) is None
    assert "shape" in _template_mismatch(
        a, {"tokens": np.zeros((2, 4), np.int32)})
    assert "key drift" in _template_mismatch(
        a, {"drafts": np.zeros((2, 8), np.int32)})

    transport = FakeTransport(2)
    leader, follower = (transport.endpoint(0), transport.endpoint(1))
    leader.broadcast({"tokens": np.zeros((2, 4), np.int32)})
    with pytest.raises(ValueError, match="does not match"):
        follower.broadcast({"tokens": np.zeros((2, 8), np.int32)})


def test_dead_follower_names_one_slice():
    """No follower running: its acks never arrive, so after the
    liveness window exactly its slice reads dead while the leader's
    own slice (heartbeaten at publish) stays live."""
    from production_stack_tpu.parallel.distributed import KIND_DECODE

    leader, _ = _bridge_pair(timeout_s=0.05)
    payload = leader._payload_template(KIND_DECODE, 1)
    with leader.lock:
        leader.publish(KIND_DECODE, 1, payload)
    time.sleep(0.1)
    with leader.lock:
        leader.publish(KIND_DECODE, 1, payload)
    live = leader.check_liveness()
    assert live[0] is True
    assert live[1] is False
    assert leader.liveness.dead_slices() == [1]
