"""Engine-side /v1/embeddings tests (reference surface:
src/vllm_router/routers/main_router.py:54-60 proxies /v1/embeddings to
pooling-capable engine pods; our TPU engine serves it natively)."""

import asyncio

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

import jax

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.embeddings import (
    Embedder,
    parse_embedding_input,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.server import EngineServer
from production_stack_tpu.models import llama


class _FakeTok:
    def encode(self, text):
        return [ord(c) % 250 + 1 for c in text]


def test_parse_embedding_input_forms():
    tok = _FakeTok()
    assert parse_embedding_input("ab", tok) == [[ord("a") % 250 + 1,
                                                 ord("b") % 250 + 1]]
    assert parse_embedding_input(["ab", "c"], tok)[1] == [ord("c") % 250 + 1]
    assert parse_embedding_input([5, 6, 7], tok) == [[5, 6, 7]]
    assert parse_embedding_input([[5, 6], [7]], tok) == [[5, 6], [7]]
    with pytest.raises(ValueError):
        parse_embedding_input(None, tok)
    with pytest.raises(ValueError):
        parse_embedding_input([""], tok)
    assert parse_embedding_input([[1] * 50], tok, max_len=8) == [[1] * 8]


def _embedder(pooling="last"):
    config = tiny_model_config("llama")
    params = llama.init_params(config, jax.random.PRNGKey(0))
    return Embedder(config, params, max_len=128, pooling=pooling,
                    batch_width=4)


def test_embedder_shapes_and_normalization():
    emb = _embedder()
    vecs = emb.embed_batch([[1, 2, 3], list(range(1, 30)), [9]])
    assert vecs.shape == (3, 128)
    np.testing.assert_allclose(
        np.linalg.norm(vecs, axis=-1), 1.0, rtol=1e-5
    )


def test_embedder_padding_invariance():
    """Same input must embed identically alone and inside a batch of
    longer inputs (padding/bucketing must not leak)."""
    emb = _embedder(pooling="mean")
    alone = emb.embed_batch([[4, 5, 6]])[0]
    batched = emb.embed_batch([[4, 5, 6], list(range(1, 60))])[0]
    np.testing.assert_allclose(alone, batched, atol=1e-5)


def test_embedder_distinguishes_inputs():
    emb = _embedder()
    vecs = emb.embed_batch([[1, 2, 3], [4, 5, 6]])
    assert np.abs(vecs[0] - vecs[1]).max() > 1e-3


def test_server_embeddings_endpoint():
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=64),
        scheduler=SchedulerConfig(max_num_seqs=2, max_model_len=128,
                                  prefill_chunk_size=32),
    )
    server = EngineServer(LLMEngine(config), "tiny-llama")

    async def run():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            resp = await client.post("/v1/embeddings", json={
                "model": "tiny-llama", "input": ["hello", "world"],
            })
            assert resp.status == 200
            data = await resp.json()
            assert data["object"] == "list"
            assert len(data["data"]) == 2
            assert data["data"][1]["index"] == 1
            assert len(data["data"][0]["embedding"]) == 128
            expected = sum(
                len(server.tokenizer.encode(s))
                for s in ("hello", "world")
            )
            assert data["usage"]["prompt_tokens"] == expected

            resp = await client.post("/v1/embeddings", json={
                "model": "tiny-llama", "input": [],
            })
            assert resp.status in (200, 400)

            resp = await client.post("/v1/embeddings", json={
                "model": "tiny-llama", "input": 42,
            })
            assert resp.status == 400
        finally:
            await client.close()

    asyncio.run(run())
