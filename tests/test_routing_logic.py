"""Routing policy behavior with stub endpoints/stats (test model:
reference src/tests/test_session_router.py stub-object pattern)."""

import asyncio

import pytest

from production_stack_tpu.router.routing.logic import (
    HeadRoomAdmissionPolicy,
    LeastLoadedPolicy,
    RoundRobinPolicy,
    SessionPolicy,
    WorkEstimatePolicy,
    get_routing_logic,
    initialize_routing_logic,
    reconfigure_routing_logic,
)
from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.router.stats.request_stats import (
    BLOCK_SIZE,
    SAFETY_FRACTION,
    TOTAL_NUMBER_OF_BLOCKS,
    RequestStats,
    initialize_request_stats_monitor,
)

EPS = [EndpointInfo(url=f"http://e{i}:8000") for i in range(3)]


@pytest.fixture(autouse=True)
def stats_monitor():
    return initialize_request_stats_monitor(60.0)


def test_round_robin_cycles_sorted():
    policy = initialize_routing_logic("roundrobin")
    urls = [
        policy.route_request(EPS, {}, {}, {}, f"r{i}", 0) for i in range(6)
    ]
    expected = sorted(ep.url for ep in EPS)
    assert urls == expected + expected


def test_session_policy_sticky_and_fallback():
    policy = initialize_routing_logic("session", session_key="x-user-id")
    h = {"x-user-id": "alice"}
    first = policy.route_request(EPS, {}, {}, h, "r1", 0)
    for i in range(5):
        assert policy.route_request(EPS, {}, {}, h, f"r{i+2}", 0) == first

    # No session header: lowest QPS wins.
    stats = {
        "http://e0:8000": RequestStats(qps=5.0),
        "http://e1:8000": RequestStats(qps=0.5),
        "http://e2:8000": RequestStats(qps=2.0),
    }
    assert policy.route_request(EPS, {}, stats, {}, "r9", 0) == \
        "http://e1:8000"


def test_session_policy_requires_key():
    with pytest.raises(ValueError):
        initialize_routing_logic("session")


def test_llq_picks_least_inflight():
    policy = initialize_routing_logic("llq")
    stats = {
        "http://e0:8000": RequestStats(
            in_prefill_requests=3, in_decoding_requests=4),
        "http://e1:8000": RequestStats(
            in_prefill_requests=0, in_decoding_requests=2),
        "http://e2:8000": RequestStats(
            in_prefill_requests=5, in_decoding_requests=0),
    }
    assert policy.route_request(EPS, {}, stats, {}, "r1", 0) == \
        "http://e1:8000"


def test_custom_work_estimate():
    policy = initialize_routing_logic("custom")
    stats = {
        # 2 queued prefills * 2s + decode ages -> busy
        "http://e0:8000": RequestStats(
            avg_decoding_length=2.0,
            ts_prefill_enqueue=[0.1, 0.2],
            ts_decoding_enqueue=[3.0],
        ),
        # idle
        "http://e1:8000": RequestStats(
            avg_decoding_length=2.0,
            ts_prefill_enqueue=[],
            ts_decoding_enqueue=[],
        ),
    }
    eps = EPS[:2]
    assert policy.route_request(eps, {}, stats, {}, "r1", 0) == \
        "http://e1:8000"


async def _route_hra(policy, eps, rid, tokens):
    result = policy.route_request(eps, {}, {}, {}, rid, tokens)
    if hasattr(result, "__await__"):
        return await asyncio.wait_for(result, timeout=2.0)
    return result


def test_hra_admits_when_capacity_available():
    async def run():
        policy = initialize_routing_logic("hra")
        url = await _route_hra(policy, EPS[:1], "r1", 64)
        assert url == EPS[0].url
    asyncio.run(run())


def test_hra_queues_oversized_then_admits_on_completion():
    async def run():
        monitor = initialize_request_stats_monitor(60.0)
        policy = initialize_routing_logic("hra")
        ep = EPS[:1]
        # Fill the engine close to budget with one huge admitted request.
        huge_tokens = int(
            TOTAL_NUMBER_OF_BLOCKS * (1 - SAFETY_FRACTION) * BLOCK_SIZE
            / 1.25
        ) - BLOCK_SIZE
        monitor.on_request_arrival("big", 0.0)
        url = await _route_hra(policy, ep, "big", huge_tokens)
        assert url == ep[0].url

        # Second request cannot fit while 'big' holds reservations.
        fut = policy.route_request(ep, {}, {}, {}, "small", 512)
        assert hasattr(fut, "__await__")
        await asyncio.sleep(0)
        assert not fut.done()

        # Completing 'big' releases blocks; 'small' gets admitted.
        monitor.on_request_response(ep[0].url, "big", 1.0,
                                    is_first_token=True)
        monitor.on_request_complete(ep[0].url, "big", 2.0)
        policy.on_request_complete(ep[0].url)
        assert await asyncio.wait_for(fut, timeout=2.0) == ep[0].url
    asyncio.run(run())


def test_hra_sjf_ordering():
    async def run():
        monitor = initialize_request_stats_monitor(60.0)
        policy = initialize_routing_logic("hra")
        ep = EPS[:1]
        huge_tokens = int(
            TOTAL_NUMBER_OF_BLOCKS * (1 - SAFETY_FRACTION) * BLOCK_SIZE
            / 1.25
        ) - BLOCK_SIZE
        monitor.on_request_arrival("big", 0.0)
        await _route_hra(policy, ep, "big", huge_tokens)

        admitted = []
        futs = {}
        for rid, tokens in (("long", 2048), ("short", 128)):
            fut = policy.route_request(ep, {}, {}, {}, rid, tokens)
            fut.add_done_callback(
                lambda f, rid=rid: admitted.append(rid))
            futs[rid] = fut
        # Release capacity: shortest job should be admitted first.
        monitor.on_request_response(ep[0].url, "big", 1.0,
                                    is_first_token=True)
        monitor.on_request_complete(ep[0].url, "big", 2.0)
        policy.on_request_complete(ep[0].url)
        await asyncio.gather(*futs.values())
        await asyncio.sleep(0)  # flush done-callbacks
        assert admitted[0] == "short"
    asyncio.run(run())


def test_initialize_and_get_and_reconfigure():
    with pytest.raises(ValueError):
        get_routing_logic()
    p1 = initialize_routing_logic("roundrobin")
    assert get_routing_logic() is p1
    p2 = reconfigure_routing_logic("llq")
    assert isinstance(p2, LeastLoadedPolicy)
    assert get_routing_logic() is p2


def test_hra_rejects_never_fitting_request():
    async def run():
        initialize_request_stats_monitor(60.0)
        policy = initialize_routing_logic("hra")
        impossible_tokens = TOTAL_NUMBER_OF_BLOCKS * BLOCK_SIZE * 2
        fut = policy.route_request(EPS[:1], {}, {}, {}, "r1",
                                   impossible_tokens)
        with pytest.raises(Exception):
            await asyncio.wait_for(fut, timeout=1.0)
        # The queue must not be wedged for subsequent requests.
        assert await _route_hra(policy, EPS[:1], "r2", 64) == EPS[0].url
    asyncio.run(run())


def test_hra_drops_cancelled_waiters_without_reserving():
    async def run():
        monitor = initialize_request_stats_monitor(60.0)
        policy = initialize_routing_logic("hra")
        ep = EPS[:1]
        huge_tokens = int(
            TOTAL_NUMBER_OF_BLOCKS * (1 - SAFETY_FRACTION) * BLOCK_SIZE
            / 1.25
        ) - BLOCK_SIZE
        monitor.on_request_arrival("big", 0.0)
        await _route_hra(policy, ep, "big", huge_tokens)

        fut = policy.route_request(ep, {}, {}, {}, "ghost", 512)
        fut.cancel()

        monitor.on_request_response(ep[0].url, "big", 1.0,
                                    is_first_token=True)
        monitor.on_request_complete(ep[0].url, "big", 2.0)
        policy.on_request_complete(ep[0].url)
        # Ghost must not have reserved anything.
        assert monitor.estimate_pending_reserved_blocks(ep[0].url) == 0
        # And new traffic flows normally.
        monitor.on_request_arrival("r3", 3.0)
        assert await _route_hra(policy, ep, "r3", 64) == ep[0].url
    asyncio.run(run())


def test_hra_churn_hundreds_queued_across_endpoint_events():
    """Heap-based admission under churn: hundreds of queued requests,
    endpoints appearing/disappearing between drains, cancellations in
    the middle of the queue — everything admissible must eventually
    admit in SJF order, and nothing wedges.

    (Round-2 verdict: the O(n^2) re-sort/linear-drain needed a test
    that drives more than a handful of queued admissions.)"""
    async def run():
        monitor = initialize_request_stats_monitor(60.0)
        policy = initialize_routing_logic("hra")
        ep_a, ep_b = EPS[0], EPS[1]

        # Saturate endpoint A so everything below queues.
        huge_tokens = int(
            TOTAL_NUMBER_OF_BLOCKS * (1 - SAFETY_FRACTION) * BLOCK_SIZE
            / 1.25
        ) - BLOCK_SIZE
        monitor.on_request_arrival("blocker", 0.0)
        assert await _route_hra(policy, [ep_a], "blocker",
                                huge_tokens) == ep_a.url

        n = 300
        futs = {}
        for i in range(n):
            # Arrivals in *descending* size so the heap has real work
            # to do; only endpoint A is known at arrival time.
            tokens = 64 * (n - i)
            futs[i] = policy.route_request(
                [ep_a], {}, {}, {}, f"r{i}", tokens)
        await asyncio.sleep(0)
        assert not any(f.done() for f in futs.values())

        # A third of the waiters give up (client disconnects).
        cancelled = set(range(0, n, 3))
        for i in cancelled:
            futs[i].cancel()

        # Endpoint B joins via a fresh arrival that queues behind the
        # existing SJF order (it is the smallest request, so it drains
        # first — proving ordering survived the churn).
        futs["tiny"] = policy.route_request(
            [ep_a, ep_b], {}, {}, {}, "tiny", 1)

        # The blocker completes: the queue drains in SJF order.
        monitor.on_request_response(ep_a.url, "blocker", 1.0,
                                    is_first_token=True)
        monitor.on_request_complete(ep_a.url, "blocker", 2.0)
        policy.on_request_complete(ep_a.url)

        admitted = [
            i for i in futs
            if i not in cancelled and futs[i].done()
            and not futs[i].cancelled()
        ]
        # The tiny request (SJF minimum) must be among the first wave.
        assert "tiny" in admitted
        got_tiny = await asyncio.wait_for(futs["tiny"], 1.0)
        assert got_tiny in (ep_a.url, ep_b.url)

        # Keep completing whatever was admitted until the queue is
        # fully drained; no future may be left hanging.
        for _ in range(2 * n):
            progressed = False
            for i, f in list(futs.items()):
                if i in cancelled or not f.done() or f.cancelled():
                    continue
                url = f.result()
                monitor.on_request_response(url, f"r{i}", 1.0,
                                            is_first_token=True)
                monitor.on_request_complete(url, f"r{i}", 2.0)
                futs.pop(i)
                policy.on_request_complete(url)
                progressed = True
            if not progressed:
                break
        remaining = [i for i, f in futs.items()
                     if i not in cancelled and not f.done()]
        assert remaining == [], f"wedged waiters: {remaining[:5]}"
        # The policy's queue must hold nothing but (possibly) the
        # cancelled husks that were never popped.
        assert all(p.future.done() for p in policy._queue)
    asyncio.run(run())


def test_prefix_aware_sticks_conversations_and_spreads_cold_prompts():
    policy = initialize_routing_logic("prefixaware")
    eps = EPS[:3]
    sys_prompt = "You are a helpful assistant. " * 40  # > 1 block

    # Round 1 of two different users: cold prefixes spread by load.
    u1_r1 = sys_prompt + "user: tell me about TPUs"
    u2_r1 = sys_prompt + "user: write me a haiku"
    first = policy.route_request(eps, {}, {}, {}, "u1r1", 100,
                                 prompt_text=u1_r1)
    # u2 shares the system-prompt blocks -> follows u1's engine (the
    # shared prefix is already cached there).
    second = policy.route_request(eps, {}, {}, {}, "u2r1", 100,
                                  prompt_text=u2_r1)
    assert second == first

    # Round 2 replays round-1 history + the answer: must stick.
    u1_r2 = u1_r1 + " assistant: ... user: more please"
    assert policy.route_request(eps, {}, {}, {}, "u1r2", 150,
                                prompt_text=u1_r2) == first

    # A completely different prompt has no cached prefix anywhere and
    # falls back to least-loaded (any engine is acceptable).
    cold = policy.route_request(
        eps, {}, {}, {}, "cold", 50,
        prompt_text="completely unrelated text " * 30)
    assert cold in {e.url for e in eps}


def test_prefix_aware_drops_index_for_departed_engines():
    policy = reconfigure_routing_logic("prefixaware")
    text = "shared prefix block " * 40
    url = policy.route_request(EPS[:2], {}, {}, {}, "a", 10,
                               prompt_text=text)
    # The engine leaves the pool; the same prefix must not pin to it.
    remaining = [ep for ep in EPS[:2] if ep.url != url]
    got = policy.route_request(remaining, {}, {}, {}, "b", 10,
                               prompt_text=text)
    assert got == remaining[0].url
    assert url not in policy._index


def test_prefix_aware_handles_missing_text():
    policy = reconfigure_routing_logic("prefixaware")
    url = policy.route_request(EPS[:2], {}, {}, {}, "x", 10,
                               prompt_text=None)
    assert url in {EPS[0].url, EPS[1].url}


def test_prefix_aware_spills_hot_prefix_under_load():
    """A shared prefix must not pin the whole fleet to one replica:
    once the preferred engine is overloaded relative to the least
    loaded, the request spills there and the prefix replicates."""
    policy = reconfigure_routing_logic("prefixaware")
    eps = EPS[:2]
    text = "the fleet-wide shared system prompt " * 30

    first = policy.route_request(eps, {}, {}, {}, "warm", 10,
                                 prompt_text=text)
    other = next(ep.url for ep in eps if ep.url != first)

    # Preferred engine now heavily loaded; the other is idle.
    stats = {
        first: RequestStats(
            qps=1.0, ttft=0.1, in_prefill_requests=20,
            in_decoding_requests=20, finished_requests=0,
            uptime=10.0),
        other: RequestStats(
            qps=0.0, ttft=0.1, in_prefill_requests=0,
            in_decoding_requests=0, finished_requests=0,
            uptime=10.0),
    }
    got = policy.route_request(eps, {}, stats, {}, "spill", 10,
                               prompt_text=text)
    assert got == other  # spilled off the hot replica
    # ... and the prefix is now indexed on BOTH engines, so with even
    # load the spill target can win on its own.
    assert policy._score(other, policy._chain(text)) > 0


def test_hra_routes_from_loop_without_default_set():
    """Regression: HRA's admission future must come from
    asyncio.get_running_loop(). The old get_event_loop() call relied
    on a thread-default loop being set — a router worker thread that
    never called set_event_loop would deprecation-warn today and
    break outright under future asyncio semantics."""
    import threading
    import warnings

    result = {}

    def worker():
        # Deliberately no default loop for this thread.
        asyncio.set_event_loop(None)

        async def main():
            policy = initialize_routing_logic("hra")
            with warnings.catch_warnings():
                warnings.simplefilter("error", DeprecationWarning)
                fut = policy.route_request(EPS[:1], {}, {}, {}, "rl", 64)
                assert fut.get_loop() is asyncio.get_running_loop()
                result["url"] = await asyncio.wait_for(fut, 2.0)

        asyncio.run(main())

    thread = threading.Thread(target=worker)
    thread.start()
    thread.join(10)
    assert result.get("url") == EPS[0].url


def test_prefix_chain_identical_across_hash_seeds():
    """Regression: the prefix chain must be a pure function of the
    text. builtin hash() is salted per process, so two router
    replicas (or one router restarted) would score the same prefix
    differently — verified by hashing in fresh interpreters pinned to
    different PYTHONHASHSEED values."""
    import json as _json
    import os
    import subprocess
    import sys

    from production_stack_tpu.router.routing.logic import (
        PrefixAwarePolicy,
    )

    text = "A very long system prompt shared by every request. " * 40
    script = (
        "import json, sys\n"
        "from production_stack_tpu.router.routing.logic import "
        "PrefixAwarePolicy\n"
        "p = PrefixAwarePolicy.__new__(PrefixAwarePolicy)\n"
        "print(json.dumps(p._chain(sys.argv[1])))\n"
    )
    chains = []
    for seed in ("1", "4242"):
        env = dict(os.environ, PYTHONHASHSEED=seed, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-c", script, text], env=env,
            capture_output=True, text=True, timeout=60, check=True,
        )
        chains.append(_json.loads(out.stdout))

    local = PrefixAwarePolicy.__new__(PrefixAwarePolicy)._chain(text)
    assert len(local) > 4  # multiple blocks actually chained
    assert chains[0] == chains[1] == local
