"""Overlapped async execution pipeline (docs/async_pipeline.md):
config gating, byte-exact greedy parity with the synchronous loop
over a mixed prefill/decode/finish run, abort-mid-flight page
accounting, and executable-cache stability when the pipeline turns
on."""

import numpy as np
import pytest

from production_stack_tpu.engine.config import (
    CacheConfig,
    EngineConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.sequence import (
    SamplingParams,
    SequenceState,
)


def _engine(async_on=False, **sched_kw):
    config = EngineConfig(
        model=tiny_model_config("llama"),
        cache=CacheConfig(page_size=16, num_pages=128),
        scheduler=SchedulerConfig(max_num_seqs=4,
                                  max_model_len=256,
                                  prefill_chunk_size=32,
                                  async_scheduling=async_on,
                                  **sched_kw),
    )
    return LLMEngine(config)


def _prompts():
    rs = np.random.RandomState(11)
    return [
        [5, 6, 7] * 12,
        [9, 9, 9, 9, 9, 9, 9, 9],
        [11, 12, 13, 14] * 20,  # 80 tokens > chunk 32
        [int(x) for x in rs.randint(1, 500, size=23)],
    ]


# Varied budgets so rows finish at different steps (each finish
# exercises the plan-ahead masking + reconcile path in async mode).
_MAX_TOKENS = [19, 7, 13, 26]


def _run_mixed(engine):
    """~50-step run: chunked prefills, staggered admission (the 4th
    prompt arrives only after the 2nd finishes — mid-decode, forcing
    an async pipeline break for its prefill), interleaved finishes."""
    prompts = _prompts()
    seqs = []
    for p, m in zip(prompts[:3], _MAX_TOKENS[:3]):
        sid = engine.add_request(p, SamplingParams(
            temperature=0.0, max_tokens=m, ignore_eos=True))
        seqs.append(engine.sequences[sid])
    late_added = False
    for _ in range(500):
        engine.step()
        if (not late_added
                and seqs[1].state == SequenceState.FINISHED):
            sid = engine.add_request(prompts[3], SamplingParams(
                temperature=0.0, max_tokens=_MAX_TOKENS[3],
                ignore_eos=True))
            seqs.append(engine.sequences[sid])
            late_added = True
        if late_added and not engine.has_work():
            break
    assert late_added and not engine.has_work()
    return [list(s.output_token_ids) for s in seqs]


def test_config_gating():
    # async x decode_steps and async x speculative_k are dissolved
    # exclusivity rules (docs/unified_step.md): bursts run as
    # synchronous pipeline breaks, verify steps reconcile through the
    # assume-1 stale-drop path. Both now construct.
    _engine(async_on=True, decode_steps=4)
    _engine(async_on=True, speculative_k=4)
    from production_stack_tpu.engine.model_runner import (
        async_scheduling_eligible,
    )
    assert async_scheduling_eligible(1, 0)
    assert not async_scheduling_eligible(4, 0)
    assert not async_scheduling_eligible(1, 8)
    assert not async_scheduling_eligible(1, 0, distributed=True)


def test_server_auto_resolution():
    from production_stack_tpu.engine.server import (
        _resolve_async_scheduling,
        parse_args,
    )
    assert _resolve_async_scheduling(parse_args([]))
    assert not _resolve_async_scheduling(
        parse_args(["--decode-steps", "4"]))
    assert not _resolve_async_scheduling(
        parse_args(["--speculative-k", "8"]))
    assert not _resolve_async_scheduling(parse_args(["--distributed"]))
    assert not _resolve_async_scheduling(
        parse_args(["--async-scheduling", "off"]))
    # A prefill-role engine has no decode steps to overlap: 'auto'
    # resolves off so the role x async exclusivity rule only fires
    # on an explicit 'on'.
    assert not _resolve_async_scheduling(
        parse_args(["--engine-role", "prefill"]))
    assert _resolve_async_scheduling(
        parse_args(["--engine-role", "decode"]))
    # Explicit 'on' alongside bursts is legal (docs/unified_step.md):
    # burst plans simply run as synchronous pipeline breaks.
    assert _resolve_async_scheduling(
        parse_args(["--async-scheduling", "on", "--decode-steps", "4"]))


def test_greedy_parity_byte_identical_and_no_recompile():
    sync = _engine(async_on=False)
    expected = _run_mixed(sync)
    async_e = _engine(async_on=True)
    got = _run_mixed(async_e)
    assert got == expected
    assert [len(t) for t in got] == _MAX_TOKENS
    # The pipeline actually pipelined: successor steps were dispatched
    # before their predecessor's readback.
    assert async_e.metrics.pipeline_ahead_steps_total > 0
    assert async_e._in_flight is None

    # Executable-cache stability: flipping async on for the SAME
    # runner introduces no new compiled program shapes (dispatch_decode
    # feeds the identical [B, 1] step program). The compile ledger is
    # the public witness: zero new "step" events, same cache size.
    obs = sync.runner.observatory
    before_events = obs.compile_events_total("step")
    before_size = obs.executable_cache_sizes()["step"]
    sync.config.scheduler.async_scheduling = True
    sid = sync.add_request(_prompts()[0], SamplingParams(
        temperature=0.0, max_tokens=8, ignore_eos=True))
    seq = sync.sequences[sid]
    while sync.has_work():
        sync.step()
    assert len(seq.output_token_ids) == 8
    assert obs.compile_events_total("step") == before_events
    assert obs.executable_cache_sizes()["step"] == before_size


def test_abort_mid_flight_no_page_leak():
    engine = _engine(async_on=True)
    free0 = engine.cache_manager.num_free_pages
    seqs = []
    for p in _prompts()[:3]:
        sid = engine.add_request(p, SamplingParams(
            temperature=0.0, max_tokens=24, ignore_eos=True))
        seqs.append(sid)
    # Step until a decode is genuinely in flight, then abort one row
    # while its step (and its plan-ahead successor's pages) is live.
    for _ in range(50):
        engine.step()
        if engine._in_flight is not None:
            break
    assert engine._in_flight is not None
    engine.abort_request(seqs[1])
    while engine.has_work():
        engine.step()
    assert engine._in_flight is None
    assert engine.sequences == {}
    # Every page is back: the aborted row's plan-ahead boundary pages
    # rode seq.pages through the ordinary free path.
    assert engine.cache_manager.num_free_pages == free0


def test_pipeline_metrics_rendered_and_scraped():
    from production_stack_tpu.engine.metrics import EngineMetrics
    m = EngineMetrics()
    m.on_pipeline_step(host_s=0.25, device_wait_s=0.5, ahead=True)
    m.on_device_idle(0.125)
    m.set_inflight_depth(1)
    text = "\n".join(m.render())
    assert "vllm:engine_step_host_seconds_total 0.25" in text
    assert "vllm:engine_step_device_wait_seconds_total 0.5" in text
    assert "vllm:engine_device_idle_seconds_total 0.125" in text
    assert "vllm:engine_pipeline_steps_total 1" in text
    assert "vllm:engine_pipeline_ahead_steps_total 1" in text
    assert "vllm:engine_async_inflight_depth 1" in text
    from production_stack_tpu.router.stats.engine_stats import (
        EngineStats,
    )
    stats = EngineStats.from_prometheus_text(text + "\n")
    assert stats.engine_step_host_seconds == 0.25
    assert stats.engine_step_device_wait_seconds == 0.5
    assert stats.engine_device_idle_seconds == 0.125
    assert stats.engine_pipeline_steps == 1.0
    assert stats.engine_pipeline_ahead_steps == 1.0
    assert stats.engine_async_inflight_depth == 1.0
