"""Cluster SLO ledger, drift sentinel, slow archive and stacktop
(docs/observability.md): burn-rate window arithmetic under a fake
clock, spec resolution, archive ring semantics, the /cluster/status
fold, the stacktop plain render, and traceview's slow-archive replay.
All pure-unit — the live wiring is tested in test_e2e_slo.py.
"""

import json

import pytest

from production_stack_tpu import obs
from production_stack_tpu.obs.cluster_status import build_snapshot
from production_stack_tpu.stacktop import (
    _load_changes,
    render_snapshot,
)
from production_stack_tpu.traceview import (
    load_slow_archive,
    render_waterfall,
)

SPEC = {
    "objective": 0.9,
    "classes": {
        "interactive": {"ttft_s": 0.5, "itl_s": 0.1},
        "batch": {"ttft_s": 5.0, "objective": 0.8},
    },
    "models": {"m-slow": {"ttft_s": 2.0}},
}


def _ledger(clock):
    return obs.SLOLedger(obs.SLOSpec.from_dict(SPEC), clock=clock)


# ---- spec resolution ---------------------------------------------------


def test_spec_rejects_bad_objective():
    with pytest.raises(ValueError):
        obs.SLOSpec.from_dict({"objective": 1.5})
    with pytest.raises(ValueError):
        obs.SLOSpec.from_dict(
            {"classes": {"batch": {"objective": 0.0}}})


def test_spec_model_targets_override_class_targets():
    spec = obs.SLOSpec.from_dict(SPEC)
    target, objective = spec.resolve("interactive", "m-slow")
    # Model-specific ttft wins; class itl survives the merge.
    assert target.ttft_s == 2.0
    assert target.itl_s == 0.1
    assert objective == 0.9
    target, objective = spec.resolve("batch", "other-model")
    assert target.ttft_s == 5.0
    assert objective == 0.8


def test_spec_load_roundtrip(tmp_path):
    path = tmp_path / "slo.json"
    path.write_text(json.dumps(SPEC))
    spec = obs.SLOSpec.load(str(path))
    assert spec.objective == 0.9
    assert set(spec.classes) == {"interactive", "batch"}


# ---- ledger scoring + burn windows -------------------------------------


def test_observe_returns_breach_verdicts():
    t = [0.0]
    ledger = _ledger(lambda: t[0])
    assert ledger.observe("interactive", "m", "http://e1",
                          ttft_s=0.2, itl_s=0.05) == []
    breaches = ledger.observe("interactive", "m", "http://e1",
                              ttft_s=0.9, itl_s=0.3)
    assert {b["metric"] for b in breaches} == {"ttft", "itl"}
    assert breaches[0]["target_s"] in (0.5, 0.1)


def test_burn_rate_window_arithmetic_under_fake_clock():
    t = [0.0]
    ledger = _ledger(lambda: t[0])
    # 1 bad of 10 at t=0: bad_frac 0.1 vs budget 0.1 -> burn 1.0 in
    # both windows.
    for i in range(9):
        ledger.observe("interactive", "m", "e", ttft_s=0.1)
    ledger.observe("interactive", "m", "e", ttft_s=9.0)
    burn = ledger.burn_rates()
    assert burn["5m"] == pytest.approx(1.0)
    assert burn["1h"] == pytest.approx(1.0)

    # 10 minutes later the bad event has aged out of the 5m window
    # but still burns the 1h budget; 10 fresh good events dilute it.
    t[0] = 600.0
    for i in range(10):
        ledger.observe("interactive", "m", "e", ttft_s=0.1)
    burn = ledger.burn_rates()
    assert burn["5m"] == 0.0
    assert burn["1h"] == pytest.approx(0.5)

    # Past the hour everything ages out.
    t[0] = 4300.0
    assert ledger.burn_rates() == {"5m": 0.0, "1h": 0.0}


def test_attainment_is_windowed_and_keyed_by_class_model():
    t = [0.0]
    ledger = _ledger(lambda: t[0])
    ledger.observe("interactive", "m", "e1", ttft_s=0.1)
    ledger.observe("interactive", "m", "e1", ttft_s=3.0)
    ledger.observe("batch", "m", "e2", ttft_s=3.0)  # within batch 5s
    att = ledger.attainments()
    assert att[("interactive", "m")] == pytest.approx(0.5)
    assert att[("batch", "m")] == pytest.approx(1.0)
    totals = ledger.totals()
    assert totals["bad"][("interactive", "m")] == 1
    # Attainment forgets events older than the hour window.
    t[0] = 3700.0
    ledger.observe("interactive", "m", "e1", ttft_s=0.1)
    assert ledger.attainments()[("interactive", "m")] == 1.0


def test_unconstrained_phase_never_breaches():
    t = [0.0]
    ledger = _ledger(lambda: t[0])
    # batch has no itl/e2e target: any value is good.
    assert ledger.observe("batch", "m", "e",
                          itl_s=99.0, e2e_s=1e6) == []


# ---- drift sentinel ----------------------------------------------------


def test_drift_sentinel_band(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps(
        {"band": 0.25, "phases": {"decode": 0.02, "prefill": 0.5}}))
    sentinel = obs.DriftSentinel.load(str(path))
    # decode 0.04 is +100% vs baseline -> tripped; prefill in band.
    verdicts = sentinel.evaluate(
        {"e1": {"decode": 0.04, "prefill": 0.55}})
    assert verdicts["decode"]["tripped"] is True
    assert verdicts["prefill"]["tripped"] is False
    flags = sentinel.flags({"e1": {"decode": 0.04, "prefill": 0.55}})
    assert flags == {"decode": 1.0, "prefill": 0.0}
    # No observation for a phase -> not tripped (absence is not drift).
    assert sentinel.evaluate({})["decode"]["tripped"] is False


def test_drift_sentinel_rejects_degenerate_baseline():
    with pytest.raises(ValueError):
        obs.DriftSentinel({"decode": 0.02}, band=0.0)


# ---- slow archive ------------------------------------------------------


def test_slow_archive_ring_and_filters():
    archive = obs.SlowArchive(2)
    for i, cls in enumerate(["batch", "interactive", "batch"]):
        archive.add({"request_id": f"r{i}", "class": cls, "model": "m"})
    assert archive.depth() == 2
    assert archive.archived_total == 3
    # Newest first; oldest entry evicted by the ring.
    assert [e["request_id"] for e in archive.snapshot()] == ["r2", "r1"]
    assert [e["request_id"]
            for e in archive.snapshot(priority_class="batch")] == ["r2"]
    assert archive.snapshot(model="other") == []
    assert len(archive.snapshot(limit=1)) == 1


# ---- cluster snapshot + stacktop render --------------------------------


class _Stats:
    num_running_requests = 3
    num_queuing_requests = 1
    kv_usage_perc = 0.5
    kv_cache_hit_rate = 0.25
    engine_mfu = 0.12
    step_time_median_by_kind = {"decode": 0.02}


def test_build_snapshot_folds_all_layers():
    t = [0.0]
    ledger = _ledger(lambda: t[0])
    ledger.observe("interactive", "m", "e", ttft_s=9.0)
    archive = obs.SlowArchive(4)
    archive.add({"request_id": "r0", "class": "interactive",
                 "model": "m"})
    sentinel = obs.DriftSentinel({"decode": 0.02}, band=0.25)

    class _Ep:
        url = "http://e1"
        model_names = ["m"]
        role = "decode"

    snap = build_snapshot({"http://e1": _Stats()}, endpoints=[_Ep()],
                          healthy={"http://e1": True}, ledger=ledger,
                          archive=archive, sentinel=sentinel,
                          now=1000.0)
    server = snap["servers"]["http://e1"]
    assert server["running"] == 3
    assert server["role"] == "decode"
    assert server["healthy"] is True
    assert snap["slo"]["bad_requests"] == 1
    assert snap["slow_archive"]["depth"] == 1
    assert snap["perf_drift"]["decode"]["tripped"] is False
    # Optional layers disabled -> keys absent, not null.
    bare = build_snapshot({"http://e1": _Stats()}, now=1000.0)
    assert set(bare) == {"ts", "servers"}


def test_stacktop_plain_render_golden():
    snap = {
        "ts": 0.0,
        "slo": {"objective": 0.9,
                "attainment": {"interactive|m": 0.5},
                "burn_rate": {"5m": 2.0, "1h": 0.25},
                "good_requests": 1, "bad_requests": 1},
        "perf_drift": {"decode": {"baseline_s": 0.02,
                                  "observed_s": 0.04,
                                  "drift": 1.0, "tripped": True}},
        "slow_archive": {"depth": 1, "capacity": 64,
                         "archived_total": 5},
        "servers": {"http://e1": {
            "healthy": True, "role": "decode", "running": 3,
            "waiting": 1, "cache_usage": 0.5, "prefix_hit_rate": 0.25,
            "mfu": 0.12, "qos_shed": {"batch": 2},
            "compile_events": {"decode": 7},
            "mesh": {"shape": {"dp": 1, "pp": 2, "sp": 1, "tp": 2},
                     "slice_id": 0,
                     "slices_live": {"0": True}},
            "autotune": {"active": 2,
                         "frozen": {"qos_shed": False},
                         "knobs": {"qos_shed": 0.95}},
        }},
    }
    out = render_snapshot(snap)
    expected = "\n".join([
        "tpu-stack cluster status @ 1970-01-01 00:00:00",
        "SLO objective=0.9 burn 5m=2.00 1h=0.25 good=1 bad=1",
        "  attainment interactive|m = 0.5000",
        "drift decode: TRIPPED (0.0400s vs 0.02s)",
        "slow archive: 1/64 (5 archived)",
        "",
        "SERVER                                     HEALTH  ROLE    "
        "MESH       RUN WAIT  CACHE    HIT    MFU  SHED COMPILES "
        "AUTOTUNE",
        "http://e1                                  ok      decode  "
        "1x2x1x2      3    1   0.50   0.25   0.12     2        7 "
        "       2",
    ])
    assert out == expected
    # A guardrail-frozen controller flags the AUTOTUNE column.
    snap["servers"]["http://e1"]["autotune"]["frozen"]["spec_k"] = True
    assert "      2!" in render_snapshot(snap)
    # A dead slice flags the mesh column; a mesh-less (older) snapshot
    # renders the placeholder.
    snap["servers"]["http://e1"]["mesh"]["slices_live"]["1"] = False
    assert "1x2x1x2!" in render_snapshot(snap)
    del snap["servers"]["http://e1"]["mesh"]
    assert "decode  -  " in render_snapshot(snap)
    # A changed server gets its marker; an unhealthy one renders DOWN.
    marked = render_snapshot(snap, changed={"http://e1"})
    assert "http://e1                                * ok" in marked
    snap["servers"]["http://e1"]["healthy"] = False
    assert "DOWN" in render_snapshot(snap)


def test_stacktop_load_change_detection():
    prev = {"servers": {"e1": {"running": 1, "waiting": 0,
                               "cache_usage": 0.1}}}
    same = {"servers": {"e1": {"running": 1, "waiting": 0,
                               "cache_usage": 0.1}}}
    moved = {"servers": {"e1": {"running": 2, "waiting": 0,
                                "cache_usage": 0.1},
                         "e2": {"running": 0}}}
    assert _load_changes(prev, same) == set()
    assert _load_changes(prev, moved) == {"e1", "e2"}
    assert _load_changes(None, moved) == set()


# ---- traceview --from-slow-archive -------------------------------------


def test_traceview_renders_from_slow_archive(tmp_path):
    router_span = {
        "span": "request", "request_id": "rid-1", "model": "m",
        "path": "/v1/chat/completions", "arrival_ts": 100.0,
        "queue_delay_ms": 1.0, "ttft_ms": 900.0, "latency_ms": 950.0,
        "chunks": 4, "status": "ok", "backend": "http://e1",
    }
    engine_span = {
        "span": "engine_request", "request_id": "rid-1",
        "seq_id": "seq-1", "role": "both",
        "events": [{"event": "enqueue", "ts": 100.01},
                   {"event": "first_token", "ts": 100.9}],
    }
    payload = {"entries": [{"request_id": "rid-1",
                            "class": "interactive", "model": "m",
                            "spans": [router_span, engine_span]}]}
    path = tmp_path / "slow.json"
    path.write_text(json.dumps(payload))
    spans = load_slow_archive(str(path))
    assert len(spans) == 2
    text = render_waterfall(spans, "rid-1")
    assert text.startswith("request rid-1  (2 spans)")
    assert "first_chunk" in text and "first_token" in text

    # CLI end-to-end: --from-slow-archive with no span-log files.
    from production_stack_tpu.traceview import main
    assert main(["--from-slow-archive", str(path)]) == 0
