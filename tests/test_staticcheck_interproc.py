"""Self-tests for the interprocedural staticcheck layer (tier 1).

Covers the PR 20 surface: the call graph (resolution kinds, honest
unresolved edges, SCCs), bottom-up function summaries (may-block /
may-host-sync chains, may-raise, page custody, returns-alloc), the
four migrated transitive rules (planted + clean fixture pairs each,
including the soundness obligation that an unresolved edge never
manufactures a finding), the new ``shape-flow`` recompile-budget
proof, chain capping, waiver expiry, fingerprint stability across a
pure rename, and ``--jobs`` output parity. Fixtures are in-memory
(``Project.from_sources``), never the real tree — the real tree's
cleanliness is asserted separately in test_staticcheck.py.
"""

import datetime
import textwrap

from production_stack_tpu.staticcheck import (
    Project,
    run_rules,
)
from production_stack_tpu.staticcheck import callgraph, summaries
from production_stack_tpu.staticcheck.core import (
    CHAIN_CAP,
    cap_frames,
    render_chain,
    _waiver_findings,
)


def _project(sources):
    return Project.from_sources(
        {path: textwrap.dedent(text)
         for path, text in sources.items()})


def _run(sources, rule):
    return [f for f in run_rules(_project(sources), rules=[rule])
            if f.rule == rule]


# ---- call graph --------------------------------------------------------


def test_callgraph_resolves_direct_method_alias_and_partial():
    project = _project({
        "production_stack_tpu/a.py": """\
            import functools
            from production_stack_tpu.b import helper

            def local():
                pass

            class C:
                def m(self):
                    self.n()
                    local()
                    helper()
                    h = functools.partial(local, 1)
                    h()

                def n(self):
                    pass
        """,
        "production_stack_tpu/b.py": """\
            def helper():
                pass
        """,
    })
    graph = callgraph.for_project(project)
    edges = {e.target_text: e
             for e in graph.edges_from(
                 "production_stack_tpu/a.py::C.m")}
    assert edges["self.n"].callee == "production_stack_tpu/a.py::C.n"
    assert edges["self.n"].kind == "method"
    assert edges["local"].callee == "production_stack_tpu/a.py::local"
    assert edges["helper"].callee == "production_stack_tpu/b.py::helper"
    assert edges["h"].callee == "production_stack_tpu/a.py::local"
    assert edges["h"].kind == "alias"


def test_callgraph_keeps_unknown_receivers_unresolved():
    project = _project({
        "production_stack_tpu/a.py": """\
            def f(obj):
                obj.method()
                callback = obj.pick()
                callback()
        """,
    })
    graph = callgraph.for_project(project)
    edges = graph.edges_from("production_stack_tpu/a.py::f")
    assert edges, "calls must be recorded even when unresolved"
    assert all(e.callee is None for e in edges)
    assert any(e.kind == "unresolved" for e in edges)


def test_callgraph_sccs_are_reverse_topological():
    project = _project({
        "production_stack_tpu/a.py": """\
            def leaf():
                pass

            def mid():
                leaf()

            def top():
                mid()

            def ping():
                pong()

            def pong():
                ping()
        """,
    })
    graph = callgraph.for_project(project)
    sccs = graph.sccs()
    order = {qual: i for i, scc in enumerate(sccs) for qual in scc}
    a = "production_stack_tpu/a.py::"
    assert order[a + "leaf"] < order[a + "mid"] < order[a + "top"]
    # The mutual recursion collapses into one SCC of size 2.
    cycle = [scc for scc in sccs if len(scc) == 2]
    assert cycle and set(cycle[0]) == {a + "ping", a + "pong"}


# ---- summaries ---------------------------------------------------------


def test_summaries_chain_reaches_through_two_helpers():
    project = _project({
        "production_stack_tpu/a.py": """\
            def outer():
                return inner()

            def inner():
                import time
                time.sleep(1)
        """,
    })
    sums = summaries.for_project(project)
    chain = sums.get("production_stack_tpu/a.py::outer").may_block
    assert chain is not None
    assert [frame[2] for frame in chain][-1].startswith("time.sleep")


def test_summaries_recursion_converges_to_shortest_chain():
    project = _project({
        "production_stack_tpu/a.py": """\
            def ping(n):
                pong(n)

            def pong(n):
                ping(n)
                open("x")
        """,
    })
    sums = summaries.for_project(project)
    pong = sums.get("production_stack_tpu/a.py::pong").may_block
    ping = sums.get("production_stack_tpu/a.py::ping").may_block
    # pong blocks directly (1 frame); ping via pong (2 frames) — the
    # cycle must not inflate either chain.
    assert pong is not None and len(pong) == 1
    assert ping is not None and len(ping) == 2


def test_summaries_consumed_vs_noncustodial_params():
    project = _project({
        "production_stack_tpu/a.py": """\
            def stores(seq, pages):
                seq.pages = pages

            def reads(pages):
                print(len(pages))

            def forwards_to_reader(pages):
                reads(pages)

            def forwards_to_unknown(pages, sink):
                sink.push(pages)
        """,
    })
    sums = summaries.for_project(project)
    a = "production_stack_tpu/a.py::"
    assert "pages" in sums.get(a + "stores").consumed_params
    assert "pages" not in sums.get(a + "reads").consumed_params
    assert "pages" not in sums.get(
        a + "forwards_to_reader").consumed_params
    # Unknown callee => must assume custody (soundness stance).
    assert "pages" in sums.get(
        a + "forwards_to_unknown").consumed_params


def test_summaries_returns_alloc_through_helper():
    project = _project({
        "production_stack_tpu/a.py": """\
            def direct(cache, n):
                return cache.allocate_pages(n)

            def wrapped(cache, n):
                return list(direct(cache, n))

            def unrelated(cache):
                return cache.stats()
        """,
    })
    sums = summaries.for_project(project)
    a = "production_stack_tpu/a.py::"
    assert sums.get(a + "direct").returns_alloc
    assert sums.get(a + "wrapped").returns_alloc
    assert not sums.get(a + "unrelated").returns_alloc


def test_summaries_may_raise_propagates():
    project = _project({
        "production_stack_tpu/a.py": """\
            def thrower():
                raise ValueError("boom")

            def caller():
                thrower()
        """,
    })
    sums = summaries.for_project(project)
    a = "production_stack_tpu/a.py::"
    assert "ValueError" in sums.get(a + "thrower").may_raise
    assert "ValueError" in sums.get(a + "caller").may_raise


# ---- transitive async-blocking -----------------------------------------

_ASYNC_HELPERS = {
    "production_stack_tpu/router/util.py": """\
        def read_config(path):
            return _load(path)

        def _load(path):
            with open(path) as f:
                return f.read()
    """,
}


def test_async_blocking_transitive_flags_handler_not_sync_caller():
    findings = _run({
        **_ASYNC_HELPERS,
        "production_stack_tpu/router/app.py": """\
            from production_stack_tpu.router.util import read_config

            async def handler(request):
                return read_config("x.json")

            def sync_caller():
                return read_config("y.json")
        """,
    }, "async-blocking")
    assert len(findings) == 1
    f = findings[0]
    assert "handler" in f.message
    assert "read_config" in f.message
    assert "open()" in f.message       # blocking primitive, 2 frames down
    assert len(f.chain) >= 3


def test_async_blocking_transitive_clean_through_async_helper():
    findings = _run({
        "production_stack_tpu/router/app.py": """\
            import asyncio

            async def helper():
                await asyncio.sleep(1)

            async def handler(request):
                await helper()
        """,
    }, "async-blocking")
    assert findings == []


def test_async_blocking_unresolved_edge_makes_no_finding():
    findings = _run({
        "production_stack_tpu/router/app.py": """\
            async def handler(request, client):
                client.fetch_sync()
        """,
    }, "async-blocking")
    assert findings == []


# ---- transitive tracer-hygiene / host-read -----------------------------


def test_tracer_hygiene_transitive_sync_below_jit_boundary():
    findings = _run({
        "production_stack_tpu/ops/kern.py": """\
            import jax

            def _peek(x):
                return x.item()

            @jax.jit
            def step(x):
                return _peek(x)
        """,
    }, "tracer-hygiene")
    transitive = [f for f in findings if "reaches a" in f.message]
    assert len(transitive) == 1
    assert "_peek" in transitive[0].message


def test_tracer_hygiene_transitive_clean_helper_not_flagged():
    findings = _run({
        "production_stack_tpu/ops/kern.py": """\
            import jax
            import jax.numpy as jnp

            def _scale(x):
                return x * 2

            @jax.jit
            def step(x):
                return _scale(x)
        """,
    }, "tracer-hygiene")
    assert findings == []


def test_host_read_transitive_helper_below_dispatch_path():
    findings = _run({
        "production_stack_tpu/engine/model_runner.py": """\
            import jax

            def dispatch_decode(rows):
                return _staging_set(rows)

            def _staging_set(rows):
                return _peek_helper(rows)

            def _dispatch(payload):
                return payload

            def execute_payload(payload):
                return payload

            def _optional_device_inputs(p):
                return p

            def _penalty_payload(p):
                return p

            def _seed_payload(p):
                return p

            def _bias_payload(p):
                return p

            def _suppress_payload(p):
                return p

            def _guided_payload(p):
                return p

            def _next_rng():
                return 1

            def _as_device(x):
                return x

            def _peek_helper(rows):
                return jax.device_get(rows)
        """,
    }, "host-read")
    transitive = [f for f in findings
                  if "reaches a blocking host read" in f.message]
    assert len(transitive) == 1
    assert "_peek_helper" in transitive[0].message


# ---- transitive page-lifecycle -----------------------------------------


def test_page_lifecycle_alloc_via_helper_summary():
    findings = _run({
        "production_stack_tpu/engine/scheduler.py": """\
            class Scheduler:
                def _grab(self, n):
                    return self.cache.allocate_pages(n)

                def admit(self, seq):
                    pages = self._grab(4)
                    if not seq.ok:
                        return None
                    seq.pages = pages
                    return pages
        """,
    }, "page-lifecycle")
    assert len(findings) == 1
    assert "pages" in findings[0].message


def test_page_lifecycle_pure_read_callee_does_not_take_custody():
    findings = _run({
        "production_stack_tpu/engine/scheduler.py": """\
            class Scheduler:
                def admit(self, seq):
                    pages = self.cache.allocate_pages(4)
                    self._log_count(pages)
                    return None

                def _log_count(self, pages):
                    print(len(pages))
        """,
    }, "page-lifecycle")
    assert len(findings) == 1  # the len() read proves nothing owned


def test_page_lifecycle_consuming_callee_takes_custody():
    findings = _run({
        "production_stack_tpu/engine/scheduler.py": """\
            class Scheduler:
                def admit(self, seq):
                    pages = self.cache.allocate_pages(4)
                    self._attach(seq, pages)
                    return None

                def _attach(self, seq, pages):
                    seq.pages = pages
        """,
    }, "page-lifecycle")
    assert findings == []


def test_page_lifecycle_unresolved_callee_counts_as_custody():
    findings = _run({
        "production_stack_tpu/engine/scheduler.py": """\
            class Scheduler:
                def admit(self, seq):
                    pages = self.cache.allocate_pages(4)
                    seq.take(pages)
                    return None
        """,
    }, "page-lifecycle")
    assert findings == []


def test_page_lifecycle_callee_may_raise_creates_exception_path():
    findings = _run({
        "production_stack_tpu/engine/scheduler.py": """\
            class Scheduler:
                def _check(self, seq):
                    if not seq.ok:
                        raise ValueError("bad")

                def admit(self, seq):
                    pages = self.cache.allocate_pages(4)
                    self._check(seq)
                    seq.pages = pages
        """,
    }, "page-lifecycle")
    assert len(findings) == 1
    assert "exception path" in findings[0].message


# ---- shape-flow --------------------------------------------------------

_RUNNER_HEADER = """\
    import jax

    class Runner:
        def __init__(self):
            self._step_jit = jax.jit(self._impl)
            self._buckets = [16, 32, 64]

        def _bucket_for(self, n):
            for b in self._buckets:
                if n <= b:
                    return b
            return self._buckets[-1]

"""


def test_shape_flow_flags_unsnapped_int_through_helper():
    findings = _run({
        "production_stack_tpu/engine/runner.py":
            _RUNNER_HEADER + """\
        def dispatch(self, rows):
            n = self._pick_width(rows)
            return self._step_jit(self.params, n)

        def _pick_width(self, rows):
            return len(rows)
""",
    }, "shape-flow")
    assert len(findings) == 1
    f = findings[0]
    assert "_pick_width" in f.message
    assert "len(" in f.message
    assert len(f.chain) >= 3


def test_shape_flow_flags_raw_param_from_caller():
    findings = _run({
        "production_stack_tpu/engine/runner.py":
            _RUNNER_HEADER + """\
        def inner_dispatch(self, w):
            return self._step_jit(self.params, w)

        def outer(self, rows):
            return self.inner_dispatch(len(rows))
""",
    }, "shape-flow")
    assert len(findings) == 1
    assert "passes w" in findings[0].message


def test_shape_flow_accepts_snap_helper_and_inline_lattice():
    findings = _run({
        "production_stack_tpu/engine/runner.py":
            _RUNNER_HEADER + """\
        def snapped(self, rows):
            t = self._bucket_for(len(rows))
            return self._step_jit(self.params, t)

        def lattice(self, rows):
            t = 16
            while t < len(rows):
                t *= 2
            return self._step_jit(self.params, t)

        def config(self, rows):
            return self._step_jit(self.params, self.decode_width)
""",
    }, "shape-flow")
    assert findings == []


def test_shape_flow_unresolved_call_makes_no_finding():
    findings = _run({
        "production_stack_tpu/engine/runner.py":
            _RUNNER_HEADER + """\
        def opaque(self, payload):
            return self._step_jit(self.params, payload.width())
""",
    }, "shape-flow")
    assert findings == []


def test_shape_flow_shape_source_waiver_suppresses():
    findings = _run({
        "production_stack_tpu/engine/runner.py":
            _RUNNER_HEADER + """\
        def declared(self, rows):
            n = len(rows)  # lint: shape-source
            return self._step_jit(self.params, n)
""",
    }, "shape-flow")
    assert findings == []


# ---- chain capping -----------------------------------------------------


def test_cap_frames_caps_at_chain_cap_and_counts_dropped():
    frames = [("f.py", i, f"frame{i}") for i in range(10)]
    capped, dropped = cap_frames(frames)
    assert len(capped) == CHAIN_CAP
    assert dropped == 10 - CHAIN_CAP
    rendered = render_chain(frames)
    assert f"… (+{10 - CHAIN_CAP} frames)" in rendered
    assert rendered.count("→") == CHAIN_CAP - 1


def test_deep_chain_is_capped_in_finding_json():
    helpers = {}
    # h0 -> h1 -> ... -> h9 -> open(): a 10-frame blocking chain.
    body = "def h9(p):\n    with open(p) as f:\n        return f.read()\n"
    for i in range(9):
        body += f"\n\ndef h{8 - i}(p):\n    return h{9 - i}(p)\n"
    findings = _run({
        "production_stack_tpu/router/util.py": body,
        "production_stack_tpu/router/app.py": """\
            from production_stack_tpu.router.util import h0

            async def handler(request):
                return h0("x")
        """,
    }, "async-blocking")
    assert len(findings) == 1
    payload = findings[0].to_json()
    assert len(payload["chain"]) == CHAIN_CAP
    assert payload["chain_dropped"] > 0
    assert "… (+" in findings[0].message


# ---- waiver expiry -----------------------------------------------------


def test_dated_waiver_suppresses_until_expiry():
    future = (datetime.date(2026, 8, 6)
              + datetime.timedelta(days=30)).isoformat()
    findings = _run({
        "production_stack_tpu/router/app.py": f"""\
            import time

            async def handler(request):
                time.sleep(1)  # lint: allow-async-blocking until={future}
        """,
    }, "async-blocking")
    assert findings == []


def test_expired_waiver_stops_suppressing_and_is_reported():
    project = _project({
        "production_stack_tpu/router/app.py": """\
            import time

            async def handler(request):
                time.sleep(1)  # lint: allow-async-blocking until=2025-01-01
        """,
    })
    findings = run_rules(project)
    rules_hit = {f.rule for f in findings}
    assert "async-blocking" in rules_hit    # suppression lapsed
    assert "expired-waiver" in rules_hit    # and the lapse is loud
    expired = [f for f in findings if f.rule == "expired-waiver"]
    assert "2025-01-01" in expired[0].message


def test_malformed_waiver_date_is_a_finding():
    project = _project({
        "production_stack_tpu/router/app.py": """\
            import time

            async def handler(request):
                time.sleep(1)  # lint: allow-async-blocking until=soon
        """,
    })
    findings = _waiver_findings(project)
    assert any(f.rule == "expired-waiver" and "soon" in f.message
               for f in findings)


# ---- fingerprint stability ---------------------------------------------


def test_transitive_fingerprint_survives_pure_helper_rename():
    def tree(helper_name):
        return {
            "production_stack_tpu/router/app.py": f"""\
                from production_stack_tpu.router.util import (
                    {helper_name},
                )

                async def handler(request):
                    return {helper_name}()
            """,
            "production_stack_tpu/router/util.py": f"""\
                def {helper_name}():
                    import time
                    time.sleep(1)
            """,
        }
    # The flagged line's *text* is unchanged modulo the rename; the
    # fingerprint normalizes neither chain nor line numbers into the
    # hash, so line drift above the call site must not move it.
    before = _run(tree("read_config"), "async-blocking")
    drifted = {
        path: ("# a new leading comment\n\n"
               + textwrap.dedent(text) if "app" in path
               else text)
        for path, text in tree("read_config").items()}
    after = _run(drifted, "async-blocking")
    assert len(before) == len(after) == 1
    assert before[0].fingerprint() == after[0].fingerprint()


# ---- --jobs parity -----------------------------------------------------


def test_jobs_parallel_run_matches_serial_run():
    sources = {
        **_ASYNC_HELPERS,
        "production_stack_tpu/router/app.py": """\
            from production_stack_tpu.router.util import read_config

            async def handler(request):
                return read_config("x.json")
        """,
        "production_stack_tpu/engine/scheduler.py": """\
            class Scheduler:
                def admit(self, seq):
                    pages = self.cache.allocate_pages(4)
                    if not seq.ok:
                        return None
                    seq.pages = pages
        """,
    }
    serial = run_rules(_project(sources))
    parallel = run_rules(_project(sources), jobs=4)
    assert [f.to_json() for f in serial] == \
        [f.to_json() for f in parallel]
    assert serial, "fixture must actually produce findings"
