"""RequestStatsMonitor lifecycle accounting (test model: reference
src/tests/test_singleton.py + request_stats semantics)."""

import pytest

from production_stack_tpu.router.stats.request_stats import (
    BLOCK_SIZE,
    DECODE_TO_PREFILL_RATIO,
    TOTAL_NUMBER_OF_BLOCKS,
    RequestStatsMonitor,
    get_request_stats_monitor,
    initialize_request_stats_monitor,
)

URL = "http://engine:8000"


def make_monitor(window=60.0):
    return initialize_request_stats_monitor(window)


def test_singleton_semantics():
    with pytest.raises(ValueError):
        RequestStatsMonitor()  # not initialized yet
    m1 = initialize_request_stats_monitor(10.0)
    m2 = get_request_stats_monitor()
    assert m1 is m2
    # Second init with different args returns same instance.
    assert initialize_request_stats_monitor(99.0) is m1
    assert m1.window_s == 10.0


def test_full_lifecycle_counts():
    m = make_monitor()
    t = 1000.0
    m.on_request_arrival("r1", t)
    m.on_request_routed(URL, "r1", prefill_tokens=64)
    m.on_request_start(URL, "r1", t + 0.01)

    stats = m.get_request_stats(t + 0.05)
    assert stats[URL].in_prefill_requests == 1
    assert stats[URL].in_decoding_requests == 0

    # First token: prefill -> decode, TTFT recorded.
    m.on_request_response(URL, "r1", t + 0.5, is_first_token=True)
    stats = m.get_request_stats(t + 0.6)
    assert stats[URL].in_prefill_requests == 0
    assert stats[URL].in_decoding_requests == 1
    assert abs(stats[URL].ttft - 0.5) < 1e-6

    for i in range(4):
        m.on_request_response(URL, "r1", t + 0.6 + i * 0.1,
                              is_first_token=False)
    m.on_request_complete(URL, "r1", t + 1.5)
    stats = m.get_request_stats(t + 1.6)
    assert stats[URL].in_decoding_requests == 0
    assert stats[URL].finished_requests == 1
    assert abs(stats[URL].avg_latency - 1.5) < 1e-6
    assert abs(stats[URL].avg_decoding_length - 1.0) < 1e-6


def test_block_accounting():
    m = make_monitor()
    t = 0.0
    m.on_request_arrival("r1", t)
    m.on_request_routed(URL, "r1", prefill_tokens=160)
    # In prefill: reserved = ceil(160 * 1.25 / 16)
    expected_reserved = -(-int(160 * (1 + DECODE_TO_PREFILL_RATIO))
                          // BLOCK_SIZE)
    assert m.estimate_pending_reserved_blocks(URL) == expected_reserved
    assert m.estimate_allocated_blocks(URL) == 0

    # Move to decode with 5 generated tokens: allocated =
    # ceil((160 + 5)/16), reserved drops to 0.
    m.on_request_response(URL, "r1", t + 1, is_first_token=True)
    for i in range(4):
        m.on_request_response(URL, "r1", t + 1.1, is_first_token=False)
    assert m.estimate_pending_reserved_blocks(URL) == 0
    assert m.estimate_allocated_blocks(URL) == -(-165 // BLOCK_SIZE)

    stats = m.get_request_stats(t + 2)
    assert stats[URL].num_free_blocks == (
        TOTAL_NUMBER_OF_BLOCKS - stats[URL].allocated_blocks
    )

    m.on_request_complete(URL, "r1", t + 3)
    assert m.estimate_allocated_blocks(URL) == 0


def test_kill_cleans_up():
    m = make_monitor()
    m.on_request_arrival("r1", 0.0)
    m.on_request_routed(URL, "r1", 32)
    m.on_request_response(URL, "r1", 1.0, is_first_token=True)
    m.on_request_kill(URL, "r1")
    stats = m.get_request_stats(2.0)
    assert stats[URL].in_prefill_requests == 0
    assert stats[URL].in_decoding_requests == 0
    assert m.estimate_allocated_blocks(URL) == 0
    # A completion after the kill must not crash or double count.
    m.on_request_complete(URL, "r1", 3.0)
    assert m.get_request_stats(4.0)[URL].finished_requests == 0


def test_qps_sliding_window():
    m = make_monitor(window=10.0)
    for i in range(20):
        rid = f"r{i}"
        m.on_request_arrival(rid, float(i))
        m.on_request_routed(URL, rid, 16)
        m.on_request_start(URL, rid, float(i))
    # At t=20, only arrivals in (10, 20] remain: 10 requests over 10 s.
    stats = m.get_request_stats(20.0)
    assert abs(stats[URL].qps - 1.0) < 0.11


def test_queueing_delay_prefill_length_and_itl():
    """The dashboard's QoS metrics: queueing delay (arrival->routed),
    avg prefill length, and per-request ITL on completion."""
    m = make_monitor()
    t = 2000.0
    m.on_request_arrival("q1", t)
    m.on_request_routed(URL, "q1", prefill_tokens=100, timestamp=t + 0.2)
    m.on_request_start(URL, "q1", t + 0.21)
    stats = m.get_request_stats(t + 0.3)
    assert abs(stats[URL].queueing_delay - 0.2) < 1e-6
    assert abs(stats[URL].avg_prefill_length - 100.0) < 1e-6

    # 1 first token + 4 more tokens over 0.8 s decode -> ITL = 0.2 s.
    m.on_request_response(URL, "q1", t + 0.5, is_first_token=True)
    for i in range(4):
        m.on_request_response(URL, "q1", t + 0.5 + (i + 1) * 0.2,
                              is_first_token=False)
    m.on_request_complete(URL, "q1", t + 1.3)
    stats = m.get_request_stats(t + 1.4)
    assert abs(stats[URL].avg_itl - 0.2) < 1e-6
