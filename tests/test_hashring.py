"""Consistent-hash ring behavior (test model: reference
src/tests/test_session_router.py minimal-remap assertions)."""

from collections import Counter

from production_stack_tpu.router.routing.hashring import ConsistentHashRing


def test_empty_ring_returns_none():
    ring = ConsistentHashRing()
    assert ring.get_node("key") is None


def test_single_node_gets_everything():
    ring = ConsistentHashRing()
    ring.add_node("http://a")
    assert all(ring.get_node(f"k{i}") == "http://a" for i in range(50))


def test_distribution_is_roughly_uniform():
    ring = ConsistentHashRing()
    nodes = [f"http://node{i}" for i in range(4)]
    for n in nodes:
        ring.add_node(n)
    counts = Counter(ring.get_node(f"session-{i}") for i in range(4000))
    for n in nodes:
        assert 0.10 < counts[n] / 4000 < 0.45, counts


def test_stickiness():
    ring = ConsistentHashRing()
    for n in ("http://a", "http://b", "http://c"):
        ring.add_node(n)
    first = {f"s{i}": ring.get_node(f"s{i}") for i in range(100)}
    again = {f"s{i}": ring.get_node(f"s{i}") for i in range(100)}
    assert first == again


def test_minimal_remap_on_node_removal():
    ring = ConsistentHashRing()
    nodes = [f"http://node{i}" for i in range(4)]
    for n in nodes:
        ring.add_node(n)
    keys = [f"session-{i}" for i in range(1000)]
    before = {k: ring.get_node(k) for k in keys}
    ring.remove_node(nodes[0])
    after = {k: ring.get_node(k) for k in keys}
    # Keys not on the removed node must not move.
    for k in keys:
        if before[k] != nodes[0]:
            assert after[k] == before[k]
        else:
            assert after[k] != nodes[0]


def test_minimal_remap_on_node_addition():
    ring = ConsistentHashRing()
    for i in range(3):
        ring.add_node(f"http://node{i}")
    keys = [f"session-{i}" for i in range(1000)]
    before = {k: ring.get_node(k) for k in keys}
    ring.add_node("http://node3")
    after = {k: ring.get_node(k) for k in keys}
    moved = sum(1 for k in keys if before[k] != after[k])
    # Only keys remapping onto the new node may move (~1/4 of keys).
    for k in keys:
        if before[k] != after[k]:
            assert after[k] == "http://node3"
    assert moved < 500


def test_sync_converges():
    ring = ConsistentHashRing()
    ring.sync(["http://a", "http://b"])
    assert set(ring.get_nodes()) == {"http://a", "http://b"}
    ring.sync(["http://b", "http://c"])
    assert set(ring.get_nodes()) == {"http://b", "http://c"}
