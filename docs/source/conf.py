# Sphinx configuration for the TPU serving stack docs
# (counterpart of reference docs/source/conf.py).

project = "production-stack-tpu"
copyright = "2026, production-stack-tpu contributors"
author = "production-stack-tpu contributors"
release = "0.1.0"

extensions = [
    "myst_parser",
    "sphinx.ext.autodoc",
    "sphinx.ext.napoleon",
    "sphinx.ext.viewcode",
]

source_suffix = {".rst": "restructuredtext", ".md": "markdown"}
master_doc = "index"
exclude_patterns = []

html_theme = "sphinx_rtd_theme"
html_static_path = []
