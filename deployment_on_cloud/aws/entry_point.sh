#!/bin/bash
# EKS bootstrap (counterpart of reference deployment_on_cloud/aws/
# entry_point.sh, which creates an EKS GPU cluster + EFS CSI). AWS has
# no TPUs, so this variant hosts the ROUTER + observability tiers on
# EKS and points the router at TPU engine endpoints running elsewhere
# (typically the GKE bootstrap in ../gcp) via static discovery over DCN.
#
# Usage: ./entry_point.sh CLUSTER_NAME ENGINE_URLS ENGINE_MODELS
#   ENGINE_URLS   comma-separated http endpoints of TPU engines
#   ENGINE_MODELS comma-separated served model names (same order)
set -euo pipefail

CLUSTER_NAME="${1:?usage: entry_point.sh CLUSTER_NAME ENGINE_URLS ENGINE_MODELS}"
ENGINE_URLS="${2:?missing ENGINE_URLS}"
ENGINE_MODELS="${3:?missing ENGINE_MODELS}"
REGION="${REGION:-us-east-1}"

echo "==> Creating EKS cluster $CLUSTER_NAME"
eksctl create cluster \
    --name "$CLUSTER_NAME" \
    --region "$REGION" \
    --node-type m6i.xlarge \
    --nodes 2

echo "==> Installing router tier (static discovery to TPU engines)"
helm install tpu-stack "$(dirname "$0")/../../helm" \
    --set servingEngineSpec.enableEngine=false \
    --set routerSpec.serviceDiscovery=static \
    --set routerSpec.staticBackends="$ENGINE_URLS" \
    --set routerSpec.staticModels="$ENGINE_MODELS" \
    --set routerSpec.serviceType=LoadBalancer

kubectl get svc tpu-stack-router-service
