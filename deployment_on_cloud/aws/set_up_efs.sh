#!/bin/bash
# Provision EFS + the CSI driver for the router tier's shared state
# (files API storage, batch JSONL artifacts — counterpart of the
# reference's aws/set_up_efs.sh flow, TPU-stack variant: the router
# tier runs on EKS, engines live on GKE TPU pools).
#
# Usage: ./set_up_efs.sh CLUSTER_NAME
set -euo pipefail

CLUSTER_NAME="${1:?usage: set_up_efs.sh CLUSTER_NAME}"
REGION="${REGION:-us-east-1}"

echo "==> Looking up cluster VPC/subnets"
VPC_ID=$(aws eks describe-cluster --name "$CLUSTER_NAME" \
    --region "$REGION" \
    --query 'cluster.resourcesVpcConfig.vpcId' --output text)
SUBNETS=$(aws eks describe-cluster --name "$CLUSTER_NAME" \
    --region "$REGION" \
    --query 'cluster.resourcesVpcConfig.subnetIds[]' --output text)
CIDR=$(aws ec2 describe-vpcs --vpc-ids "$VPC_ID" --region "$REGION" \
    --query 'Vpcs[0].CidrBlock' --output text)

echo "==> Creating EFS file system"
FS_ID=$(aws efs create-file-system --region "$REGION" \
    --performance-mode generalPurpose --encrypted \
    --tags "Key=Name,Value=${CLUSTER_NAME}-router-files" \
    --query 'FileSystemId' --output text)

echo "==> Opening NFS (2049) from the VPC"
SG_ID=$(aws ec2 create-security-group --region "$REGION" \
    --group-name "${CLUSTER_NAME}-efs" \
    --description "EFS for ${CLUSTER_NAME}" --vpc-id "$VPC_ID" \
    --query 'GroupId' --output text)
aws ec2 authorize-security-group-ingress --region "$REGION" \
    --group-id "$SG_ID" --protocol tcp --port 2049 --cidr "$CIDR"

echo "==> Waiting for the file system, then creating mount targets"
aws efs wait file-system-available --file-system-id "$FS_ID" \
    --region "$REGION" 2>/dev/null || sleep 15
for subnet in $SUBNETS; do
  aws efs create-mount-target --file-system-id "$FS_ID" \
      --subnet-id "$subnet" --security-groups "$SG_ID" \
      --region "$REGION" || true
done

echo "==> Installing the EFS CSI driver"
eksctl create addon --name aws-efs-csi-driver \
    --cluster "$CLUSTER_NAME" --region "$REGION" --force || \
  helm repo add aws-efs-csi-driver \
      https://kubernetes-sigs.github.io/aws-efs-csi-driver/ && \
  helm upgrade --install aws-efs-csi-driver \
      aws-efs-csi-driver/aws-efs-csi-driver -n kube-system

echo "==> StorageClass + PVC (router-files-pvc)"
kubectl apply -f - <<YAML
kind: StorageClass
apiVersion: storage.k8s.io/v1
metadata:
  name: efs-sc
provisioner: efs.csi.aws.com
parameters:
  provisioningMode: efs-ap
  fileSystemId: ${FS_ID}
  directoryPerms: "700"
---
apiVersion: v1
kind: PersistentVolumeClaim
metadata:
  name: router-files-pvc
spec:
  accessModes: [ReadWriteMany]
  storageClassName: efs-sc
  resources:
    requests:
      storage: 100Gi
YAML

echo "==> Done: EFS $FS_ID; use --set routerSpec.filesPvc=router-files-pvc"
