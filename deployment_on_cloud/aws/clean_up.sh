#!/bin/bash
# Tear down the EKS router tier (EFS mounts must go first or the VPC
# deletion hangs).
set -euo pipefail
CLUSTER_NAME="${1:?usage: clean_up.sh CLUSTER_NAME}"
REGION="${REGION:-us-east-1}"

helm uninstall tpu-stack || true
FS_IDS=$(aws efs describe-file-systems --region "$REGION" \
  --query "FileSystems[?Tags[?Key=='Name' && Value=='${CLUSTER_NAME}-router-files']].FileSystemId" \
  --output text)
for fs in $FS_IDS; do
  for mt in $(aws efs describe-mount-targets --file-system-id "$fs" \
      --region "$REGION" --query 'MountTargets[].MountTargetId' \
      --output text); do
    aws efs delete-mount-target --mount-target-id "$mt" --region "$REGION"
  done
  sleep 10
  aws efs delete-file-system --file-system-id "$fs" --region "$REGION"
done
eksctl delete cluster --name "$CLUSTER_NAME" --region "$REGION"
