#!/bin/bash
set -euo pipefail
RESOURCE_GROUP="${1:?usage: clean_up.sh RESOURCE_GROUP CLUSTER_NAME}"
CLUSTER_NAME="${2:?usage: clean_up.sh RESOURCE_GROUP CLUSTER_NAME}"
helm uninstall tpu-stack || true
az aks delete --resource-group "$RESOURCE_GROUP" \
  --name "$CLUSTER_NAME" --yes
