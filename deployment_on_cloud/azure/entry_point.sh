#!/bin/bash
# AKS bootstrap (counterpart of reference deployment_on_cloud/azure/
# entry_point.sh). Azure has no TPUs; like the AWS variant this hosts
# the router + observability tiers and fronts remote TPU engines via
# static discovery.
#
# Usage: ./entry_point.sh RESOURCE_GROUP CLUSTER_NAME ENGINE_URLS ENGINE_MODELS
set -euo pipefail

RESOURCE_GROUP="${1:?usage: entry_point.sh RG CLUSTER ENGINE_URLS ENGINE_MODELS}"
CLUSTER_NAME="${2:?usage: entry_point.sh RG CLUSTER ENGINE_URLS ENGINE_MODELS}"
ENGINE_URLS="${3:?missing ENGINE_URLS}"
ENGINE_MODELS="${4:?missing ENGINE_MODELS}"
LOCATION="${LOCATION:-eastus}"

az group create --name "$RESOURCE_GROUP" --location "$LOCATION"
az aks create \
    --resource-group "$RESOURCE_GROUP" \
    --name "$CLUSTER_NAME" \
    --node-count 2 \
    --node-vm-size Standard_D4s_v5 \
    --generate-ssh-keys
az aks get-credentials --resource-group "$RESOURCE_GROUP" \
    --name "$CLUSTER_NAME"

helm install tpu-stack "$(dirname "$0")/../../helm" \
    --set servingEngineSpec.enableEngine=false \
    --set routerSpec.serviceDiscovery=static \
    --set routerSpec.staticBackends="$ENGINE_URLS" \
    --set routerSpec.staticModels="$ENGINE_MODELS" \
    --set routerSpec.serviceType=LoadBalancer

kubectl get svc tpu-stack-router-service
