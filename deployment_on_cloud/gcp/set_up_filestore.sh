#!/bin/bash
# Provision a Filestore share and bind it into the cluster as the
# model-weights PV (counterpart of the reference's EFS/Filestore CSI
# flows: shared storage so every engine pod mounts the same checkpoint
# instead of pulling per pod — tutorials/03-load-model-from-pv.md).
#
# Usage: ./set_up_filestore.sh PROJECT_ID INSTANCE_NAME [SIZE_GB]
set -euo pipefail

PROJECT_ID="${1:?usage: set_up_filestore.sh PROJECT_ID INSTANCE_NAME [SIZE_GB]}"
INSTANCE_NAME="${2:?usage: set_up_filestore.sh PROJECT_ID INSTANCE_NAME [SIZE_GB]}"
SIZE_GB="${3:-1024}"
ZONE="${ZONE:-us-central2-b}"
SHARE_NAME="${SHARE_NAME:-models}"
NETWORK="${NETWORK:-default}"

gcloud config set project "$PROJECT_ID"

echo "==> Creating Filestore instance $INSTANCE_NAME (${SIZE_GB}GiB)"
gcloud filestore instances create "$INSTANCE_NAME" \
    --zone "$ZONE" \
    --tier BASIC_SSD \
    --file-share "name=${SHARE_NAME},capacity=${SIZE_GB}GB" \
    --network "name=${NETWORK}"

IP=$(gcloud filestore instances describe "$INSTANCE_NAME" \
    --zone "$ZONE" --format='value(networks[0].ipAddresses[0])')
echo "==> Filestore ready at ${IP}:/${SHARE_NAME}"

echo "==> Creating PV + PVC (model-weights-pvc)"
kubectl apply -f - <<YAML
apiVersion: v1
kind: PersistentVolume
metadata:
  name: model-weights-pv
spec:
  capacity:
    storage: ${SIZE_GB}Gi
  accessModes: [ReadWriteMany]
  nfs:
    server: ${IP}
    path: /${SHARE_NAME}
  persistentVolumeReclaimPolicy: Retain
---
apiVersion: v1
kind: PersistentVolumeClaim
metadata:
  name: model-weights-pvc
spec:
  accessModes: [ReadWriteMany]
  storageClassName: ""
  volumeName: model-weights-pv
  resources:
    requests:
      storage: ${SIZE_GB}Gi
YAML

cat <<MSG
==> Done. Install the chart with the PVC mounted, e.g.:
  helm install tpu-stack ../../helm \\
    --set servingEngineSpec.modelSpec[0].pvcStorage=model-weights-pvc \\
    --set servingEngineSpec.modelSpec[0].modelPath=/models/llama-3-8b
(prefetch weights once with tutorials/assets/values-03-pvc-prefetch.yaml)
MSG
