#!/bin/bash
# GKE bootstrap for the TPU serving stack (counterpart of reference
# deployment_on_cloud/gcp/entry_point.sh, which creates a GPU cluster +
# Filestore CSI). This variant creates a CPU default pool for the
# router/observability tiers and a TPU v5e pod-slice node pool for the
# engines, then installs the chart.
#
# Usage: ./entry_point.sh PROJECT_ID CLUSTER_NAME [values.yaml]
set -euo pipefail

PROJECT_ID="${1:?usage: entry_point.sh PROJECT_ID CLUSTER_NAME [values.yaml]}"
CLUSTER_NAME="${2:?usage: entry_point.sh PROJECT_ID CLUSTER_NAME [values.yaml]}"
VALUES_FILE="${3:-$(dirname "$0")/production_stack_specification.yaml}"

REGION="${REGION:-us-central2}"
ZONE="${ZONE:-${REGION}-b}"
# v5e 2x4 slice (8 chips) matches the chart default
# (helm/values.yaml tpu.topology: 2x4).
TPU_TYPE="${TPU_TYPE:-ct5lp-hightpu-8t}"
TPU_TOPOLOGY="${TPU_TOPOLOGY:-2x4}"
NUM_TPU_NODES="${NUM_TPU_NODES:-1}"

gcloud config set project "$PROJECT_ID"

echo "==> Creating GKE cluster $CLUSTER_NAME ($ZONE)"
gcloud container clusters create "$CLUSTER_NAME" \
    --zone "$ZONE" \
    --machine-type e2-standard-8 \
    --num-nodes 2 \
    --addons GcpFilestoreCsiDriver

echo "==> Adding TPU v5e node pool ($TPU_TYPE, topology $TPU_TOPOLOGY)"
gcloud container node-pools create tpu-pool \
    --cluster "$CLUSTER_NAME" \
    --zone "$ZONE" \
    --machine-type "$TPU_TYPE" \
    --tpu-topology "$TPU_TOPOLOGY" \
    --num-nodes "$NUM_TPU_NODES" \
    --node-taints google.com/tpu=present:NoSchedule

gcloud container clusters get-credentials "$CLUSTER_NAME" --zone "$ZONE"

echo "==> Installing tpu-stack chart"
helm install tpu-stack "$(dirname "$0")/../../helm" -f "$VALUES_FILE"

echo "==> Done. Router endpoint:"
kubectl get svc tpu-stack-router-service
