#!/bin/bash
# Tear down everything entry_point.sh created (reference
# deployment_on_cloud/gcp cleanup flow).
set -euo pipefail

PROJECT_ID="${1:?usage: clean_up.sh PROJECT_ID CLUSTER_NAME}"
CLUSTER_NAME="${2:?usage: clean_up.sh PROJECT_ID CLUSTER_NAME}"
ZONE="${ZONE:-${REGION:-us-central2}-b}"

gcloud config set project "$PROJECT_ID"
helm uninstall tpu-stack || true
gcloud container clusters delete "$CLUSTER_NAME" --zone "$ZONE" --quiet
