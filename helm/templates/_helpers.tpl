{{/* Common labels */}}
{{- define "tpu-stack.labels" -}}
helm.sh/chart: {{ .Chart.Name }}-{{ .Chart.Version }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
app.kubernetes.io/instance: {{ .Release.Name }}
release: {{ .Release.Name }}
{{- end }}

{{/* Engine pod selector labels (the router's discovery matches these) */}}
{{- define "tpu-stack.engineLabels" -}}
environment: serving
release: {{ .Release.Name }}
{{- end }}

{{- define "tpu-stack.serviceAccountName" -}}
{{- if .Values.serviceAccount.name }}
{{- .Values.serviceAccount.name }}
{{- else }}
{{- printf "%s-sa" .Release.Name }}
{{- end }}
{{- end }}
