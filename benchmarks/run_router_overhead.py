"""Router-overhead baseline: measured QPS/TTFT curves per policy.

Launches N fake OpenAI engines (testing/fake_engine.py — configurable
token rate, zero accelerators) behind the router, then drives the
multi-round-QA workload through it across a QPS sweep for each routing
policy. The router's own cost is the difference between these curves
and the fake engines' configured service time.

This is the measured artifact the reference produces with
src/tests/perftest (fake-openai-server + request-generator); results
land in benchmarks/results/router_overhead.{json,md} and are committed
so the baseline is inspectable without re-running.

Usage:
    python benchmarks/run_router_overhead.py            # full sweep
    python benchmarks/run_router_overhead.py --quick    # 1 policy/QPS
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL = "perf/model"
BASE_PORT = 9300
ROUTER_PORT = 8301


def _wait_http(url: str, timeout: float = 60.0) -> None:
    import urllib.request
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            urllib.request.urlopen(url, timeout=1)
            return
        except Exception:
            time.sleep(0.3)
    raise RuntimeError(f"{url} did not come up")


def _launch(cmd, log):
    return subprocess.Popen(
        cmd, cwd=REPO, stdout=log, stderr=subprocess.STDOUT,
        start_new_session=True)


def _free_ports(n: int):
    """OS-allocated free ports: a stale process from an earlier case
    (or an aborted run) can hold any fixed port and wedge the bind."""
    import socket
    socks, ports = [], []
    for _ in range(n):
        sk = socket.socket()
        sk.bind(("127.0.0.1", 0))
        socks.append(sk)
        ports.append(sk.getsockname()[1])
    for sk in socks:
        sk.close()
    return ports


def run_case(policy: str, qps: float, num_engines: int, speed: int,
             num_users: int, rounds: int) -> dict:
    procs = []
    ports = _free_ports(num_engines + 1)
    router_port = ports[-1]
    logf = open("/tmp/router_overhead_case.log", "w")
    try:
        backends, models = [], []
        for i in range(num_engines):
            port = ports[i]
            procs.append(_launch(
                [sys.executable, "-m",
                 "production_stack_tpu.testing.fake_engine",
                 "--port", str(port), "--model", MODEL,
                 "--speed", str(speed), "--ttft", "0.02"], logf))
            backends.append(f"http://127.0.0.1:{port}")
            models.append(MODEL)
        router_cmd = [
            sys.executable, "-m", "production_stack_tpu.router.app",
            "--port", str(router_port),
            "--service-discovery", "static",
            "--static-backends", ",".join(backends),
            "--static-models", ",".join(models),
            "--routing-logic", policy,
            "--engine-stats-interval", "5",
        ]
        if policy == "session":
            router_cmd += ["--session-key", "x-user-id"]
        procs.append(_launch(router_cmd, logf))
        for b in backends:
            _wait_http(b + "/health")
        _wait_http(f"http://127.0.0.1:{router_port}/health")

        out = subprocess.run(
            [sys.executable, "benchmarks/multi_round_qa.py",
             "--base-url", f"http://127.0.0.1:{router_port}",
             "--model", MODEL,
             "--num-users", str(num_users),
             "--num-rounds", str(rounds),
             "--qps", str(qps),
             "--system-prompt-len", "100",
             "--chat-history-len", "100",
             "--answer-len", "50",
             "--seed", "0"],
            cwd=REPO, capture_output=True, text=True, timeout=600)
        # The summary is the last JSON object on stdout.
        tail = out.stdout.strip().splitlines()
        start = next(i for i, line in enumerate(tail)
                     if line.strip() == "{")
        summary = json.loads("\n".join(tail[start:]))
        summary.update(policy=policy, qps_target=qps,
                       num_engines=num_engines,
                       engine_speed_tok_s=speed)
        return summary
    finally:
        for p in procs:
            try:
                os.killpg(p.pid, signal.SIGTERM)
            except Exception:
                pass
        for p in procs:
            try:
                p.wait(timeout=5)
            except Exception:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                    p.wait(timeout=5)
                except Exception:
                    pass
        logf.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out-dir", default="benchmarks/results")
    args = ap.parse_args()

    if args.quick:
        policies, qps_values = ["roundrobin"], [4.0]
        num_users, rounds = 8, 2
    else:
        policies = ["roundrobin", "session", "llq", "hra",
                    "prefixaware", "custom"]
        qps_values = [2.0, 8.0, 16.0]
        num_users, rounds = 24, 3

    rows = []
    for policy in policies:
        for qps in qps_values:
            print(f"# {policy} @ {qps} qps ...", file=sys.stderr)
            rows.append(run_case(policy, qps, num_engines=4,
                                 speed=500, num_users=num_users,
                                 rounds=rounds))
            print(json.dumps(rows[-1]), file=sys.stderr)

    os.makedirs(args.out_dir, exist_ok=True)
    with open(os.path.join(args.out_dir, "router_overhead.json"),
              "w") as f:
        json.dump({"rows": rows,
                   "workload": {
                       "engines": 4, "engine_speed_tok_s": 500,
                       "engine_ttft_s": 0.02, "num_users": num_users,
                       "rounds": rounds, "answer_len": 50,
                   }}, f, indent=1)

    lines = [
        "# Router overhead baseline (fake engines, no accelerator)",
        "",
        "4 fake engines at 500 tok/s, 20 ms synthetic TTFT; "
        f"{num_users} users x {rounds} rounds, 100-token system "
        "prompt + growing history, 50-token answers. Engine-side "
        "floor: TTFT 0.02 s. Anything above that is queueing + "
        "router overhead.",
        "",
        "| policy | target QPS | achieved req/s | p50 TTFT (s) | "
        "p99 TTFT (s) | avg latency (s) | gen tok/s | errors |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['policy']} | {r['qps_target']} | "
            f"{r.get('req_per_s', '-')} | "
            f"{r.get('p50_ttft_s', '-')} | {r.get('p99_ttft_s', '-')} "
            f"| {r.get('avg_latency_s', '-')} | "
            f"{r.get('gen_tokens_per_s', '-')} | "
            f"{r.get('errors', 0)} |")
    with open(os.path.join(args.out_dir, "router_overhead.md"),
              "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))


if __name__ == "__main__":
    main()
