#!/bin/bash
# One-command on-chip round-up for a (possibly short) live-tunnel
# window. Phases are ORDERED BY VALUE-PER-MINUTE: the known-good XLA
# engine number and the layout-deciding decode probe land first, the
# Pallas validation/microbench and variants after, so an interrupted
# window still leaves the artifacts that matter most. Every phase runs
# in its own process with a hard timeout (a Mosaic hang must not wedge
# the harness — results/round3_onchip_notes.md), and artifacts land in
# benchmarks/results/ as soon as each phase finishes.
#
# Usage: bash benchmarks/chip_roundup.sh
cd "$(dirname "$0")/.." || exit 1
OUT="benchmarks/results"
STAMP=$(date -u +%Y%m%dT%H%M%S)
LOG="$OUT/chip_roundup_$STAMP"
mkdir -p "$OUT"

phase() { echo; echo "=== $1 ($(date -u +%H:%M:%S)) ==="; }

phase "0: tunnel sanity"
timeout -k 10 120 python -c "import jax; print('sanity', jax.device_get(jax.numpy.ones(4)+1))" || {
  echo "NO TUNNEL — aborting"; exit 1; }

phase "1: instrumented engine run (xla, stacked) — the reference point"
PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" timeout -k 30 1800 \
  python bench.py --worker xla+stacked --tpu \
  > "${LOG}_xla.json" 2> "${LOG}_xla.err"
echo "rc=$? headline:"; cat "${LOG}_xla.json"

phase "2: decode roofline probe (kv-writes + engine bursts, both layouts)"
timeout -k 30 2400 python benchmarks/decode_probe.py 2>&1 \
  | tee "${LOG}_decode_probe.log" | tail -10

phase "3: engine run (xla + per-layer cache pytree)"
# The round-3 decode-roofline experiment (round3_onchip_notes.md par 0.6).
PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" timeout -k 30 1800 \
  python bench.py --worker xla+per_layer --tpu \
  > "${LOG}_xla_pl.json" 2> "${LOG}_xla_pl.err"
echo "rc=$? headline:"; cat "${LOG}_xla_pl.json"

phase "4: kernel validation + microbench (gates the pallas runs)"
timeout -k 30 2400 bash benchmarks/chip_validate.sh 2>&1 | tee "${LOG}_validate.log" | tail -20

phase "5: instrumented engine run (pallas, stacked — aliasing fix)"
PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" timeout -k 30 1800 \
  python bench.py --worker pallas+stacked --tpu \
  > "${LOG}_pallas.json" 2> "${LOG}_pallas.err"
echo "rc=$? headline:"; cat "${LOG}_pallas.json"

phase "5b: engine run (pallas + per-layer cache pytree)"
PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" timeout -k 30 1800 \
  python bench.py --worker pallas+per_layer --tpu \
  > "${LOG}_pallas_pl.json" 2> "${LOG}_pallas_pl.err"
echo "rc=$? headline:"; cat "${LOG}_pallas_pl.json"

phase "6: north-star 8B config (int8, BASELINE config 2)"
# Bare impl = the serving default layout (auto -> per_layer).
PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" BENCH_MODEL=8b timeout -k 30 2400 \
  python bench.py --worker xla --tpu \
  > "${LOG}_8b.json" 2> "${LOG}_8b.err"
echo "rc=$? headline:"; cat "${LOG}_8b.json"

phase "7: per-phase timing decomposition"
python - "$LOG" <<'PYEOF'
import collections
import json
import re
import sys

log = sys.argv[1]
print(f"| impl | req/s | tok/s | mfu | decode burst avg | prefill512 avg |")
print(f"|---|---|---|---|---|---|")
for impl in ("xla", "xla_pl", "pallas", "pallas_pl", "8b"):
    agg = collections.defaultdict(lambda: [0, 0.0])
    try:
        for line in open(f"{log}_{impl}.err"):
            m = re.search(r"timing (\w+) t=(\d+) ([\d.]+)", line)
            if m:
                k = f"{m.group(1)}_t{m.group(2)}"
                agg[k][0] += 1
                agg[k][1] += float(m.group(3))
        head = json.load(open(f"{log}_{impl}.json"))
        e = head.get("extra", {})
        d = agg.get("decode_t32", [1, 0.0])
        p = agg.get("prefill_t512", [1, 0.0])
        print(f"| {impl} | {head.get('value')} "
              f"| {e.get('total_tokens_per_s')} | {e.get('mfu')} "
              f"| {d[1]/max(d[0],1)*1000:.0f} ms "
              f"| {p[1]/max(p[0],1)*1000:.0f} ms |")
    except Exception as ex:  # noqa: BLE001 — report, don't die
        print(f"| {impl} | (failed: {ex}) | | | | |")
PYEOF

phase "8: driver bench (full probe->fallback flow)"
timeout -k 30 3600 python bench.py > "${LOG}_driver.json" 2> "${LOG}_driver.err"
echo "rc=$? headline:"; cat "${LOG}_driver.json"

echo
echo "=== done; artifacts: ${LOG}_* ==="
echo "Next: set the engine defaults (attention impl + cache layout) to"
echo "the measured winners, refresh BASELINE.json round4_measured, run"
echo "benchmarks/chip_sweep.sh <winner>, and fold tables into"
echo "tutorials/07+08 and results/round4_onchip_notes.md."
