#!/bin/bash
# One-command on-chip round-up for a (possibly short) live-tunnel
# window: kernel validation + microbench, instrumented engine runs for
# BOTH attention impls, and the full driver bench. Every phase runs in
# its own process with a hard timeout (Mosaic hangs must not wedge the
# harness — see results/round3_onchip_notes.md), and each phase's
# artifacts land in benchmarks/results/ as soon as it finishes, so an
# interrupted run still leaves evidence.
#
# Usage: bash benchmarks/chip_roundup.sh
cd "$(dirname "$0")/.." || exit 1
OUT="benchmarks/results"
STAMP=$(date -u +%Y%m%dT%H%M%S)
LOG="$OUT/chip_roundup_$STAMP"
mkdir -p "$OUT"

phase() { echo; echo "=== $1 ($(date -u +%H:%M:%S)) ==="; }

phase "0: tunnel sanity"
timeout 120 python -c "import jax; print('sanity', jax.device_get(jax.numpy.ones(4)+1))" || {
  echo "NO TUNNEL — aborting"; exit 1; }

phase "1: kernel validation + microbench"
timeout 2400 bash benchmarks/chip_validate.sh 2>&1 | tee "${LOG}_validate.log" | tail -20

phase "2: instrumented engine run (pallas)"
PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" timeout 1800 \
  python bench.py --worker pallas --tpu \
  > "${LOG}_pallas.json" 2> "${LOG}_pallas.err"
echo "rc=$? headline:"; cat "${LOG}_pallas.json"

phase "3: instrumented engine run (xla)"
PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" timeout 1800 \
  python bench.py --worker xla --tpu \
  > "${LOG}_xla.json" 2> "${LOG}_xla.err"
echo "rc=$? headline:"; cat "${LOG}_xla.json"

phase "3b: instrumented engine run (xla + per-layer cache pytree)"
# The round-3 decode-roofline experiment (round3_onchip_notes.md par 0.6):
# per-layer cache buffers vs the stacked array. Decide on numbers.
PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" timeout 1800 \
  python bench.py --worker xla+per_layer --tpu \
  > "${LOG}_xla_pl.json" 2> "${LOG}_xla_pl.err"
echo "rc=$? headline:"; cat "${LOG}_xla_pl.json"

phase "3c: instrumented engine run (pallas + per-layer cache pytree)"
PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" timeout 1800 \
  python bench.py --worker pallas+per_layer --tpu \
  > "${LOG}_pallas_pl.json" 2> "${LOG}_pallas_pl.err"
echo "rc=$? headline:"; cat "${LOG}_pallas_pl.json"

phase "4: per-phase timing decomposition"
python - "$LOG" <<'PYEOF'
import collections
import json
import re
import sys

log = sys.argv[1]
print(f"| impl | req/s | tok/s | mfu | decode burst avg | prefill512 avg |")
print(f"|---|---|---|---|---|---|")
for impl in ("pallas", "xla", "xla_pl", "pallas_pl"):
    agg = collections.defaultdict(lambda: [0, 0.0])
    try:
        for line in open(f"{log}_{impl}.err"):
            m = re.search(r"timing (\w+) t=(\d+) ([\d.]+)", line)
            if m:
                k = f"{m.group(1)}_t{m.group(2)}"
                agg[k][0] += 1
                agg[k][1] += float(m.group(3))
        head = json.load(open(f"{log}_{impl}.json"))
        e = head.get("extra", {})
        d = agg.get("decode_t32", [1, 0.0])
        p = agg.get("prefill_t512", [1, 0.0])
        print(f"| {impl} | {head.get('value')} "
              f"| {e.get('total_tokens_per_s')} | {e.get('mfu')} "
              f"| {d[1]/max(d[0],1)*1000:.0f} ms "
              f"| {p[1]/max(p[0],1)*1000:.0f} ms |")
    except Exception as ex:  # noqa: BLE001 — report, don't die
        print(f"| {impl} | (failed: {ex}) | | | | |")
PYEOF

phase "4b: north-star 8B config (int8, BASELINE config 2)"
PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" BENCH_MODEL=8b timeout 2400 \
  python bench.py --worker xla --tpu \
  > "${LOG}_8b.json" 2> "${LOG}_8b.err"
echo "rc=$? headline:"; cat "${LOG}_8b.json"

phase "5: driver bench (full probe->fallback flow)"
timeout 3600 python bench.py > "${LOG}_driver.json" 2> "${LOG}_driver.err"
echo "rc=$? headline:"; cat "${LOG}_driver.json"

echo
echo "=== done; artifacts: ${LOG}_* ==="
echo "Next: pick the faster impl as the engine default, refresh"
echo "BASELINE.json round3_measured, and fold the table into"
echo "tutorials/07 + results/round3_onchip_notes.md."
