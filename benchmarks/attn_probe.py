"""Decode attention micro-probe: where do the ~5.9 ms/step go?

The round-5 ablation attributed ~5.9 of 11.1 ms/token-step to the
paged attention READ side (gather + softmax + AV) at the 1B bench
config — ~4.5x its ~1.3 ms HBM-traffic floor. This probe times ONE
layer's decode attention (chained K times in one program, honest RTT
protocol) across implementations to locate the overhead:

  gather_dps    page gather only ([kv, pages, d, ps] layout), summed
  attend_dps    full paged_attention (the served path)
  attend_tm     same math on a token-major [kv, pages, ps, d] cache
  attend_dense  per-row dense [B, ctx, kv, d] K/V (no page table):
                the no-gather upper bound
  attend_flat   gather flattened to [B, ctx, kv, d] then dense math
                (isolates einsum-on-gathered-shape vs gather itself)

ms are per chained invocation of ONE layer; multiply by 2*L mentally
(16 layers, K and V) only for the gather-traffic cases — the full
attention cases already read both K and V.

Run on a live chip:  python benchmarks/attn_probe.py
Artifact: benchmarks/results/attn_probe.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

B, NH, KV, D, PS, PAGES_PER_SEQ, NUM_PAGES, STEPS = (
    32, 32, 8, 64, 128, 8, 512, 32)


def build():
    import jax
    import jax.numpy as jnp

    rs = np.random.RandomState(0)
    k_dps = jnp.asarray(
        rs.randn(KV, NUM_PAGES, D, PS), jnp.bfloat16)
    v_dps = jnp.asarray(
        rs.randn(KV, NUM_PAGES, D, PS), jnp.bfloat16)
    k_tm = jnp.transpose(k_dps, (0, 1, 3, 2))  # [kv, pages, ps, d]
    v_tm = jnp.transpose(v_dps, (0, 1, 3, 2))
    pt = jnp.asarray(
        np.arange(1, B * PAGES_PER_SEQ + 1, dtype=np.int32)
        .reshape(B, PAGES_PER_SEQ))
    ctx = PAGES_PER_SEQ * PS
    # Dense per-row copies of the same values (parity-checkable).
    k_dense = jnp.transpose(
        k_dps[:, pt], (1, 2, 4, 0, 3)
    ).reshape(B, ctx, KV, D)
    v_dense = jnp.transpose(
        v_dps[:, pt], (1, 2, 4, 0, 3)).reshape(B, ctx, KV, D)
    q = jnp.asarray(rs.randn(B, 1, NH, D), jnp.bfloat16)
    q_pos = jnp.full((B, 1), ctx - 64, jnp.int32)
    kv_lens = jnp.full((B,), ctx - 63, jnp.int32)
    return (k_dps, v_dps, k_tm, v_tm, k_dense, v_dense, pt, q, q_pos,
            kv_lens)


def chain(step, xs_n=STEPS):
    """Run ``step`` STEPS times in one jitted program with the OUTPUT
    fed back into the next step's query.

    Two liveness guarantees, both load-bearing (the first version of
    this probe lacked them and produced a physically impossible
    negative ms/step on one leg — the scan body's work was sliced
    down to the single emitted element):
      - the full output contributes to the carried q, so no part of
        the per-step computation is dead;
      - each step's inputs depend on the previous step's output, so
        nothing loop-invariant about the attention math can be
        hoisted out of the scan (the page table is additionally
        rotated by i inside each case).
    """
    import jax
    import jax.numpy as jnp

    def body(q, i):
        out = step(q, i)  # [B,1,NH,D] (attend) or [B] (gather)
        if out.ndim == 1:
            contrib = out[:, None, None, None]
        else:
            contrib = out
        q_next = (q + contrib.astype(jnp.float32) * 1e-6).astype(
            q.dtype)
        return q_next, out.reshape(-1)[0]

    def prog(q):
        _, outs = jax.lax.scan(body, q, jax.numpy.arange(xs_n))
        return outs

    return jax.jit(prog)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out",
                    default="benchmarks/results/attn_probe.json")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from production_stack_tpu.ops.attention import (
        NEG_INF,
        paged_attention,
    )

    (k_dps, v_dps, k_tm, v_tm, k_dense, v_dense, pt, q, q_pos,
     kv_lens) = build()
    scale = 1.0 / float(np.sqrt(D))
    ctx = PAGES_PER_SEQ * PS
    rows = []

    # Every case takes (q, i): q is the chain-carried query (output
    # feedback — see chain()); the page table is rotated by i so the
    # gather itself is loop-variant and cannot be hoisted. At i=0 the
    # rotation is identity, so the parity checks compare like-for-like.
    def pt_i(i):
        return (pt + i) % NUM_PAGES

    # 1. gather only (one layer's K pages), reduced (the sum keeps
    # every gathered element live).
    def gather_dps(qq, i):
        k = k_dps[:, pt_i(i)]  # [kv, B, P, d, ps]
        return k.sum(axis=(0, 2, 3, 4))

    # 2. the served path.
    def attend_dps(qq, i):
        return paged_attention(qq, k_dps, v_dps, pt_i(i), q_pos,
                               kv_lens)

    # 3. token-major layout, same math in its native order.
    def attend_tm(qq, i):
        qg = qq.reshape(B, 1, KV, NH // KV, D)
        k = k_tm[:, pt_i(i)]  # [kv, B, P, ps, d]
        v = v_tm[:, pt_i(i)]
        scores = jnp.einsum(
            "btkgd,kbpcd->bkgtpc", qg, k,
            preferred_element_type=jnp.float32) * scale
        token_pos = (jnp.arange(PAGES_PER_SEQ)[:, None] * PS
                     + jnp.arange(PS)[None, :])
        mask = ((token_pos[None, None] <= q_pos[:, :, None, None])
                & (token_pos[None] < kv_lens[:, None, None])[:, None])
        scores = jnp.where(mask[:, None, None], scores, NEG_INF)
        shape = scores.shape
        probs = jax.nn.softmax(
            scores.reshape(*shape[:-2], -1), axis=-1).reshape(shape)
        out = jnp.einsum(
            "bkgtpc,kbpcd->btkgd", probs.astype(v.dtype), v,
            preferred_element_type=jnp.float32)
        return out.reshape(B, 1, NH, D).astype(qq.dtype)

    # 4. dense per-row K/V: the no-gather bound.
    def attend_dense(qq, i):
        qg = qq.reshape(B, 1, KV, NH // KV, D)
        scores = jnp.einsum(
            "btkgd,bckd->bkgtc", qg, k_dense,
            preferred_element_type=jnp.float32) * scale
        token_pos = jnp.arange(ctx)
        mask = ((token_pos[None, None] <= q_pos[:, :, None])
                & (token_pos[None] < kv_lens[:, None])[:, None])
        scores = jnp.where(mask[:, None, None], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum(
            "bkgtc,bckd->btkgd", probs.astype(v_dense.dtype), v_dense,
            preferred_element_type=jnp.float32)
        return out.reshape(B, 1, NH, D).astype(qq.dtype)

    # 5. gather, flatten to dense shape, then dense math.
    def attend_flat(qq, i):
        qg = qq.reshape(B, 1, KV, NH // KV, D)
        k = jnp.transpose(k_dps[:, pt_i(i)], (1, 2, 4, 0, 3)).reshape(
            B, ctx, KV, D)
        v = jnp.transpose(v_dps[:, pt_i(i)], (1, 2, 4, 0, 3)).reshape(
            B, ctx, KV, D)
        scores = jnp.einsum(
            "btkgd,bckd->bkgtc", qg, k,
            preferred_element_type=jnp.float32) * scale
        token_pos = jnp.arange(ctx)
        mask = ((token_pos[None, None] <= q_pos[:, :, None])
                & (token_pos[None] < kv_lens[:, None])[:, None])
        scores = jnp.where(mask[:, None, None], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum(
            "bkgtc,bckd->btkgd", probs.astype(v.dtype), v,
            preferred_element_type=jnp.float32)
        return out.reshape(B, 1, NH, D).astype(qq.dtype)

    cases = [("gather_dps", gather_dps), ("attend_dps", attend_dps),
             ("attend_tm", attend_tm), ("attend_dense", attend_dense),
             ("attend_flat", attend_flat)]

    # Numerical parity across implementations first (same inputs;
    # i=0 makes the table rotation the identity).
    ref = np.asarray(attend_dps(q, jnp.int32(0)), np.float32)
    for name, fn in cases[2:]:
        got = np.asarray(fn(q, jnp.int32(0)), np.float32)
        err = float(np.max(np.abs(got - ref)))
        print(f"# parity {name}: max|diff| = {err:.5f}")
        assert err < 0.1, (name, err)

    # Paired-length differencing: time an N-step and a 5N-step chain
    # and take (T5N - TN) / 4N. The constant per-dispatch cost (tunnel
    # RTT ~65 ms, host sync, scan setup) cancels EXACTLY — the first
    # version of this probe subtracted a "probed RTT" that re-fetched
    # an already-fetched buffer (0 ms), so every case carried ~RTT/N
    # of inflation and all five implementations read ~2.1 ms/step.
    n_lo, n_hi = STEPS, STEPS * 5
    for name, fn in cases:
        p_lo, p_hi = chain(fn, n_lo), chain(fn, n_hi)
        walls = {}
        for tag, prog in (("lo", p_lo), ("hi", p_hi)):
            jax.device_get(prog(q)[-1])  # compile + warm
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                jax.device_get(prog(q)[-1])
                best = min(best, time.perf_counter() - t0)
            walls[tag] = best
        per = (walls["hi"] - walls["lo"]) / (n_hi - n_lo)
        row = {"case": name,
               "ms_per_invocation": round(per * 1e3, 3),
               "wall_lo_ms": round(walls["lo"] * 1e3, 1),
               "wall_hi_ms": round(walls["hi"] * 1e3, 1)}
        rows.append(row)
        print(json.dumps(row), flush=True)

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump({"backend": jax.default_backend(),
                   "shape": {"B": B, "NH": NH, "KV": KV, "D": D,
                             "PS": PS, "P": PAGES_PER_SEQ,
                             "steps": STEPS},
                   "rows": rows}, f, indent=1)
    print(f"# wrote {args.out}")


if __name__ == "__main__":
    main()
