"""WildChat dataset preparation (parity: benchmarks/cleanup_wildchat.py).

Converts WildChat parquet shards (downloaded separately — this
environment and many clusters are egress-free, so no auto-download)
into the ShareGPT-style JSON the load generator replays, filtering by
token budget and round count.

  python benchmarks/prepare_wildchat.py --input wildchat/*.parquet \\
      --output wildchat_clean.json --max-tokens 4096 --min-rounds 2
"""

import argparse
import glob
import json

try:
    from benchmarks.prepare_sharegpt import count_tokens
except ImportError:  # run as a plain script from benchmarks/
    from prepare_sharegpt import count_tokens


def conversations_from_parquet(paths):
    import pandas as pd
    for path in paths:
        df = pd.read_parquet(path)
        for conv in df["conversation"]:
            turns = []
            for turn in conv:
                role = turn.get("role")
                content = turn.get("content") or ""
                if role not in ("user", "assistant") or not content:
                    continue
                turns.append({
                    "from": "human" if role == "user" else "gpt",
                    "value": content,
                })
            if turns:
                yield {"conversations": turns}


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--input", nargs="+", required=True,
                        help="WildChat parquet shard(s) or globs")
    parser.add_argument("--output", required=True)
    parser.add_argument("--max-tokens", type=int, default=4096)
    parser.add_argument("--min-rounds", type=int, default=2)
    parser.add_argument("--max-conversations", type=int, default=None)
    parser.add_argument("--tokenizer", default=None,
                        help="Local HF tokenizer path (optional)")
    args = parser.parse_args(argv)

    tokenizer = None
    if args.tokenizer:
        from production_stack_tpu.engine.tokenizer import HFTokenizer
        tokenizer = HFTokenizer(args.tokenizer)

    paths = []
    for pattern in args.input:
        paths.extend(sorted(glob.glob(pattern)) or [pattern])

    kept, seen = [], 0
    for entry in conversations_from_parquet(paths):
        seen += 1
        turns = entry["conversations"]
        human_turns = [t for t in turns if t["from"] == "human"]
        if len(human_turns) < args.min_rounds:
            continue
        total = sum(count_tokens(t["value"], tokenizer) for t in turns)
        if total > args.max_tokens:
            continue
        kept.append(entry)
        if (args.max_conversations
                and len(kept) >= args.max_conversations):
            break

    with open(args.output, "w") as f:
        json.dump(kept, f)
    print(f"Kept {len(kept)}/{seen} conversations")


if __name__ == "__main__":
    main()
