"""Decode-step ablation: attribute the per-token-step milliseconds.

Round-5 finding (results/round5_notes.md): widening the decode batch
LOWERS throughput (1B b32 11.07 -> b64 6.99 -> b128 4.26 req/s), so
the 13.5 ms/token-step at the served config is NOT weight-stream
bound — some per-row cost dominates. This probe attributes the step
by re-timing the real burst program with individual components
knocked out via monkeypatching the model's module globals (no product
code changes):

  full          the real body: forward + greedy sampling + feedback
  no_attn       paged_attention -> q (skip gather + softmax reads)
  no_kv_write   write_to_pages -> identity (skip the per-layer scatters)
  matmul_floor  both knocked out: weights/norms/rope/lm_head/sampling
  no_sample     full forward, sampling replaced by constant feedback
  deferred      the kv_tail burst body (read-only caches in the scan,
                one batched flush per layer at the end) — the served
                deferred_kv_writes path

All variants run b=32 rows x K chained steps in ONE compiled program
(lax.scan, caches donated) and are timed by PAIRED-LENGTH
DIFFERENCING: wall(K=160) - wall(K=32) over 128 steps, which cancels
the constant per-dispatch cost (tunnel RTT ~65 ms, host sync, scan
setup) exactly (docs/source/dev_guide/tpu_tunnel_runbook.md). Deltas
vs `full` give the attribution; `matmul_floor` is the measured
weights floor to compare against the analytic ~3-4 ms (853M bf16
params / 819 GB/s + lm_head).

Run on a live chip:  python benchmarks/decode_ablation.py
Artifact: benchmarks/results/decode_ablation.json + markdown stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Served bench config shapes; --tiny shrinks them for the CPU smoke.
BATCH = 32
BURST = 32
PROMPT = 512
PAGE_SIZE = 128
NUM_PAGES = 512
TINY = False


def build_state():
    """1B bench geometry, per_layer caches, b rows mid-generation."""
    import jax
    import jax.numpy as jnp

    from production_stack_tpu.engine.config import (
        bench_1b_model_config,
        tiny_model_config,
    )
    from production_stack_tpu.models import llama

    m = tiny_model_config("llama") if TINY else bench_1b_model_config()
    kv, d, ps, pages = (m.num_key_value_heads, m.head_dim,
                        PAGE_SIZE, NUM_PAGES)
    L = m.num_hidden_layers
    params = llama.init_params(m, jax.random.PRNGKey(0))
    k_cache = tuple(jnp.zeros((kv, pages, d, ps), m.jax_dtype)
                    for _ in range(L))
    v_cache = tuple(jnp.zeros((kv, pages, d, ps), m.jax_dtype)
                    for _ in range(L))
    rs = np.random.RandomState(0)
    # Page-table WIDTH must match the engine's (max_model_len /
    # page_size = 8 at the served config): the XLA gather reads every
    # table slot regardless of kv_lens, so width is a cost factor.
    if TINY:
        pages_per_seq = (PROMPT + BURST) // PAGE_SIZE + 2
    else:
        pages_per_seq = 1024 // PAGE_SIZE
    assert BATCH * pages_per_seq < pages
    pt = jnp.asarray(
        np.arange(1, BATCH * pages_per_seq + 1, dtype=np.int32)
        .reshape(BATCH, pages_per_seq))
    tokens = jnp.asarray(rs.randint(1, m.vocab_size - 1,
                                    size=(BATCH, 1)), jnp.int32)
    positions = jnp.full((BATCH, 1), PROMPT, jnp.int32)
    kv_lens = jnp.full((BATCH,), PROMPT + 1, jnp.int32)
    active = jnp.ones((BATCH,), bool)
    return m, params, k_cache, v_cache, tokens, positions, pt, kv_lens, active


def make_burst(m, variant: str, page_table, active):
    """The burst program for one ablation variant.

    Mirrors model_runner._decode_burst_impl's carry structure (token
    feedback, position/kv_len advance, donated caches) minus the
    lifecycle bookkeeping that is pure [B]-vector arithmetic.
    """
    import jax
    import jax.numpy as jnp

    from production_stack_tpu.models import llama
    from production_stack_tpu.ops.sampling import sample_tokens

    def sample(variant_tok, logits, step_rng):
        if variant == "no_sample":
            return variant_tok[:, 0]
        return sample_tokens(
            logits[:, 0, :], jnp.zeros((BATCH,)),
            jnp.ones((BATCH,)),
            jnp.zeros((BATCH,), jnp.int32), step_rng)

    def body(params, carry, step_rng):
        tok, pos, kvl, kc, vc = carry
        logits, kc, vc = llama.forward(
            params, m, tok, pos, page_table, kvl,
            active[:, None], kc, vc)
        sampled = sample(tok, logits, step_rng)
        return (sampled[:, None], pos + 1, kvl + 1, kc, vc), sampled

    def burst(params, tokens, positions, kv_lens, k_cache, v_cache,
              rng, num_steps):
        rngs = jax.random.split(rng, num_steps)
        carry = (tokens, positions, kv_lens, k_cache, v_cache)

        def scan_body(c, r):
            return body(params, c, r)

        (_, _, _, kc, vc), out = jax.lax.scan(scan_body, carry, rngs)
        return out, kc, vc

    def burst_deferred(params, tokens, positions, kv_lens, k_cache,
                       v_cache, rng, num_steps):
        """The served deferred path, at the SERVED tail width: chains
        of num_steps run as num_steps/BURST sequential BURST-wide
        bursts with a flush between each — tail width must NOT scale
        with the chain length or the paired-length differencing
        overstates tail-attention work that serving never does
        (mirrors model_runner._decode_burst_deferred_impl per burst).
        """
        from production_stack_tpu.ops.attention import write_to_pages

        assert num_steps % BURST == 0
        outs = []
        for chunk in range(num_steps // BURST):
            kv0 = positions[:, 0]
            tails = tuple(
                jnp.zeros((BATCH, BURST, m.num_key_value_heads,
                           m.head_dim), m.jax_dtype)
                for _ in range(m.num_hidden_layers))

            def dbody(carry, step_rng, kv0=kv0):
                tok, pos, kt, vt = carry
                logits, kt, vt = llama.forward(
                    params, m, tok, pos, page_table, kv0,
                    active[:, None], k_cache, v_cache,
                    kv_tail=(kt, vt))
                sampled = sample(tok, logits, step_rng)
                return (sampled[:, None], pos + 1, kt, vt), sampled

            rng, sub = jax.random.split(rng)
            rngs = jax.random.split(sub, BURST)
            (tokens, positions, kt, vt), out = jax.lax.scan(
                dbody, (tokens, positions, tails, tails), rngs)
            outs.append(out)
            tail_pos = kv0[:, None] + jnp.arange(BURST)[None, :]
            tail_valid = jnp.ones((BATCH, BURST), bool)
            k_cache = tuple(
                write_to_pages(c, kt[i], page_table, tail_pos,
                               tail_valid)
                for i, c in enumerate(k_cache))
            v_cache = tuple(
                write_to_pages(c, vt[i], page_table, tail_pos,
                               tail_valid)
                for i, c in enumerate(v_cache))
        return jnp.concatenate(outs, axis=0), k_cache, v_cache

    fn = burst_deferred if variant == "deferred" else burst
    return jax.jit(fn, donate_argnums=(4, 5), static_argnums=(7,))


def run_variant(variant: str):
    import jax.numpy as jnp

    from production_stack_tpu.models import llama

    orig_attn = llama.paged_attention
    orig_write = llama.write_to_pages
    try:
        if variant in ("no_attn", "matmul_floor"):
            llama.paged_attention = (
                lambda q, kc, vc, pt, pos, kl, layer=None: q)
        if variant in ("no_kv_write", "matmul_floor"):
            llama.write_to_pages = (
                lambda cache, new, pt, pos, valid, layer=None: cache)
        (m, params, k_cache, v_cache, tokens, positions, pt, kv_lens,
         active) = build_state()

        import jax

        # Paired-length differencing: (T_hi - T_lo) / (hi - lo) steps
        # cancels the constant per-dispatch cost exactly (tunnel RTT
        # ~65 ms — at burst 32 that masquerades as ~2 ms/step; the
        # first version of this probe under-measured its RTT by
        # re-fetching an already-fetched buffer).
        n_lo, n_hi = BURST, BURST * 5
        walls = {}
        burst = make_burst(m, variant, pt, active)
        # Donated caches thread through both chain lengths (contents
        # don't affect timing; re-donating avoids 2 GB copies/call).
        state = {"kc": k_cache, "vc": v_cache}
        for tag, n in (("lo", n_lo), ("hi", n_hi)):

            def fn():
                out, kc2, vc2 = burst(
                    params, tokens, positions, kv_lens,
                    state["kc"], state["vc"], jax.random.PRNGKey(1),
                    n)
                state["kc"], state["vc"] = kc2, vc2
                return out

            jax.device_get(fn()[-1])  # compile + warm
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                jax.device_get(fn()[-1])
                best = min(best, time.perf_counter() - t0)
            walls[tag] = best
        per = (walls["hi"] - walls["lo"]) / (n_hi - n_lo)
        return {
            "case": variant, "batch": BATCH,
            "burst_lo": n_lo, "burst_hi": n_hi,
            "ms_per_token_step": round(per * 1e3, 2),
            "wall_lo_ms": round(walls["lo"] * 1e3, 1),
            "wall_hi_ms": round(walls["hi"] * 1e3, 1),
        }
    finally:
        llama.paged_attention = orig_attn
        llama.write_to_pages = orig_write


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out",
                    default="benchmarks/results/decode_ablation.json")
    ap.add_argument("--variants", default=(
        "full,no_attn,no_kv_write,matmul_floor,no_sample"))
    ap.add_argument("--tiny", action="store_true",
                    help="tiny model + small shapes (CPU/CI smoke)")
    args = ap.parse_args(argv)
    if args.tiny:
        global BATCH, BURST, PROMPT, PAGE_SIZE, NUM_PAGES, TINY
        BATCH, BURST, PROMPT, PAGE_SIZE, NUM_PAGES, TINY = (
            2, 4, 16, 16, 32, True)

    import jax
    backend = jax.default_backend()
    rows = []
    for v in args.variants.split(","):
        try:
            rows.append(run_variant(v))
        except Exception as e:  # noqa: BLE001 — record, continue
            rows.append({"case": v, "error": repr(e)[:300]})
        print(json.dumps(rows[-1]), flush=True)

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump({"backend": backend, "batch": BATCH, "burst": BURST,
                   "rows": rows}, f, indent=1)
    print(f"# wrote {args.out}")


if __name__ == "__main__":
    main()
