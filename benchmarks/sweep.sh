#!/usr/bin/env bash
# QPS sweep driver (parity: reference benchmarks/run.sh / run_single.sh).
# Runs multi_round_qa.py at increasing offered QPS against a serving
# endpoint and collects one summary JSON per point.
#
# Usage: ./sweep.sh <base-url> <model> [output-dir]
set -euo pipefail

BASE_URL="${1:?usage: sweep.sh <base-url> <model> [output-dir]}"
MODEL="${2:?usage: sweep.sh <base-url> <model> [output-dir]}"
OUT="${3:-sweep-results}"
mkdir -p "$OUT"

# Reference workload shape (run.sh:14-60): long shared system prompt,
# growing per-user history, fixed answer length, rising QPS.
QPS_POINTS=(0.1 0.5 1.1 2.1 3.1 4.1)
NUM_USERS=20
NUM_ROUNDS=5
SYSTEM_PROMPT="${SWEEP_SYSTEM_PROMPT:-500}"   # words
CHAT_HISTORY="${SWEEP_CHAT_HISTORY:-200}"     # words
ANSWER_LEN="${SWEEP_ANSWER_LEN:-100}"

# Warmup: long-history users to populate caches (run.sh warmup phase).
python "$(dirname "$0")/multi_round_qa.py" \
  --base-url "$BASE_URL" --model "$MODEL" \
  --num-users 5 --num-rounds 2 --qps 2 \
  --system-prompt-len "$SYSTEM_PROMPT" \
  --chat-history-len "$CHAT_HISTORY" \
  --answer-len 16 > "$OUT/warmup.json"

for qps in "${QPS_POINTS[@]}"; do
  echo "=== sweep point qps=$qps ==="
  python "$(dirname "$0")/multi_round_qa.py" \
    --base-url "$BASE_URL" --model "$MODEL" \
    --num-users "$NUM_USERS" --num-rounds "$NUM_ROUNDS" \
    --qps "$qps" \
    --system-prompt-len "$SYSTEM_PROMPT" \
    --chat-history-len "$CHAT_HISTORY" \
    --answer-len "$ANSWER_LEN" \
    --output-csv "$OUT/qps_${qps}.csv" \
    | tee "$OUT/qps_${qps}.json"
done

python "$(dirname "$0")/plot_sweep.py" --dir "$OUT" || true
echo "Results in $OUT/"
