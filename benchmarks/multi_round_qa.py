"""Multi-round QA load generator (capability parity with reference
benchmarks/multi-round-qa.py:1-661, asyncio-native rebuild).

Simulated users arrive with lognormal inter-arrival gaps; each runs R
chat rounds against an OpenAI-compatible endpoint, replaying its growing
history, streaming the answer and recording TTFT (first chunk), ITL and
generation throughput. Session affinity and admission hints ride the
same headers the reference uses: ``x-user-id`` and ``x-prefill-tokens``.

Outputs a console summary + optional per-request CSV. ShareGPT mode
replays real conversations with optional length inflation.

Example:
  python benchmarks/multi_round_qa.py \\
      --base-url http://localhost:8001 --model tiny-llama \\
      --num-users 10 --num-rounds 3 --qps 1.0 --answer-len 64
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import time
from dataclasses import dataclass, field
from typing import List, Optional

import aiohttp


@dataclass
class RequestRecord:
    user_id: str
    round_idx: int
    start_time: float
    ttft: float = -1.0
    finish_time: float = -1.0
    prompt_tokens: int = 0
    gen_tokens: int = 0
    error: Optional[str] = None

    @property
    def latency(self) -> float:
        return self.finish_time - self.start_time

    @property
    def gen_time(self) -> float:
        return self.finish_time - (self.start_time + self.ttft)


@dataclass
class Workload:
    base_url: str
    model: str
    num_users: int = 10
    num_rounds: int = 3
    qps: float = 1.0  # user arrival rate
    system_prompt_len: int = 100  # words
    chat_history_len: int = 200  # words per round of context growth
    answer_len: int = 64  # max_tokens per round
    sharegpt_path: Optional[str] = None
    inflation_ratio: float = 0.0  # fraction of rounds inflated
    inflation_factor: int = 10
    ignore_eos: bool = True
    seed: int = 0


def _words(rng: random.Random, n: int) -> str:
    return " ".join(f"w{rng.randint(0, 9999)}" for _ in range(n))


class UserSession:
    def __init__(self, workload: Workload, user_id: str,
                 session: aiohttp.ClientSession,
                 records: List[RequestRecord],
                 conversation: Optional[List[dict]] = None):
        self.w = workload
        self.user_id = user_id
        self.http = session
        self.records = records
        self.rng = random.Random(hash(user_id) ^ workload.seed)
        self.messages: List[dict] = [{
            "role": "system",
            "content": _words(self.rng, workload.system_prompt_len),
        }]
        self.conversation = conversation  # ShareGPT turns, if any

    def _next_question(self, round_idx: int) -> str:
        if self.conversation is not None:
            text = self.conversation[
                round_idx % len(self.conversation)
            ]
        else:
            text = _words(self.rng, self.w.chat_history_len)
        if (self.w.inflation_ratio > 0
                and self.rng.random() < self.w.inflation_ratio):
            text = " ".join([text] * self.w.inflation_factor)
        return text

    async def run(self) -> None:
        for round_idx in range(self.w.num_rounds):
            self.messages.append({
                "role": "user",
                "content": self._next_question(round_idx),
            })
            record = RequestRecord(
                user_id=self.user_id, round_idx=round_idx,
                start_time=time.time(),
            )
            self.records.append(record)
            prefill_estimate = sum(
                len(m["content"].split()) for m in self.messages
            ) * 2  # crude words->tokens
            record.prompt_tokens = prefill_estimate
            try:
                answer = await self._stream_round(
                    record, prefill_estimate
                )
                self.messages.append(
                    {"role": "assistant", "content": answer}
                )
            except Exception as e:
                record.error = str(e)
                record.finish_time = time.time()
                return

    async def _stream_round(self, record: RequestRecord,
                            prefill_estimate: int) -> str:
        payload = {
            "model": self.w.model,
            "messages": self.messages,
            "max_tokens": self.w.answer_len,
            "stream": True,
            "temperature": 0.0,
        }
        if self.w.ignore_eos:
            payload["ignore_eos"] = True
        headers = {
            "x-user-id": self.user_id,
            "x-prefill-tokens": str(prefill_estimate),
        }
        pieces: List[str] = []
        async with self.http.post(
            f"{self.w.base_url}/v1/chat/completions",
            json=payload, headers=headers,
            timeout=aiohttp.ClientTimeout(total=600),
        ) as resp:
            if resp.status != 200:
                raise RuntimeError(
                    f"HTTP {resp.status}: {(await resp.text())[:200]}"
                )
            async for raw_line in resp.content:
                line = raw_line.decode().strip()
                if not line.startswith("data: "):
                    continue
                data = line[len("data: "):]
                if data == "[DONE]":
                    break
                try:
                    chunk = json.loads(data)
                except json.JSONDecodeError:
                    continue
                delta = chunk["choices"][0].get("delta", {})
                content = delta.get("content")
                if content:
                    if record.ttft < 0:
                        record.ttft = time.time() - record.start_time
                    record.gen_tokens += 1
                    pieces.append(content)
        record.finish_time = time.time()
        if record.ttft < 0:  # no content chunks (very short answers)
            record.ttft = record.finish_time - record.start_time
        return "".join(pieces)


async def run_benchmark(workload: Workload) -> List[RequestRecord]:
    records: List[RequestRecord] = []
    rng = random.Random(workload.seed)
    conversations = None
    if workload.sharegpt_path:
        with open(workload.sharegpt_path) as f:
            conversations = json.load(f)

    async with aiohttp.ClientSession() as http:
        tasks = []
        for i in range(workload.num_users):
            conv = None
            if conversations:
                entry = conversations[i % len(conversations)]
                conv = [t["value"] for t in entry.get(
                    "conversations", []
                ) if t.get("from") == "human"] or ["hello"]
            user = UserSession(
                workload, f"user-{i}", http, records, conv
            )
            tasks.append(asyncio.create_task(user.run()))
            # Lognormal inter-arrival gaps with mean 1/qps (matches the
            # reference's arrival process shape).
            if workload.qps > 0 and i < workload.num_users - 1:
                mean_gap = 1.0 / workload.qps
                gap = rng.lognormvariate(0, 0.5)
                await asyncio.sleep(gap * mean_gap / 1.13)  # E[ln N]
        await asyncio.gather(*tasks)
    return records


def summarize(records: List[RequestRecord],
              wall_time: float) -> dict:
    ok = [r for r in records if r.error is None and r.finish_time > 0]
    errors = [r for r in records if r.error is not None]
    if not ok:
        return {"completed": 0, "errors": len(errors)}
    ttfts = sorted(r.ttft for r in ok)
    latencies = sorted(r.latency for r in ok)
    gen_tokens = sum(r.gen_tokens for r in ok)
    prompt_tokens = sum(r.prompt_tokens for r in ok)

    def pct(values, p):
        return values[min(len(values) - 1, int(p * len(values)))]

    return {
        "completed": len(ok),
        "errors": len(errors),
        "wall_time_s": round(wall_time, 2),
        "req_per_s": round(len(ok) / wall_time, 3),
        "avg_ttft_s": round(sum(ttfts) / len(ttfts), 4),
        "p50_ttft_s": round(pct(ttfts, 0.50), 4),
        "p90_ttft_s": round(pct(ttfts, 0.90), 4),
        "p99_ttft_s": round(pct(ttfts, 0.99), 4),
        "avg_latency_s": round(
            sum(latencies) / len(latencies), 4),
        "gen_tokens_per_s": round(gen_tokens / wall_time, 1),
        "prompt_tokens_per_s": round(prompt_tokens / wall_time, 1),
        "avg_gen_throughput_per_req": round(
            sum(r.gen_tokens / max(r.gen_time, 1e-6) for r in ok)
            / len(ok), 1),
    }


def write_csv(records: List[RequestRecord], path: str) -> None:
    import csv
    with open(path, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow([
            "user_id", "round", "start_time", "ttft", "latency",
            "prompt_tokens", "gen_tokens", "error",
        ])
        for r in records:
            writer.writerow([
                r.user_id, r.round_idx, r.start_time, r.ttft,
                r.latency if r.finish_time > 0 else -1,
                r.prompt_tokens, r.gen_tokens, r.error or "",
            ])


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--base-url", required=True)
    parser.add_argument("--model", required=True)
    parser.add_argument("--num-users", type=int, default=10)
    parser.add_argument("--num-rounds", type=int, default=3)
    parser.add_argument("--qps", type=float, default=1.0)
    parser.add_argument("--system-prompt-len", type=int, default=100)
    parser.add_argument("--chat-history-len", type=int, default=200)
    parser.add_argument("--answer-len", type=int, default=64)
    parser.add_argument("--sharegpt", default=None)
    parser.add_argument("--inflation-ratio", type=float, default=0.0)
    parser.add_argument("--inflation-factor", type=int, default=10)
    parser.add_argument("--no-ignore-eos", action="store_true")
    parser.add_argument("--output-csv", default=None)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--log-wandb", action="store_true",
                        help="Stream the summary to Weights & Biases "
                             "(the fork's router-sidecar mode, reference "
                             "deployment-router.yaml:24-63); no-op if "
                             "wandb is not installed")
    parser.add_argument("--wandb-project", default="tpu-stack-bench")
    args = parser.parse_args(argv)

    workload = Workload(
        base_url=args.base_url.rstrip("/"),
        model=args.model,
        num_users=args.num_users,
        num_rounds=args.num_rounds,
        qps=args.qps,
        system_prompt_len=args.system_prompt_len,
        chat_history_len=args.chat_history_len,
        answer_len=args.answer_len,
        sharegpt_path=args.sharegpt,
        inflation_ratio=args.inflation_ratio,
        inflation_factor=args.inflation_factor,
        ignore_eos=not args.no_ignore_eos,
        seed=args.seed,
    )
    t0 = time.time()
    records = asyncio.run(run_benchmark(workload))
    summary = summarize(records, time.time() - t0)
    print(json.dumps(summary, indent=2))
    if args.output_csv:
        write_csv(records, args.output_csv)
    if args.log_wandb:
        try:
            import wandb
        except ImportError:
            print("wandb not installed; skipping --log-wandb")
        else:
            run = wandb.init(project=args.wandb_project,
                             config=vars(args))
            run.log(summary)
            run.finish()
    return summary


if __name__ == "__main__":
    main()
