"""Plot one benchmark run's per-request CSV (parity:
benchmarks/plot_single.py in the reference): TTFT and latency
distributions + tokens/s over time.

  python benchmarks/plot_single.py bench.csv --output bench.png
"""

import argparse
import csv


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("csv_path")
    parser.add_argument("--output", default="bench_single.png")
    args = parser.parse_args(argv)

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = list(csv.DictReader(open(args.csv_path)))
    if not rows:
        raise SystemExit("empty CSV")
    ttft = [float(r["ttft"]) for r in rows if r.get("ttft")]
    latency = [float(r["latency"]) for r in rows if r.get("latency")]
    start = [float(r["start_time"]) for r in rows]
    t0 = min(start)

    fig, axes = plt.subplots(1, 3, figsize=(15, 4))
    axes[0].hist(ttft, bins=30)
    axes[0].set_title("TTFT (s)")
    axes[1].hist(latency, bins=30)
    axes[1].set_title("Request latency (s)")
    axes[2].scatter([s - t0 for s in start], ttft, s=8)
    axes[2].set_title("TTFT over run")
    axes[2].set_xlabel("time since start (s)")
    fig.tight_layout()
    fig.savefig(args.output, dpi=120)
    print(f"wrote {args.output}")


if __name__ == "__main__":
    main()
