#!/bin/bash
# Engine-side QPS sweep on the live chip (VERDICT r3 task 5): starts
# the real TPU engine server with the bench-grade config, runs
# sweep.sh against it, and lands curves + plots in
# benchmarks/results/engine_sweep/. Run AFTER chip_roundup.sh (which
# decides the attention impl default); pass the winner as $1.
#
# Usage: bash benchmarks/chip_sweep.sh [xla|pallas|auto] [extra args]
set -uo pipefail
cd "$(dirname "$0")/.." || exit 1
IMPL="${1:-auto}"
# SWEEP_OUT: land a variant run elsewhere (e.g. engine_sweep_deferred)
# without clobbering the committed default curve.
OUT="${SWEEP_OUT:-benchmarks/results/engine_sweep}"
mkdir -p "$OUT"
# Pick a free port: the dev tunnel's relay squats much of 8082-8117
# (observed 2026-07-31: an 8093 collision sent the whole sweep to the
# relay — every request 404'd). Start high and verify.
PORT="${SWEEP_PORT:-8923}"
for _try in $(seq 1 100); do
  python - "$PORT" <<'EOF'
import socket, sys
s = socket.socket()
try:
    s.bind(("127.0.0.1", int(sys.argv[1])))
except OSError:
    sys.exit(7)   # taken
s.close(); sys.exit(0)  # free
EOF
  rc=$?
  [ "$rc" -eq 0 ] && break
  [ "$rc" -ne 7 ] && { echo "port probe broke (rc=$rc)"; exit 1; }
  PORT=$((PORT + 1))
done
[ "$rc" -eq 0 ] || { echo "no free port in 100 tries"; exit 1; }
echo "sweep server port: $PORT"

# max-model-len 2048 (not the bench.py 1024): 5 rounds of growing
# byte-tokenized history reach ~1.8k tokens by round 4 (the first
# sweep attempt 400'd rounds 2-4 at 1024). 768 pages = 3 GB KV
# alongside the ~2.4 GB bf16 model; 32 seqs x 16 pages/seq = 512
# worst-case concurrent demand fits with headroom for prefix reuse.
python -m production_stack_tpu.engine.server \
  --model bench-1b --random-weights --port "$PORT" \
  --page-size 128 --num-pages 768 --max-num-seqs 32 \
  --max-model-len 2048 --prefill-chunk-size 512 \
  --prefill-batch-size 8 --decode-steps 32 \
  --attention-impl "$IMPL" \
  > "$OUT/server.log" 2>&1 &
SERVER_PID=$!
trap 'kill $SERVER_PID 2>/dev/null' EXIT

# Compile warmup can take minutes on the tunnel; poll generously.
for i in $(seq 1 120); do
  curl -s --max-time 2 "http://127.0.0.1:$PORT/health" >/dev/null 2>&1 \
    && break
  sleep 5
done
curl -s --max-time 5 "http://127.0.0.1:$PORT/health" >/dev/null || {
  echo "engine server did not come up; tail of log:"
  tail -20 "$OUT/server.log"; exit 1; }

# Byte-level encoding: ~5-7 tokens/word, so the reference's 500-word
# system prompt alone would approach the 2048-token model len. Use a
# byte-budget-scaled workload (same shape: ~600-token system prompt,
# history growing to ~1.8k tokens by round 4 — inside the window).
SWEEP_SYSTEM_PROMPT=80 SWEEP_CHAT_HISTORY=30 SWEEP_ANSWER_LEN=64 \
  bash benchmarks/sweep.sh "http://127.0.0.1:$PORT" bench-1b "$OUT"
echo "=== engine sweep done; commit $OUT and fold the table into"
echo "    tutorials/08 + BASELINE.json ==="
