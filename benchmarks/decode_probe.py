"""Decode-step roofline probe: where do the ms/step go?

Round-3 finding (results/round3_onchip_notes.md §0.6): XLA decode at
the 1B bench config measured ~42 ms/token-step vs a ~5 ms weights-
bound roofline — ~34 GB of traffic/step ≈ one full-cache copy per
layer. This probe isolates the burst body's cost on the chip across
the factors that could explain it, using the honest tunnel timing
protocol (chain N invocations in ONE compiled program, sync once,
subtract min-probed RTT — block_until_ready is unreliable here):

  1. forward-only, single decode step (stacked vs per_layer caches)
  2. forward+sampling chained K steps under lax.scan — the real
     _decode_burst_impl via the engine's jit, both layouts
  3. KV-write-only step (the round-3 16x pathology's isolated form)

Run on a live chip:  python benchmarks/decode_probe.py
Artifacts: benchmarks/results/decode_probe.json + markdown to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _rtt_timer():
    import jax

    def sync(o):
        jax.device_get(o)

    def measure(fn, out_probe, repeats=3):
        """min wall time of fn() followed by one sync, minus RTT."""
        out = fn()
        sync(out_probe(out))
        rtt = float("inf")
        probe = out_probe(out)
        for _ in range(3):
            t0 = time.perf_counter()
            sync(probe)
            rtt = min(rtt, time.perf_counter() - t0)
        samples = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = fn()
            sync(out_probe(out))
            total = time.perf_counter() - t0
            if total > rtt:
                samples.append(total - rtt)
        return (min(samples) if samples else 0.0), rtt

    return measure


def probe_engine(layout: str, impl: str, burst: int = 32):
    """Build the bench engine and time one real decode burst dispatch."""
    import jax

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        SchedulerConfig,
        bench_1b_model_config,
    )
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import (
        SamplingParams,
        SequenceState,
    )

    config = EngineConfig(
        model=bench_1b_model_config(),
        cache=CacheConfig(page_size=128, num_pages=512,
                          cache_layout=layout),
        scheduler=SchedulerConfig(max_num_seqs=32, max_model_len=1024,
                                  prefill_chunk_size=512,
                                  prefill_batch_size=8,
                                  decode_steps=burst),
    )
    config.model.attention_impl = impl
    engine = LLMEngine(config)
    rs = np.random.RandomState(0)
    seqs = []
    for i in range(32):
        prompt = [int(x) for x in rs.randint(
            1, config.model.vocab_size - 1, size=512)]
        sid = engine.add_request(prompt, SamplingParams(
            max_tokens=burst * 4, temperature=0.0, ignore_eos=True))
        seqs.append(engine.sequences[sid])
    # Prefill everything (and compile the burst) before timing.
    while any(s.num_computed_tokens < s.num_prompt_tokens
              for s in seqs):
        engine.step()
    engine.step()  # one burst: compile + warm

    t0 = time.perf_counter()
    engine.step()
    wall = time.perf_counter() - t0
    alive = sum(s.state not in (SequenceState.FINISHED,) for s in seqs)
    return {
        "case": f"engine_burst_{impl}_{layout}",
        "burst": burst, "batch": 32, "alive_rows": alive,
        "wall_s_per_burst": round(wall, 4),
        "ms_per_token_step": round(wall / burst * 1e3, 2),
    }


def probe_kv_write(layout: str):
    """Isolated per-layer KV write cost (the round-3 16x pathology)."""
    import jax
    import jax.numpy as jnp

    from production_stack_tpu.engine.config import bench_1b_model_config
    from production_stack_tpu.ops.attention import write_to_pages

    m = bench_1b_model_config()
    L, kv, d, ps, pages = (m.num_hidden_layers,
                           m.num_key_value_heads, m.head_dim, 128, 512)
    b = 32
    rng = np.random.RandomState(0)
    new_kv = jnp.asarray(rng.randn(b, 1, kv, d), m.jax_dtype)
    pt = jnp.asarray(
        np.arange(1, b * 8 + 1, dtype=np.int32).reshape(b, 8))
    pos = jnp.full((b, 1), 17, jnp.int32)
    valid = jnp.ones((b, 1), bool)

    measure = _rtt_timer()
    if layout == "per_layer":
        caches = tuple(jnp.zeros((kv, pages, d, ps), m.jax_dtype)
                       for _ in range(L))

        @jax.jit
        def step(caches, new_kv):
            return tuple(
                write_to_pages(c, new_kv, pt, pos, valid)
                for c in caches)

        arg = caches

        def run():
            return step(arg, new_kv)

        def out_probe(o):
            return o[0][0, 0, 0, 0]
    else:
        cache = jnp.zeros((L, kv, pages, d, ps), m.jax_dtype)

        @jax.jit
        def step(cache, new_kv):
            for layer in range(L):
                cache = write_to_pages(cache, new_kv, pt, pos, valid,
                                       layer=layer)
            return cache

        arg = cache

        def run():
            return step(arg, new_kv)

        def out_probe(o):
            return o[0, 0, 0, 0, 0]

    wall, rtt = measure(run, out_probe)
    return {"case": f"kv_write_all_layers_{layout}",
            "wall_ms": round(wall * 1e3, 3),
            "rtt_ms": round(rtt * 1e3, 1)}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out",
                    default="benchmarks/results/decode_probe.json")
    ap.add_argument("--quick", action="store_true",
                    help="kv-write probes only (CI smoke)")
    args = ap.parse_args(argv)

    import jax
    rows = []
    backend = jax.default_backend()
    for layout in ("stacked", "per_layer"):
        rows.append(probe_kv_write(layout))
        print(json.dumps(rows[-1]), flush=True)
    if not args.quick:
        for layout in ("stacked", "per_layer"):
            for impl in ("xla", "pallas"):
                try:
                    rows.append(probe_engine(layout, impl))
                except Exception as e:  # noqa: BLE001 — record, go on
                    rows.append({
                        "case": f"engine_burst_{impl}_{layout}",
                        "error": repr(e)[:300]})
                print(json.dumps(rows[-1]), flush=True)

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump({"backend": backend, "rows": rows}, f, indent=1)
    print(f"# wrote {args.out}")


if __name__ == "__main__":
    main()
