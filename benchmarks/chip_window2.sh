#!/bin/bash
# Second chip-window plan for round 5 (run by the tunnel watcher on
# the first successful probe after the 01:27 UTC re-wedge). Ordered
# by value-per-minute given what window 1 already banked
# (results/round5_notes.md): the 8B north star has never produced a
# number, so it goes first; then the QPS sweep, the driver-flow
# check, and kernel parity. Every phase is a subprocess under
# `timeout -k` (a Mosaic hang must not take the harness down).
#
# Usage: bash benchmarks/chip_window2.sh
cd "$(dirname "$0")/.." || exit 1
OUT="benchmarks/results"
STAMP=$(date -u +%Y%m%dT%H%M%S)
LOG="$OUT/chip_window2_$STAMP"
mkdir -p "$OUT"

phase() { echo; echo "=== $1 ($(date -u +%H:%M:%S)) ==="; }

phase "0: tunnel sanity"
timeout -k 10 120 python -c "import jax; print('sanity', jax.device_get(jax.numpy.ones(4)+1))" || {
  echo "NO TUNNEL — aborting"; exit 1; }

phase "1: north-star 8B (int8, direct-int8 init, per_layer cache)"
# The host-side init is ~2 min; budget generously.
PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" BENCH_MODEL=8b \
  BENCH_IMPLS=xla timeout -k 30 3000 \
  python bench.py > "${LOG}_8b.json" 2> "${LOG}_8b.err"
echo "rc=$? headline:"; cat "${LOG}_8b.json"

phase "2: engine QPS sweep (xla winner config)"
timeout -k 60 5400 bash benchmarks/chip_sweep.sh xla 2>&1 \
  | tee "${LOG}_sweep.log" | tail -15

phase "3: driver-flow bench (new defaults: xla + per_layer)"
timeout -k 30 3600 python bench.py > "${LOG}_driver.json" \
  2> "${LOG}_driver.err"
echo "rc=$? headline:"; cat "${LOG}_driver.json"

phase "4: kernel parity validation (fixed PYTHONPATH)"
VALIDATE_SKIP_MICROBENCH=1 timeout -k 30 1200 \
  bash benchmarks/chip_validate.sh 2>&1 \
  | tee "${LOG}_validate.log" | tail -8

echo
echo "=== done; artifacts: ${LOG}_* ==="
