#!/bin/bash
# Page-size sweep (round 5, after the decode ablation attributed the
# step to paged-KV gather ~5.9 ms + scatter ~5.1 ms at page_size 128
# — indexing overhead, not bandwidth: the written bytes are ~1 MB and
# the gather's theoretical cost ~1.3 ms). Bigger pages mean fewer,
# larger contiguous slices per row: at 1024 (= max_model_len) the
# page table is one entry wide and the gather is a single 1 MB slice
# per row per layer. Trade-off: prefix-cache sharing granularity
# coarsens (the bench's 128-token shared prefix stops hitting above
# ps=128) — measured here, decided on numbers.
#
# KV capacity held at 64k tokens per cell: pages = 65536 / page_size.
#
# Usage: bash benchmarks/chip_pagesize.sh
cd "$(dirname "$0")/.." || exit 1
OUT="benchmarks/results"
STAMP=$(date -u +%Y%m%dT%H%M%S)
LOG="$OUT/pagesize_$STAMP"
mkdir -p "$OUT"

phase() { echo; echo "=== $1 ($(date -u +%H:%M:%S)) ==="; }

phase "0: tunnel sanity"
timeout -k 10 120 python -c "import jax; print('sanity', jax.device_get(jax.numpy.ones(4)+1))" || {
  echo "NO TUNNEL — aborting"; exit 1; }

for ps in 256 512 1024; do
  phase "1B page_size=$ps"
  env PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" BENCH_IMPLS=xla \
      BENCH_PAGE_SIZE="$ps" BENCH_NUM_PAGES="$((65536 / ps))" \
      timeout -k 30 2400 \
      python bench.py > "${LOG}_ps${ps}.json" 2> "${LOG}_ps${ps}.err"
  echo "rc=$? headline:"; cat "${LOG}_ps${ps}.json"
done

echo
echo "=== done; artifacts: ${LOG}_* ==="
