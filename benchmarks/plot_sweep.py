"""Plot TTFT / throughput vs offered QPS from sweep.sh outputs
(parity: reference benchmarks/plot_pretty.py / plot_single.py)."""

import argparse
import glob
import json
import os


def load_points(directory):
    points = []
    for path in sorted(glob.glob(os.path.join(directory, "qps_*.json"))):
        qps = float(
            os.path.basename(path)[len("qps_"):-len(".json")]
        )
        with open(path) as f:
            summary = json.load(f)
        if summary.get("completed"):
            points.append((qps, summary))
    return points


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--dir", default="sweep-results")
    parser.add_argument("--output", default=None)
    args = parser.parse_args(argv)

    points = load_points(args.dir)
    if not points:
        print("No sweep results found in", args.dir)
        return

    print(f"{'QPS':>6} {'req/s':>8} {'p50 TTFT':>10} "
          f"{'p90 TTFT':>10} {'gen tok/s':>10}")
    for qps, s in points:
        print(f"{qps:>6} {s['req_per_s']:>8} {s['p50_ttft_s']:>10} "
              f"{s['p90_ttft_s']:>10} {s['gen_tokens_per_s']:>10}")

    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        print("(matplotlib unavailable; table only)")
        return

    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4))
    xs = [p[0] for p in points]
    ax1.plot(xs, [p[1]["p50_ttft_s"] for p in points],
             marker="o", label="p50")
    ax1.plot(xs, [p[1]["p90_ttft_s"] for p in points],
             marker="s", label="p90")
    ax1.set_xlabel("offered QPS")
    ax1.set_ylabel("TTFT (s)")
    ax1.legend()
    ax1.grid(alpha=0.3)
    ax2.plot(xs, [p[1]["gen_tokens_per_s"] for p in points],
             marker="o")
    ax2.set_xlabel("offered QPS")
    ax2.set_ylabel("generation tokens/s")
    ax2.grid(alpha=0.3)
    out = args.output or os.path.join(args.dir, "sweep.png")
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print("Plot saved to", out)


if __name__ == "__main__":
    main()
