"""ShareGPT dataset preparation (parity: benchmarks/cleanup_sharegpt.py).

Filters a ShareGPT JSON dump to conversations whose turns fit a token
budget, using whitespace-token counts (no tokenizer download needed) or
an HF tokenizer from a local path.

  python benchmarks/prepare_sharegpt.py --input sharegpt.json \\
      --output sharegpt_clean.json --max-tokens 4096 --min-rounds 2
"""

import argparse
import json


def count_tokens(text: str, tokenizer=None) -> int:
    if tokenizer is not None:
        return len(tokenizer.encode(text))
    return max(1, int(len(text.split()) * 1.3))


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--input", required=True)
    parser.add_argument("--output", required=True)
    parser.add_argument("--max-tokens", type=int, default=4096)
    parser.add_argument("--min-rounds", type=int, default=2)
    parser.add_argument("--max-conversations", type=int, default=None)
    parser.add_argument("--tokenizer", default=None,
                        help="Local HF tokenizer path (optional)")
    args = parser.parse_args(argv)

    tokenizer = None
    if args.tokenizer:
        from production_stack_tpu.engine.tokenizer import HFTokenizer
        tokenizer = HFTokenizer(args.tokenizer)

    with open(args.input) as f:
        data = json.load(f)

    kept = []
    for entry in data:
        turns = entry.get("conversations", [])
        human_turns = [t for t in turns if t.get("from") == "human"]
        if len(human_turns) < args.min_rounds:
            continue
        total = sum(
            count_tokens(t.get("value", ""), tokenizer) for t in turns
        )
        if total > args.max_tokens:
            continue
        kept.append(entry)
        if (args.max_conversations
                and len(kept) >= args.max_conversations):
            break

    with open(args.output, "w") as f:
        json.dump(kept, f)
    print(f"Kept {len(kept)}/{len(data)} conversations")


if __name__ == "__main__":
    main()
