"""Attention kernel microbenchmark: Pallas page-walk vs XLA gather.

Times the decode and prefill attention implementations in isolation on
the current backend (intended for the real TPU chip) across batch and
context length — the per-kernel evidence VERDICT round 2 asked for
("kernel-vs-XLA microbench table, B=8-32, 2-16k ctx"). Page size is
pinned to the engine's 128 (one full lane tile per page; Mosaic
rejects smaller minor-dim slices of an HBM ref).

Writes a JSON table to ``--out`` (default
benchmarks/results/kernel_microbench.json) and prints a markdown table.

Usage:
    python benchmarks/kernel_microbench.py            # full sweep
    python benchmarks/kernel_microbench.py --quick    # CI smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_state(b, ctx, page_size, kv_heads, head_dim, max_ctx,
                dtype):
    """Random cache + page tables for ``b`` sequences of ``ctx`` tokens."""
    import jax.numpy as jnp
    max_pages_per_seq = -(-max_ctx // page_size)
    num_pages = b * max_pages_per_seq + 2
    rng = np.random.RandomState(0)
    # Token-minor page layout, matching the engine and both kernels
    # (ops/attention.py: [kv_heads, num_pages, head_dim, page_size]).
    kc = jnp.asarray(
        rng.randn(kv_heads, num_pages, head_dim, page_size),
        dtype)
    vc = jnp.asarray(
        rng.randn(kv_heads, num_pages, head_dim, page_size),
        dtype)
    pt = np.zeros((b, max_pages_per_seq), np.int32)
    nxt = 1
    for i in range(b):
        for j in range(-(-ctx // page_size)):
            pt[i, j] = nxt
            nxt += 1
    kl = np.full((b,), ctx, np.int32)
    return kc, vc, jnp.asarray(pt), jnp.asarray(kl)


def _time(step, x0, args=(), *, iters=64, warmup=1, repeats=3):
    """Per-invocation device time of ``step`` (a shape-preserving fn).

    ``block_until_ready`` is unreliable on the tunneled device (it can
    return before execution finishes) and a host sync costs a ~65 ms
    round trip — both swamp a µs-scale kernel. So the kernel is
    chained ``iters`` times *inside one compiled program* (each
    iteration feeds its output back as the next query, so nothing can
    be DCE'd or overlapped away) and the whole program is synced once
    with a device_get reduction; the measured RTT of that sync is
    subtracted. Min over ``repeats`` suppresses residual jitter. See
    benchmarks/results/round3_onchip_notes.md §2.
    """
    import jax
    import jax.numpy as jnp

    # The KV caches must be ARGUMENTS, not closure constants: closed-
    # over arrays are embedded in the serialized program, and a
    # multi-hundred-MB cache blows up the tunnel's remote-compile
    # request (HTTP 413).
    @jax.jit
    def chained(x, *rest):
        def body(_, xc):
            return step(xc, *rest)
        return jnp.sum(
            jax.lax.fori_loop(0, iters, body, x).astype(jnp.float32))

    def sync(o):
        jax.device_get(o)

    out = None
    for _ in range(warmup):
        out = chained(x0, *args)
    sync(out)
    # RTT of a sync on already-ready data: min over several probes so
    # one spike can't overestimate it (an overestimated rtt biases the
    # subtraction low, and min-over-repeats would lock that in).
    rtt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        sync(out)
        rtt = min(rtt, time.perf_counter() - t0)
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = chained(x0, *args)
        sync(out)
        total = time.perf_counter() - t0
        if total > rtt:  # discard repeats swallowed by RTT jitter
            samples.append((total - rtt) / iters)
    # Fall back to a 0.1 µs floor only if every repeat was smaller
    # than the sync round trip (compute too tiny to resolve).
    return min(samples) if samples else 1e-7


def bench_decode(b, ctx, page_size, *, kv_heads=8, q_heads=32,
                 head_dim=64, max_ctx=None, iters=20):
    import jax.numpy as jnp
    from production_stack_tpu.ops.attention import paged_attention
    from production_stack_tpu.ops.paged_attention_pallas import (
        paged_decode_attention,
    )
    max_ctx = max_ctx or ctx
    dtype = jnp.bfloat16
    kc, vc, pt, kl = _make_state(
        b, ctx, page_size, kv_heads, head_dim, max_ctx, dtype)
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, q_heads, head_dim), dtype)

    # Both paths run inside one compiled program (as in the engine's
    # jitted forward); the output feeds back as the next query.
    t_pallas = _time(
        lambda x, kc, vc, pt, kl: paged_decode_attention(
            x, kc, vc, pt, kl),
        q, (kc, vc, pt, kl), iters=iters)
    t_xla = _time(
        lambda x, kc, vc, pt, kl: paged_attention(
            x[:, None], kc, vc, pt, (kl - 1)[:, None], kl)[:, 0],
        q, (kc, vc, pt, kl), iters=iters)
    return t_pallas, t_xla


def bench_prefill(b, t, prior_ctx, page_size, *, kv_heads=8,
                  q_heads=32, head_dim=64, max_ctx=None, iters=20):
    import jax.numpy as jnp
    from production_stack_tpu.ops.attention import paged_attention
    from production_stack_tpu.ops.prefill_attention_pallas import (
        paged_prefill_attention,
    )
    ctx = prior_ctx + t
    max_ctx = max_ctx or ctx
    dtype = jnp.bfloat16
    kc, vc, pt, kl = _make_state(
        b, ctx, page_size, kv_heads, head_dim, max_ctx, dtype)
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, t, q_heads, head_dim), dtype)
    pos = jnp.asarray(
        np.broadcast_to(
            np.arange(prior_ctx, prior_ctx + t, dtype=np.int32)[None],
            (b, t)).copy())

    t_pallas = _time(
        lambda x, *r: paged_prefill_attention(x, *r),
        q, (kc, vc, pt, pos, kl), iters=iters)
    t_xla = _time(
        lambda x, *r: paged_attention(x, *r),
        q, (kc, vc, pt, pos, kl), iters=iters)
    return t_pallas, t_xla


def bench_ragged(r, w, mix, page_size, *, kv_heads=8, q_heads=32,
                 head_dim=64, int8=False, iters=20):
    """Fused ragged kernel vs the XLA gather at a mixed-row shape.

    ``mix = (decode_rows, verify_rows, prefill_rows, decode_ctx,
    prefill_prior)``; remaining rows are pads (kv_lens 0), matching
    the unified planner's common case of a lightly mixed step. Verify
    rows carry a 3-draft span. The XLA side runs ops.attention
    .paged_attention over the same [r, w] block with the positions the
    composed path materializes — exactly what _unified_impl composed
    before the fused kernel. The model runner's empirical 'auto' gate
    (_ragged_microbench_verdict) reads these rows (kind == 'ragged')
    and serves the kernel only when every measured cell wins.
    """
    import jax.numpy as jnp
    from production_stack_tpu.ops.attention import paged_attention
    from production_stack_tpu.ops.ragged_attention_pallas import (
        paged_ragged_attention,
    )
    n_dec, n_ver, n_pre, dec_ctx, pre_prior = mix
    span = 4  # 1 committed + 3 drafts on verify rows
    kv = np.zeros((r,), np.int32)
    li = np.zeros((r,), np.int32)
    dl = np.zeros((r,), np.int32)
    i = 0
    for _ in range(n_dec):
        kv[i], li[i] = dec_ctx, 0
        i += 1
    for _ in range(n_ver):
        kv[i], li[i], dl[i] = dec_ctx + span - 1, span - 1, span - 1
        i += 1
    for _ in range(n_pre):
        kv[i], li[i] = pre_prior + w, w - 1
        i += 1

    max_ctx = int(kv.max())
    max_pages_per_seq = -(-max_ctx // page_size)
    num_pages = r * max_pages_per_seq + 2
    rng = np.random.RandomState(0)
    dtype = jnp.bfloat16
    kc = jnp.asarray(
        rng.randn(kv_heads, num_pages, head_dim, page_size), dtype)
    vc = jnp.asarray(
        rng.randn(kv_heads, num_pages, head_dim, page_size), dtype)
    if int8:
        from production_stack_tpu.ops.quant_kv import (
            QuantKV,
            quantize_kv,
        )

        def _q(c):
            qc, scale = quantize_kv(jnp.transpose(c, (0, 1, 3, 2)))
            return QuantKV(jnp.transpose(qc, (0, 1, 3, 2)), scale)

        kc, vc = _q(kc), _q(vc)
    pt = np.zeros((r, max_pages_per_seq), np.int32)
    nxt = 1
    for row in range(r):
        for j in range(-(-int(kv[row]) // page_size)):
            pt[row, j] = nxt
            nxt += 1
    # The engine's layout invariant recovers each row's first query
    # position (docs/unified_step.md).
    pos = np.maximum(
        (kv - 1 - li)[:, None] + np.arange(w, dtype=np.int32)[None],
        0).astype(np.int32)
    pt, pos = jnp.asarray(pt), jnp.asarray(pos)
    kv, li, dl = map(jnp.asarray, (kv, li, dl))
    q = jnp.asarray(rng.randn(r, w, q_heads, head_dim), dtype)

    t_pallas = _time(
        lambda x, kc, vc, pt, kv, li, dl: paged_ragged_attention(
            x, kc, vc, pt, kv, li, dl),
        q, (kc, vc, pt, kv, li, dl), iters=iters)
    t_xla = _time(
        lambda x, kc, vc, pt, pos, kv: paged_attention(
            x, kc, vc, pt, pos, kv),
        q, (kc, vc, pt, pos, kv), iters=iters)
    return t_pallas, t_xla


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="tiny sweep (CI smoke)")
    ap.add_argument("--out",
                    default="benchmarks/results/kernel_microbench.json")
    args = ap.parse_args()

    import jax
    try:
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/jax-comp-cache")
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    device = jax.devices()[0]
    print(f"# backend: {jax.default_backend()} "
          f"({device.device_kind})")

    rows = []
    # Page size is fixed at 128: the v2 kernels DMA whole token-minor
    # pages, whose minor dim must be a full 128-lane tile (Mosaic
    # rejects smaller slices of an HBM ref). The engine serves with
    # page_size=128 for the same reason.
    if args.quick:
        decode_cases = [(8, 512, 128)]
        prefill_cases = [(4, 128, 0, 128)]
        ragged_cases = [(4, 128, (2, 1, 1, 96, 0), 128, False)]
        iters = 3
    else:
        decode_cases = [
            (b, ctx, 128)
            for b, ctx in ((8, 512), (8, 2048), (16, 2048),
                           (32, 2048), (32, 8192), (8, 16384))
        ]
        prefill_cases = [
            (b, t, prior, 128)
            for b, t, prior in ((4, 512, 0), (4, 512, 1536),
                                (8, 512, 1536), (4, 512, 7680),
                                (1, 512, 15872))
        ]
        # Mixed-row shapes the unified planner actually emits
        # (docs/unified_step.md): mostly-decode steps with one or two
        # chunks riding along, with and without verify spans, bf16
        # AND int8 (one kernel serves both caches).
        ragged_cases = [
            (r, w, mix, 128, int8)
            for r, w, mix in (
                (8, 128, (6, 0, 1, 2048, 1536)),
                (8, 128, (4, 2, 1, 2048, 1536)),
                (16, 512, (12, 0, 2, 4096, 3584)),
                (16, 512, (8, 4, 2, 8192, 7680)),
            )
            for int8 in (False, True)
        ]
        iters = 256

    for b, ctx, ps in decode_cases:
        t_pal, t_xla = bench_decode(b, ctx, ps, iters=iters)
        rows.append({
            "kind": "decode", "batch": b, "ctx": ctx,
            "page_size": ps, "pallas_us": round(t_pal * 1e6, 1),
            "xla_us": round(t_xla * 1e6, 1),
            "speedup": round(t_xla / t_pal, 2),
        })
        print(rows[-1])
    for b, t, prior, ps in prefill_cases:
        t_pal, t_xla = bench_prefill(b, t, prior, ps, iters=iters)
        rows.append({
            "kind": "prefill", "batch": b, "chunk": t,
            "prior_ctx": prior, "page_size": ps,
            "pallas_us": round(t_pal * 1e6, 1),
            "xla_us": round(t_xla * 1e6, 1),
            "speedup": round(t_xla / t_pal, 2),
        })
        print(rows[-1])
    for r, w, mix, ps, int8 in ragged_cases:
        t_pal, t_xla = bench_ragged(r, w, mix, ps, int8=int8,
                                    iters=iters)
        rows.append({
            "kind": "ragged", "rows": r, "width": w,
            "mix": "dec%d/ver%d/pre%d" % mix[:3],
            "ctx": mix[3], "page_size": ps,
            "kv_dtype": "int8" if int8 else "bf16",
            "pallas_us": round(t_pal * 1e6, 1),
            "xla_us": round(t_xla * 1e6, 1),
            "speedup": round(t_xla / t_pal, 2),
        })
        print(rows[-1])

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump({
            "backend": jax.default_backend(),
            "device_kind": device.device_kind,
            "notes": (
                "Per-kernel device time vs the XLA gather path "
                "(speedup = xla_us / pallas_us). Consumed by the "
                "model runner's empirical 'auto' gates: decode rows "
                "retired the decode kernel (PALLAS_DECODE_IN_AUTO); "
                "ragged rows (kind='ragged', the fused unified-step "
                "kernel, bf16 + int8 kv_dtype) gate "
                "attention_impl_unified resolution — 'auto' serves "
                "the fused kernel only when backend=='tpu' and every "
                "ragged cell wins (_ragged_microbench_verdict)."),
            "rows": rows,
        }, f, indent=1)
    print(f"# wrote {args.out}")

    # Markdown table for the docs.
    print("\n| kind | B/R | ctx/chunk | page | pallas µs | xla µs | "
          "xla/pallas |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        ctx = r.get("ctx", f"{r.get('chunk')}+{r.get('prior_ctx')}")
        if r["kind"] == "ragged":
            ctx = f"{r['mix']}@w{r['width']} ({r['kv_dtype']})"
        b = r.get("batch", r.get("rows"))
        print(f"| {r['kind']} | {b} | {ctx} | "
              f"{r['page_size']} | {r['pallas_us']} | {r['xla_us']} | "
              f"{r['speedup']} |")


if __name__ == "__main__":
    main()
