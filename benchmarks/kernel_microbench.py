"""Attention kernel microbenchmark: Pallas page-walk vs XLA gather.

Times the decode and prefill attention implementations in isolation on
the current backend (intended for the real TPU chip) across batch,
context length, and page size — the per-kernel evidence VERDICT round 2
asked for ("kernel-vs-XLA microbench table, B=8-32, 2-16k ctx").

Writes a JSON table to ``--out`` (default
benchmarks/results/kernel_microbench.json) and prints a markdown table.

Usage:
    python benchmarks/kernel_microbench.py            # full sweep
    python benchmarks/kernel_microbench.py --quick    # CI smoke
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_state(b, ctx, page_size, kv_heads, head_dim, max_ctx,
                dtype):
    """Random cache + page tables for ``b`` sequences of ``ctx`` tokens."""
    import jax.numpy as jnp
    max_pages_per_seq = -(-max_ctx // page_size)
    num_pages = b * max_pages_per_seq + 2
    rng = np.random.RandomState(0)
    kc = jnp.asarray(
        rng.randn(kv_heads, num_pages, page_size, head_dim),
        dtype)
    vc = jnp.asarray(
        rng.randn(kv_heads, num_pages, page_size, head_dim),
        dtype)
    pt = np.zeros((b, max_pages_per_seq), np.int32)
    nxt = 1
    for i in range(b):
        for j in range(-(-ctx // page_size)):
            pt[i, j] = nxt
            nxt += 1
    kl = np.full((b,), ctx, np.int32)
    return kc, vc, jnp.asarray(pt), jnp.asarray(kl)


def _time(fn, *args, iters=20, warmup=3):
    import jax
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def bench_decode(b, ctx, page_size, *, kv_heads=8, q_heads=32,
                 head_dim=64, max_ctx=None, iters=20):
    import jax
    import jax.numpy as jnp
    from production_stack_tpu.ops.attention import paged_attention
    from production_stack_tpu.ops.paged_attention_pallas import (
        paged_decode_attention,
    )
    max_ctx = max_ctx or ctx
    dtype = jnp.bfloat16
    kc, vc, pt, kl = _make_state(
        b, ctx, page_size, kv_heads, head_dim, max_ctx, dtype)
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, q_heads, head_dim), dtype)

    # Jit BOTH paths: in the engine each runs inside the jitted
    # forward — timing the XLA path eagerly would charge it per-op
    # dispatch overhead it never pays in serving.
    xla = jax.jit(lambda q, kc, vc, pt, kl: paged_attention(
        q[:, None], kc, vc, pt, (kl - 1)[:, None], kl))
    t_pallas = _time(
        lambda: paged_decode_attention(q, kc, vc, pt, kl),
        iters=iters)
    t_xla = _time(lambda: xla(q, kc, vc, pt, kl), iters=iters)
    return t_pallas, t_xla


def bench_prefill(b, t, prior_ctx, page_size, *, kv_heads=8,
                  q_heads=32, head_dim=64, max_ctx=None, iters=20):
    import jax.numpy as jnp
    from production_stack_tpu.ops.attention import paged_attention
    from production_stack_tpu.ops.prefill_attention_pallas import (
        paged_prefill_attention,
    )
    ctx = prior_ctx + t
    max_ctx = max_ctx or ctx
    dtype = jnp.bfloat16
    kc, vc, pt, kl = _make_state(
        b, ctx, page_size, kv_heads, head_dim, max_ctx, dtype)
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, t, q_heads, head_dim), dtype)
    pos = jnp.asarray(
        np.broadcast_to(
            np.arange(prior_ctx, prior_ctx + t, dtype=np.int32)[None],
            (b, t)).copy())

    import jax
    xla = jax.jit(paged_attention)
    t_pallas = _time(
        lambda: paged_prefill_attention(q, kc, vc, pt, pos, kl),
        iters=iters)
    t_xla = _time(lambda: xla(q, kc, vc, pt, pos, kl), iters=iters)
    return t_pallas, t_xla


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="tiny sweep (CI smoke)")
    ap.add_argument("--out",
                    default="benchmarks/results/kernel_microbench.json")
    args = ap.parse_args()

    import jax
    device = jax.devices()[0]
    print(f"# backend: {jax.default_backend()} "
          f"({device.device_kind})")

    rows = []
    if args.quick:
        decode_cases = [(8, 512, 16)]
        prefill_cases = [(4, 128, 0, 16)]
        iters = 3
    else:
        decode_cases = [
            (b, ctx, ps)
            for ps in (16, 64, 128)
            for b, ctx in ((8, 512), (8, 2048), (16, 2048),
                           (32, 2048), (32, 8192), (8, 16384))
        ]
        prefill_cases = [
            (b, t, prior, ps)
            for ps in (16, 64, 128)
            for b, t, prior in ((4, 512, 0), (4, 512, 1536),
                                (8, 512, 1536), (4, 512, 7680),
                                (1, 512, 15872))
        ]
        iters = 20

    for b, ctx, ps in decode_cases:
        t_pal, t_xla = bench_decode(b, ctx, ps, iters=iters)
        rows.append({
            "kind": "decode", "batch": b, "ctx": ctx,
            "page_size": ps, "pallas_us": round(t_pal * 1e6, 1),
            "xla_us": round(t_xla * 1e6, 1),
            "speedup": round(t_xla / t_pal, 2),
        })
        print(rows[-1])
    for b, t, prior, ps in prefill_cases:
        t_pal, t_xla = bench_prefill(b, t, prior, ps, iters=iters)
        rows.append({
            "kind": "prefill", "batch": b, "chunk": t,
            "prior_ctx": prior, "page_size": ps,
            "pallas_us": round(t_pal * 1e6, 1),
            "xla_us": round(t_xla * 1e6, 1),
            "speedup": round(t_xla / t_pal, 2),
        })
        print(rows[-1])

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump({
            "backend": jax.default_backend(),
            "device_kind": device.device_kind,
            "rows": rows,
        }, f, indent=1)
    print(f"# wrote {args.out}")

    # Markdown table for the docs.
    print("\n| kind | B | ctx/chunk | page | pallas µs | xla µs | "
          "xla/pallas |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        ctx = r.get("ctx", f"{r.get('chunk')}+{r.get('prior_ctx')}")
        print(f"| {r['kind']} | {r['batch']} | {ctx} | "
              f"{r['page_size']} | {r['pallas_us']} | {r['xla_us']} | "
              f"{r['speedup']} |")


if __name__ == "__main__":
    main()
