#!/usr/bin/env bash
# Router-overhead perf rig (parity: reference src/tests/perftest/*):
# N fake engines at a configurable token rate, the router in front,
# multi-round load through it. Measures pure router overhead with zero
# accelerators.
#
# Usage: ./router_perftest.sh [num-engines] [speed-tok/s] [qps]
set -euo pipefail

N="${1:-4}"
SPEED="${2:-500}"
QPS="${3:-5}"
MODEL="perf/model"
BASE_PORT=9100
ROUTER_PORT=8201
DIR="$(cd "$(dirname "$0")/.." && pwd)"
cd "$DIR"

PIDS=()
cleanup() { kill "${PIDS[@]}" 2>/dev/null || true; }
trap cleanup EXIT

BACKENDS=""
MODELS=""
for i in $(seq 0 $((N - 1))); do
  port=$((BASE_PORT + i))
  python -m production_stack_tpu.testing.fake_engine \
    --port "$port" --model "$MODEL" --speed "$SPEED" --ttft 0.02 &
  PIDS+=($!)
  BACKENDS+="http://127.0.0.1:${port},"
  MODELS+="${MODEL},"
done

python -m production_stack_tpu.router.app --port "$ROUTER_PORT" \
  --service-discovery static \
  --static-backends "${BACKENDS%,}" \
  --static-models "${MODELS%,}" \
  --routing-logic session --session-key x-user-id \
  --engine-stats-interval 5 &
PIDS+=($!)
sleep 3

python benchmarks/multi_round_qa.py \
  --base-url "http://127.0.0.1:${ROUTER_PORT}" --model "$MODEL" \
  --num-users 20 --num-rounds 3 --qps "$QPS" \
  --system-prompt-len 100 --chat-history-len 100 --answer-len 50
