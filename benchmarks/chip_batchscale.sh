#!/bin/bash
# Decode batch-scaling study (round 5, after window 2 banked the 8B
# north star + QPS sweep). Rationale: on-chip decode at batch 32 is
# weights-bound-ish (13.5 ms/token-step vs a ~3-4 ms HBM roofline,
# results/decode_probe.json) — widening the decode batch amortizes
# the per-step weight read over more sequences, so tok/s should scale
# well below linearly in cost. Each phase is one bench.py worker at a
# wider max_num_seqs (fresh compile per width: decode batch is a
# static program shape). 8B last: its compile is the expensive one.
#
# Usage: bash benchmarks/chip_batchscale.sh
cd "$(dirname "$0")/.." || exit 1
OUT="benchmarks/results"
STAMP=$(date -u +%Y%m%dT%H%M%S)
LOG="$OUT/batchscale_$STAMP"
mkdir -p "$OUT"

phase() { echo; echo "=== $1 ($(date -u +%H:%M:%S)) ==="; }

phase "0: tunnel sanity"
timeout -k 10 120 python -c "import jax; print('sanity', jax.device_get(jax.numpy.ones(4)+1))" || {
  echo "NO TUNNEL — aborting"; exit 1; }

run_cell() {  # name, extra env as K=V args
  local name="$1"; shift
  phase "1B $name"
  env PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" BENCH_IMPLS=xla \
      "$@" timeout -k 30 2400 \
      python bench.py > "${LOG}_${name}.json" 2> "${LOG}_${name}.err"
  echo "rc=$? headline:"; cat "${LOG}_${name}.json"
}

run_cell b64  BENCH_MAX_SEQS=64  BENCH_N_REQUESTS=96
run_cell b128 BENCH_MAX_SEQS=128 BENCH_NUM_PAGES=640 BENCH_N_REQUESTS=192

phase "8B batch 32 (vs banked batch 16)"
env PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" BENCH_MODEL=8b \
    BENCH_IMPLS=xla BENCH_MAX_SEQS=32 BENCH_N_REQUESTS=48 \
    timeout -k 30 3000 \
    python bench.py > "${LOG}_8b_b32.json" 2> "${LOG}_8b_b32.err"
echo "rc=$? headline:"; cat "${LOG}_8b_b32.json"

echo
echo "=== done; artifacts: ${LOG}_* ==="
