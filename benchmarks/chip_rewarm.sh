#!/bin/bash
# Post-recovery re-warm (run by chip_watch.sh when the tunnel comes
# back): one driver-flow bench.py run with the served defaults. Two
# purposes: (1) confirms the recovered tunnel serves the full engine
# path end-to-end; (2) re-populates the XLA compile cache so the
# driver's end-of-round bench compiles warm (a fresh heavy compile is
# the observed wedge trigger — round5_notes.md). Nothing else: after
# a wedge the tunnel is left ALONE for the driver.
cd "$(dirname "$0")/.." || exit 1
OUT="benchmarks/results"
STAMP=$(date -u +%Y%m%dT%H%M%S)
env PSTPU_TIMING=1 BENCH_DEVICE_KIND="TPU v5 lite" \
  timeout -k 30 3600 python bench.py \
  > "$OUT/rewarm_${STAMP}.json" 2> "$OUT/rewarm_${STAMP}.err"
echo "rc=$?"; cat "$OUT/rewarm_${STAMP}.json"
