#!/bin/bash
# Phased on-chip validation; each phase in its own process + timeout
# so a Mosaic hang can't wedge the whole run. Exits non-zero if any
# phase fails or hangs.
cd "$(dirname "$0")/.." || exit 1
REPO="$(pwd)"
FAILED=0

echo "=== phase 0: sanity ==="
timeout 120 python -c "import jax; print('sanity', jax.device_get(jax.numpy.ones(4)+1))" || exit 1

echo "=== phase 1: decode kernel compile+parity ==="
PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" timeout 420 python - <<'PYEOF'
import time
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/jax-comp-cache")
from production_stack_tpu.ops.attention import paged_attention
from production_stack_tpu.ops.paged_attention_pallas import paged_decode_attention
rng = np.random.RandomState(0)
nh, nkv, d, page, npages = 32, 8, 64, 128, 512
kc = jnp.asarray(rng.randn(nkv, npages, d, page), jnp.float32).astype(jnp.bfloat16)
vc = jnp.asarray(rng.randn(nkv, npages, d, page), jnp.float32).astype(jnp.bfloat16)
b, maxp = 8, 8
pt = np.zeros((b, maxp), np.int32); kl = np.zeros((b,), np.int32)
nxt = 1
for i in range(b):
    n = rng.randint(400, maxp*page); kl[i] = n
    for j in range(-(-n // page)): pt[i, j] = nxt; nxt += 1
q = jnp.asarray(rng.randn(b, nh, d), jnp.float32).astype(jnp.bfloat16)
pt_, kl_ = jnp.asarray(pt), jnp.asarray(kl)
t0 = time.time()
out = paged_decode_attention(q, kc, vc, pt_, kl_)
host = jax.device_get(out)
print("decode compiled+ran in %.1fs" % (time.time()-t0))
ref = paged_attention(q[:, None], kc, vc, pt_, (kl_-1)[:, None], kl_)[:, 0]
err = float(jnp.max(jnp.abs(out.astype(jnp.float32)-ref.astype(jnp.float32))))
assert err < 0.05, err
print("DECODE OK err=%.4f" % err)
PYEOF
if [ $? -ne 0 ]; then echo "DECODE KERNEL FAILED/HUNG"; FAILED=1; fi

echo "=== phase 2: prefill kernel compile+parity ==="
PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" timeout 420 python - <<'PYEOF'
import time
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/jax-comp-cache")
from production_stack_tpu.ops.attention import paged_attention
from production_stack_tpu.ops.prefill_attention_pallas import paged_prefill_attention
rng = np.random.RandomState(0)
nh, nkv, d, page, npages = 32, 8, 64, 128, 512
kc = jnp.asarray(rng.randn(nkv, npages, d, page), jnp.float32).astype(jnp.bfloat16)
vc = jnp.asarray(rng.randn(nkv, npages, d, page), jnp.float32).astype(jnp.bfloat16)
b, t, maxp = 4, 512, 8
pt = np.zeros((b, maxp), np.int32); kl = np.zeros((b,), np.int32)
pos = np.zeros((b, t), np.int32); nxt = 1
for i in range(b):
    prior = int(rng.randint(0, 4)) * 128
    kl[i] = prior + t
    for j in range(-(-int(kl[i]) // page)): pt[i, j] = nxt; nxt += 1
    pos[i] = np.arange(prior, prior + t)
q = jnp.asarray(rng.randn(b, t, nh, d), jnp.float32).astype(jnp.bfloat16)
pt_, kl_, pos_ = jnp.asarray(pt), jnp.asarray(kl), jnp.asarray(pos)
t0 = time.time()
out = paged_prefill_attention(q, kc, vc, pt_, pos_, kl_)
host = jax.device_get(out)
print("prefill compiled+ran in %.1fs" % (time.time()-t0))
ref = paged_attention(q, kc, vc, pt_, pos_, kl_)
err = float(jnp.max(jnp.abs(out.astype(jnp.float32)-ref.astype(jnp.float32))))
assert err < 0.05, err
print("PREFILL OK err=%.4f" % err)
PYEOF
if [ $? -ne 0 ]; then echo "PREFILL KERNEL FAILED/HUNG"; FAILED=1; fi

if [ -z "${VALIDATE_SKIP_MICROBENCH:-}" ]; then
  echo "=== phase 3: kernel microbench ==="
  timeout 1500 python benchmarks/kernel_microbench.py
  if [ $? -ne 0 ]; then echo "MICROBENCH FAILED/HUNG"; FAILED=1; fi
fi

echo "=== done (failed=$FAILED) ==="
exit $FAILED
