#!/bin/bash
# Standing tunnel watcher (docs/source/dev_guide/tpu_tunnel_runbook.md):
# probe every 4 minutes with the canonical probe; on the first success
# run the script given as $1 (default: benchmarks/chip_window2.sh),
# then exit. Committed (rather than living in /tmp) because session
# restarts kill background processes — whoever resumes relaunches:
#
#   nohup bash benchmarks/chip_watch.sh benchmarks/chip_batchscale.sh \
#     > /tmp/chip_watch.log 2>&1 &
cd "$(dirname "$0")/.." || exit 1
TARGET="${1:-benchmarks/chip_window2.sh}"
MAX_PROBES="${MAX_PROBES:-400}"   # ~26 h at 4-min cadence

for i in $(seq 1 "$MAX_PROBES"); do
  echo "[watch] probe $i/$MAX_PROBES ($(date -u +%H:%M:%S))"
  if timeout -k 10 120 python -c "
import jax
d = jax.devices(); assert d and d[0].platform == 'tpu', d
import jax.numpy as jnp
print(float(jax.device_get((jnp.ones((8,8))@jnp.ones((8,8))).sum())))
" 2>/dev/null; then
    echo "[watch] TUNNEL UP ($(date -u +%H:%M:%S)) — running $TARGET"
    bash "$TARGET"
    echo "[watch] target done ($(date -u +%H:%M:%S))"
    exit 0
  fi
  sleep 240
done
echo "[watch] gave up after $MAX_PROBES probes"
exit 1
