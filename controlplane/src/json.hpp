// Minimal JSON parse/serialize for the control-plane agent.
//
// Parity note: the reference operator (Go) marshals its DynamicConfig with
// encoding/json (src/router-controller/internal/controller/
// staticroute_controller.go:146-150). We need the same round-trip in C++
// with zero external dependencies, so this header implements the subset of
// JSON the agent exchanges with the router and the Kubernetes API:
// objects, arrays, strings (with escapes), numbers, booleans, null.
#pragma once

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace cpjson {

class Value;
using ValuePtr = std::shared_ptr<Value>;

enum class Type { Null, Bool, Number, String, Array, Object };

class Value {
 public:
  Type type = Type::Null;
  bool boolean = false;
  double number = 0.0;
  std::string str;
  std::vector<ValuePtr> arr;
  // std::map keeps keys sorted -> deterministic serialization, which the
  // reconciler relies on for change detection via content digests.
  std::map<std::string, ValuePtr> obj;

  static ValuePtr make_null() { return std::make_shared<Value>(); }
  static ValuePtr make_bool(bool b) {
    auto v = std::make_shared<Value>();
    v->type = Type::Bool;
    v->boolean = b;
    return v;
  }
  static ValuePtr make_number(double d) {
    auto v = std::make_shared<Value>();
    v->type = Type::Number;
    v->number = d;
    return v;
  }
  static ValuePtr make_string(const std::string& s) {
    auto v = std::make_shared<Value>();
    v->type = Type::String;
    v->str = s;
    return v;
  }
  static ValuePtr make_array() {
    auto v = std::make_shared<Value>();
    v->type = Type::Array;
    return v;
  }
  static ValuePtr make_object() {
    auto v = std::make_shared<Value>();
    v->type = Type::Object;
    return v;
  }

  bool is_null() const { return type == Type::Null; }
  bool is_object() const { return type == Type::Object; }
  bool is_array() const { return type == Type::Array; }
  bool is_string() const { return type == Type::String; }

  // Object accessors with defaults (missing key or wrong type -> default).
  ValuePtr get(const std::string& key) const {
    auto it = obj.find(key);
    return it == obj.end() ? nullptr : it->second;
  }
  std::string get_string(const std::string& key,
                         const std::string& dflt = "") const {
    auto v = get(key);
    return (v && v->type == Type::String) ? v->str : dflt;
  }
  double get_number(const std::string& key, double dflt = 0.0) const {
    auto v = get(key);
    return (v && v->type == Type::Number) ? v->number : dflt;
  }
  bool get_bool(const std::string& key, bool dflt = false) const {
    auto v = get(key);
    return (v && v->type == Type::Bool) ? v->boolean : dflt;
  }
  void set(const std::string& key, ValuePtr v) { obj[key] = v; }
  void set_string(const std::string& key, const std::string& s) {
    obj[key] = make_string(s);
  }
  void set_number(const std::string& key, double d) {
    obj[key] = make_number(d);
  }
  void set_bool(const std::string& key, bool b) { obj[key] = make_bool(b); }
};

// ---------------------------------------------------------------- parsing

class ParseError : public std::runtime_error {
 public:
  explicit ParseError(const std::string& msg) : std::runtime_error(msg) {}
};

class Parser {
 public:
  explicit Parser(const std::string& text) : text_(text) {}

  ValuePtr parse() {
    skip_ws();
    ValuePtr v = parse_value();
    skip_ws();
    if (pos_ != text_.size()) throw ParseError("trailing data");
    return v;
  }

 private:
  const std::string& text_;
  size_t pos_ = 0;

  [[noreturn]] void fail(const std::string& what) {
    throw ParseError(what + " at offset " + std::to_string(pos_));
  }
  char peek() {
    if (pos_ >= text_.size()) fail("unexpected end");
    return text_[pos_];
  }
  char next() {
    char c = peek();
    ++pos_;
    return c;
  }
  void skip_ws() {
    while (pos_ < text_.size()) {
      char c = text_[pos_];
      if (c == ' ' || c == '\t' || c == '\n' || c == '\r')
        ++pos_;
      else
        break;
    }
  }
  void expect(char c) {
    if (next() != c) fail(std::string("expected '") + c + "'");
  }
  void expect_word(const char* w) {
    for (const char* p = w; *p; ++p)
      if (pos_ >= text_.size() || text_[pos_++] != *p)
        fail(std::string("expected '") + w + "'");
  }

  ValuePtr parse_value() {
    switch (peek()) {
      case '{':
        return parse_object();
      case '[':
        return parse_array();
      case '"':
        return Value::make_string(parse_string());
      case 't':
        expect_word("true");
        return Value::make_bool(true);
      case 'f':
        expect_word("false");
        return Value::make_bool(false);
      case 'n':
        expect_word("null");
        return Value::make_null();
      default:
        return parse_number();
    }
  }

  ValuePtr parse_object() {
    expect('{');
    auto v = Value::make_object();
    skip_ws();
    if (peek() == '}') {
      ++pos_;
      return v;
    }
    while (true) {
      skip_ws();
      std::string key = parse_string();
      skip_ws();
      expect(':');
      skip_ws();
      v->obj[key] = parse_value();
      skip_ws();
      char c = next();
      if (c == '}') return v;
      if (c != ',') fail("expected ',' or '}'");
    }
  }

  ValuePtr parse_array() {
    expect('[');
    auto v = Value::make_array();
    skip_ws();
    if (peek() == ']') {
      ++pos_;
      return v;
    }
    while (true) {
      skip_ws();
      v->arr.push_back(parse_value());
      skip_ws();
      char c = next();
      if (c == ']') return v;
      if (c != ',') fail("expected ',' or ']'");
    }
  }

  std::string parse_string() {
    expect('"');
    std::string out;
    while (true) {
      char c = next();
      if (c == '"') return out;
      if (c == '\\') {
        char e = next();
        switch (e) {
          case '"':
            out += '"';
            break;
          case '\\':
            out += '\\';
            break;
          case '/':
            out += '/';
            break;
          case 'b':
            out += '\b';
            break;
          case 'f':
            out += '\f';
            break;
          case 'n':
            out += '\n';
            break;
          case 'r':
            out += '\r';
            break;
          case 't':
            out += '\t';
            break;
          case 'u': {
            unsigned code = parse_hex4();
            // Surrogate pair handling for non-BMP code points.
            if (code >= 0xD800 && code <= 0xDBFF && pos_ + 1 < text_.size() &&
                text_[pos_] == '\\' && text_[pos_ + 1] == 'u') {
              pos_ += 2;
              unsigned lo = parse_hex4();
              if (lo >= 0xDC00 && lo <= 0xDFFF)
                code = 0x10000 + ((code - 0xD800) << 10) + (lo - 0xDC00);
            }
            append_utf8(out, code);
            break;
          }
          default:
            fail("bad escape");
        }
      } else {
        out += c;
      }
    }
  }

  unsigned parse_hex4() {
    unsigned v = 0;
    for (int i = 0; i < 4; ++i) {
      char c = next();
      v <<= 4;
      if (c >= '0' && c <= '9')
        v |= unsigned(c - '0');
      else if (c >= 'a' && c <= 'f')
        v |= unsigned(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F')
        v |= unsigned(c - 'A' + 10);
      else
        fail("bad \\u escape");
    }
    return v;
  }

  static void append_utf8(std::string& out, unsigned code) {
    if (code < 0x80) {
      out += char(code);
    } else if (code < 0x800) {
      out += char(0xC0 | (code >> 6));
      out += char(0x80 | (code & 0x3F));
    } else if (code < 0x10000) {
      out += char(0xE0 | (code >> 12));
      out += char(0x80 | ((code >> 6) & 0x3F));
      out += char(0x80 | (code & 0x3F));
    } else {
      out += char(0xF0 | (code >> 18));
      out += char(0x80 | ((code >> 12) & 0x3F));
      out += char(0x80 | ((code >> 6) & 0x3F));
      out += char(0x80 | (code & 0x3F));
    }
  }

  ValuePtr parse_number() {
    size_t start = pos_;
    if (peek() == '-') ++pos_;
    while (pos_ < text_.size() &&
           (isdigit((unsigned char)text_[pos_]) || text_[pos_] == '.' ||
            text_[pos_] == 'e' || text_[pos_] == 'E' || text_[pos_] == '+' ||
            text_[pos_] == '-'))
      ++pos_;
    if (pos_ == start) fail("bad value");
    try {
      return Value::make_number(std::stod(text_.substr(start, pos_ - start)));
    } catch (const std::exception&) {
      fail("bad number");
    }
  }
};

inline ValuePtr parse(const std::string& text) { return Parser(text).parse(); }

// ------------------------------------------------------------ serializing

inline void write_escaped(std::ostream& os, const std::string& s) {
  os << '"';
  for (char c : s) {
    switch (c) {
      case '"':
        os << "\\\"";
        break;
      case '\\':
        os << "\\\\";
        break;
      case '\b':
        os << "\\b";
        break;
      case '\f':
        os << "\\f";
        break;
      case '\n':
        os << "\\n";
        break;
      case '\r':
        os << "\\r";
        break;
      case '\t':
        os << "\\t";
        break;
      default:
        if ((unsigned char)c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          os << buf;
        } else {
          os << c;
        }
    }
  }
  os << '"';
}

inline void write(std::ostream& os, const ValuePtr& v) {
  if (!v) {
    os << "null";
    return;
  }
  switch (v->type) {
    case Type::Null:
      os << "null";
      break;
    case Type::Bool:
      os << (v->boolean ? "true" : "false");
      break;
    case Type::Number: {
      double d = v->number;
      if (std::isfinite(d) && d == std::floor(d) &&
          std::fabs(d) < 9.0e15) {
        os << (long long)d;
      } else {
        std::ostringstream tmp;
        tmp.precision(17);
        tmp << d;
        os << tmp.str();
      }
      break;
    }
    case Type::String:
      write_escaped(os, v->str);
      break;
    case Type::Array: {
      os << '[';
      bool first = true;
      for (const auto& e : v->arr) {
        if (!first) os << ',';
        first = false;
        write(os, e);
      }
      os << ']';
      break;
    }
    case Type::Object: {
      os << '{';
      bool first = true;
      for (const auto& kv : v->obj) {
        if (!first) os << ',';
        first = false;
        write_escaped(os, kv.first);
        os << ':';
        write(os, kv.second);
      }
      os << '}';
      break;
    }
  }
}

inline std::string dump(const ValuePtr& v) {
  std::ostringstream os;
  write(os, v);
  return os.str();
}

}  // namespace cpjson
