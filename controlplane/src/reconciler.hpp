// Reconcile loop: StaticRoute specs -> rendered dynamic config + router
// health probing + status reporting.
//
// Behavior parity with the reference operator's Reconcile
// (src/router-controller/internal/controller/staticroute_controller.go:71-132):
//   fetch spec -> render config (CreateOrUpdate) -> update status
//   (ConfigMapRef, LastAppliedTime, Conditions) -> probe router health with
//   the spec's thresholds -> requeue on the health-check period.
//
// Two backends:
//  * file mode — specs are *.json files in --spec-dir (the ConfigMap-mount
//    equivalent); rendered configs land at
//    <out>/<configName>/dynamic_config.json for the router's
//    DynamicConfigWatcher; status at <out>/status/<name>.json.
//  * k8s mode — specs are StaticRoute custom resources fetched from the
//    Kubernetes API through a kubectl-proxy sidecar (plain HTTP, no TLS
//    stack needed); rendered configs become ConfigMaps; status is written
//    to the CR's /status subresource.
#pragma once

#include <dirent.h>
#include <sys/stat.h>
#include <sys/types.h>

#include <algorithm>
#include <cstdio>
#include <ctime>
#include <fstream>
#include <functional>
#include <map>
#include <set>
#include <sstream>
#include <string>
#include <vector>

#include "http.hpp"
#include "json.hpp"
#include "spec.hpp"

namespace cpagent {

inline std::string now_iso8601() {
  std::time_t t = std::time(nullptr);
  char buf[32];
  std::strftime(buf, sizeof(buf), "%Y-%m-%dT%H:%M:%SZ", std::gmtime(&t));
  return buf;
}

inline bool mkdir_p(const std::string& path) {
  std::string cur;
  std::istringstream ss(path);
  std::string part;
  if (!path.empty() && path[0] == '/') cur = "/";
  while (std::getline(ss, part, '/')) {
    if (part.empty()) continue;
    cur += part + "/";
    if (::mkdir(cur.c_str(), 0755) != 0 && errno != EEXIST) return false;
  }
  return true;
}

inline bool read_file(const std::string& path, std::string* out) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return false;
  std::ostringstream ss;
  ss << f.rdbuf();
  *out = ss.str();
  return true;
}

// Write-then-rename so the router's watcher never sees a half-written file.
inline bool write_file_atomic(const std::string& path,
                              const std::string& content) {
  std::string tmp = path + ".tmp";
  {
    std::ofstream f(tmp, std::ios::binary | std::ios::trunc);
    if (!f) return false;
    f << content;
    // Flush + close BEFORE checking: operator<< may buffer, and a
    // failed destructor-time flush (e.g. ENOSPC) would otherwise pass
    // the check and rename a truncated file into place.
    f.flush();
    f.close();
    if (f.fail()) {
      ::remove(tmp.c_str());
      return false;
    }
  }
  return ::rename(tmp.c_str(), path.c_str()) == 0;
}

// Per-route health probe state, carried across reconcile ticks exactly like
// the reference's consecutive success/failure threshold logic.
struct HealthState {
  int consecutive_successes = 0;
  int consecutive_failures = 0;
  bool healthy = false;
  bool ever_probed = false;
  std::string last_probe_time;
  std::string last_detail;

  void observe(bool success, const HealthCheckConfig& cfg,
               const std::string& detail) {
    ever_probed = true;
    last_probe_time = now_iso8601();
    last_detail = detail;
    if (success) {
      consecutive_successes++;
      consecutive_failures = 0;
      if (consecutive_successes >= cfg.success_threshold) healthy = true;
    } else {
      consecutive_failures++;
      consecutive_successes = 0;
      if (consecutive_failures >= cfg.failure_threshold) healthy = false;
    }
  }
};

struct RouteStatus {
  std::string name;
  bool ready = false;
  std::string reason;
  std::string message;
  std::string config_ref;
  std::string last_applied_time;
  // k8s condition semantics: set when Ready flips, carried otherwise.
  std::string last_transition_time;
  HealthState health;

  cpjson::ValuePtr to_json() const {
    auto v = cpjson::Value::make_object();
    v->set_string("name", name);
    auto conds = cpjson::Value::make_array();
    auto ready_cond = cpjson::Value::make_object();
    ready_cond->set_string("type", "Ready");
    ready_cond->set_string("status", ready ? "True" : "False");
    ready_cond->set_string("reason", reason);
    ready_cond->set_string("message", message);
    ready_cond->set_string("lastTransitionTime",
                           last_transition_time.empty()
                               ? now_iso8601()
                               : last_transition_time);
    conds->arr.push_back(ready_cond);
    v->set("conditions", conds);
    v->set_string("configMapRef", config_ref);
    if (!last_applied_time.empty())
      v->set_string("lastAppliedTime", last_applied_time);
    if (health.ever_probed) {
      auto h = cpjson::Value::make_object();
      h->set_bool("healthy", health.healthy);
      h->set_number("consecutiveSuccesses", health.consecutive_successes);
      h->set_number("consecutiveFailures", health.consecutive_failures);
      h->set_string("lastProbeTime", health.last_probe_time);
      h->set_string("detail", health.last_detail);
      v->set("routerHealth", h);
    }
    return v;
  }
};

class Reconciler {
 public:
  // Probe hook is injectable for tests; default does a real HTTP GET.
  using ProbeFn = std::function<bool(const std::string& url, int timeout_s,
                                     std::string* detail)>;

  Reconciler() {
    probe_ = [](const std::string& url, int timeout_s, std::string* detail) {
      cphttp::Response r = cphttp::get(url, timeout_s);
      if (!r.ok) {
        *detail = r.error;
        return false;
      }
      *detail = "HTTP " + std::to_string(r.status);
      return r.status >= 200 && r.status < 300;
    };
  }

  void set_probe(ProbeFn fn) { probe_ = std::move(fn); }

  // ------------------------------------------------------------ file mode

  // One pass over --spec-dir. Returns per-route statuses (also persisted
  // under <out>/status/).
  std::vector<RouteStatus> reconcile_dir(const std::string& spec_dir,
                                         const std::string& out_dir) {
    std::vector<RouteStatus> statuses;
    std::set<std::string> seen;
    // GC may only run when every spec's resource identity is known; a
    // transiently unreadable/unparseable file whose metadata.name
    // differs from its filename must not tear down its live config.
    bool gc_safe = true;
    mkdir_p(out_dir + "/status");
    for (const std::string& fname : list_json_files(spec_dir)) {
      std::string name = fname.substr(0, fname.size() - 5);  // strip .json
      RouteStatus st;
      st.name = name;

      std::string text;
      if (!read_file(spec_dir + "/" + fname, &text)) {
        st.reason = "ReadError";
        st.message = "cannot read spec file";
        gc_safe = false;  // identity unknown — protect live configs
        finish_error_status(out_dir, &st);
        statuses.push_back(st);
        seen.insert(st.name);
        continue;
      }
      ParseResult parsed = try_parse(name, text);
      if (!parsed.ok) {
        // parse_spec resolves metadata.name before most failures; key
        // the status off it so GC doesn't mistake the route for gone.
        // Never adopt an unsafe name — it becomes a path component.
        if (is_safe_name(parsed.spec.name) && parsed.spec.name != name)
          st.name = parsed.spec.name;
        else if (parsed.spec.name.empty())
          gc_safe = false;  // bad JSON: identity unknown
        st.reason = "InvalidSpec";
        st.message = parsed.error;
        finish_error_status(out_dir, &st);
        statuses.push_back(st);
        seen.insert(st.name);
        continue;
      }
      const StaticRouteSpec& spec = parsed.spec;
      // metadata.name (when present) is the resource identity, not the
      // file name — status and health state key off it.
      st.name = spec.name;
      st.config_ref = spec.config_name();
      recover_state(out_dir, spec.name);
      st.health = health_[spec.name];

      std::string rendered = render_dynamic_config(spec);
      std::string cfg_dir = out_dir + "/" + spec.config_name();
      std::string cfg_path = cfg_dir + "/dynamic_config.json";
      std::string existing;
      bool changed = !read_file(cfg_path, &existing) || existing != rendered;
      if (changed) {
        mkdir_p(cfg_dir);
        if (!write_file_atomic(cfg_path, rendered)) {
          st.reason = "WriteError";
          st.message = "cannot write " + cfg_path;
          st.last_applied_time = applied_time_[spec.name];
          stamp_transition(st.name, &st);
          finish_file_status(out_dir, st);
          statuses.push_back(st);
          // Still seen: a transient write failure must not let
          // collect_garbage tear down the live config.
          seen.insert(st.name);
          continue;
        }
        applied_time_[spec.name] = now_iso8601();
      }
      st.last_applied_time = applied_time_[spec.name];

      probe_router(spec, spec.name, &st);
      st.ready = true;
      st.reason = "Reconciled";
      st.message = changed ? "config updated" : "config up to date";
      health_[spec.name] = st.health;
      stamp_transition(st.name, &st);
      finish_file_status(out_dir, st);
      statuses.push_back(st);
      seen.insert(st.name);
    }
    if (gc_safe) collect_garbage(out_dir, seen);
    return statuses;
  }

  // ------------------------------------------------------------- k8s mode

  // One pass against the Kubernetes API (via kubectl-proxy base URL).
  // Group/version mirrors the reference's
  // production-stack.vllm.ai/v1alpha1 StaticRoute CRD.
  std::vector<RouteStatus> reconcile_k8s(const std::string& api_base,
                                         const std::string& ns) {
    std::vector<RouteStatus> statuses;
    std::string list_url =
        ns.empty()
            ? api_base + "/apis/" + kGroup + "/" + kVersion + "/staticroutes"
            : api_base + "/apis/" + kGroup + "/" + kVersion +
                  "/namespaces/" + ns + "/staticroutes";
    cphttp::Response resp = cphttp::get(list_url, 10);
    if (!resp.ok || resp.status != 200) {
      RouteStatus st;
      st.name = "<list>";
      st.reason = "ApiError";
      st.message = resp.ok ? "HTTP " + std::to_string(resp.status)
                           : resp.error;
      statuses.push_back(st);
      return statuses;
    }
    cpjson::ValuePtr list;
    try {
      list = cpjson::parse(resp.body);
    } catch (const cpjson::ParseError& e) {
      RouteStatus st;
      st.name = "<list>";
      st.reason = "ApiError";
      st.message = std::string("bad list body: ") + e.what();
      statuses.push_back(st);
      return statuses;
    }
    auto items = list->get("items");
    if (!items || !items->is_array()) return statuses;

    std::set<std::string> seen_keys;
    for (const auto& item : items->arr) {
      RouteStatus st;
      ParseResult parsed = parse_spec("", item);
      if (!parsed.ok) {
        auto meta = item->get("metadata");
        st.name = meta && meta->is_object() ? meta->get_string("name")
                                            : "<unknown>";
        // The CR still exists — protect its probe/applied state from
        // prune_state during a transiently invalid edit.
        if (meta && meta->is_object() && !st.name.empty()) {
          std::string ns_of = meta->get_string("namespace");
          seen_keys.insert((ns_of.empty() ? "default" : ns_of) + "/" +
                           st.name);
        }
        st.reason = "InvalidSpec";
        st.message = parsed.error;
        statuses.push_back(st);
        continue;
      }
      StaticRouteSpec& spec = parsed.spec;
      st.name = spec.name;
      // CRs are namespaced: same-named routes in different namespaces
      // must not share probe/applied state.
      std::string key = spec.namespace_ + "/" + spec.name;
      seen_keys.insert(key);
      st.health = health_[key];
      st.config_ref = spec.config_name();

      // Recover lastAppliedTime + the Ready transition time from the
      // CR's existing status so an agent restart (or repeated --once
      // run) doesn't clobber them.
      auto prev = item->get("status");
      if (applied_time_[key].empty() && prev && prev->is_object())
        applied_time_[key] = prev->get_string("lastAppliedTime");
      recover_transition(key, prev);

      if (!upsert_configmap(api_base, item, spec, key, &st)) {
        // Carry the recovered lastAppliedTime so a failure-path status
        // PUT can't clobber it in the CR.
        st.last_applied_time = applied_time_[key];
        stamp_transition(key, &st);
        update_cr_status(api_base, item, spec, st);
        statuses.push_back(st);
        continue;
      }
      st.last_applied_time = applied_time_[key];
      probe_router(spec, key, &st);
      st.ready = true;
      st.reason = "Reconciled";
      st.message = "config map reconciled";
      health_[key] = st.health;
      stamp_transition(key, &st);
      update_cr_status(api_base, item, spec, st);
      statuses.push_back(st);
    }
    prune_state(seen_keys, ns);
    return statuses;
  }

  static constexpr const char* kGroup = "production-stack.tpu";
  static constexpr const char* kVersion = "v1alpha1";

 private:
  ProbeFn probe_;
  std::map<std::string, HealthState> health_;
  std::map<std::string, std::string> applied_time_;
  std::map<std::string, std::time_t> last_probe_;
  // Ready value + when it last flipped, per route key (k8s condition
  // semantics: lastTransitionTime only moves on actual transitions).
  std::map<std::string, std::pair<bool, std::string>> transition_;

  // Set st->last_transition_time, stamping a fresh time only when the
  // Ready condition actually changed value.
  void stamp_transition(const std::string& key, RouteStatus* st) {
    auto it = transition_.find(key);
    if (it != transition_.end() && it->second.first == st->ready &&
        !it->second.second.empty()) {
      st->last_transition_time = it->second.second;
      return;
    }
    st->last_transition_time = now_iso8601();
    transition_[key] = {st->ready, st->last_transition_time};
  }

  // Seed transition_ from a previously-persisted status object.
  void recover_transition(const std::string& key,
                          const cpjson::ValuePtr& prev) {
    if (transition_.count(key) || !prev || !prev->is_object()) return;
    auto conds = prev->get("conditions");
    if (!conds || !conds->is_array() || conds->arr.empty()) return;
    for (const auto& c : conds->arr) {
      if (!c->is_object() || c->get_string("type") != "Ready") continue;
      std::string t = c->get_string("lastTransitionTime");
      if (!t.empty())
        transition_[key] = {c->get_string("status") == "True", t};
      return;
    }
  }

  // Drop per-route state for routes that no longer exist (k8s mode; the
  // file-mode analogue lives in collect_garbage). When the reconcile is
  // namespace-scoped, only that namespace's keys are candidates.
  void prune_state(const std::set<std::string>& seen_keys,
                   const std::string& ns) {
    auto stale = [&](const std::string& key) {
      if (seen_keys.count(key)) return false;
      return ns.empty() || key.rfind(ns + "/", 0) == 0;
    };
    for (auto it = health_.begin(); it != health_.end();)
      it = stale(it->first) ? health_.erase(it) : std::next(it);
    for (auto it = applied_time_.begin(); it != applied_time_.end();)
      it = stale(it->first) ? applied_time_.erase(it) : std::next(it);
    for (auto it = last_probe_.begin(); it != last_probe_.end();)
      it = stale(it->first) ? last_probe_.erase(it) : std::next(it);
    for (auto it = transition_.begin(); it != transition_.end();)
      it = stale(it->first) ? transition_.erase(it) : std::next(it);
  }

  static std::vector<std::string> list_json_files(const std::string& dir) {
    std::vector<std::string> out;
    DIR* d = ::opendir(dir.c_str());
    if (!d) return out;
    while (struct dirent* e = ::readdir(d)) {
      std::string n = e->d_name;
      if (n.size() > 5 && n.substr(n.size() - 5) == ".json")
        out.push_back(n);
    }
    ::closedir(d);
    std::sort(out.begin(), out.end());
    return out;
  }

  static ParseResult try_parse(const std::string& name,
                               const std::string& text) {
    try {
      return parse_spec(name, cpjson::parse(text));
    } catch (const cpjson::ParseError& e) {
      ParseResult r;
      r.error = std::string("bad JSON: ") + e.what();
      return r;
    }
  }

  void probe_router(const StaticRouteSpec& spec, const std::string& key,
                    RouteStatus* st) {
    if (spec.router_url.empty()) return;
    // Honor the spec's own healthCheck.periodSeconds (the reference
    // requeues on it); the process --period only sets the outer tick.
    std::time_t now = std::time(nullptr);
    auto it = last_probe_.find(key);
    if (it != last_probe_.end() &&
        now - it->second < spec.health.period_s)
      return;
    last_probe_[key] = now;

    // Append /health based on the URL's *path* component; a substring
    // test would misfire on hosts like http://healthy-router:8001.
    std::string url = spec.router_url;
    cphttp::Url parsed = cphttp::parse_url(url);
    std::string path = parsed.path;
    bool has_health = path == "/health" ||
                      (path.size() >= 7 &&
                       path.compare(path.size() - 7, 7, "/health") == 0);
    if (!has_health) {
      if (!url.empty() && url.back() == '/') url.pop_back();
      url += "/health";
    }
    std::string detail;
    bool up = probe_(url, spec.health.timeout_s, &detail);
    st->health.observe(up, spec.health, detail);
  }

  // A fresh process (e.g. --once runs) must not reset lastAppliedTime or
  // the health-probe state machine; recover both from the persisted
  // status file so file mode is stateless-process-safe.
  void recover_state(const std::string& out_dir, const std::string& name) {
    if (!applied_time_[name].empty() || health_[name].ever_probed) return;
    std::string text;
    if (!read_file(out_dir + "/status/" + name + ".json", &text)) return;
    try {
      auto prev = cpjson::parse(text);
      if (applied_time_[name].empty())
        applied_time_[name] = prev->get_string("lastAppliedTime");
      recover_transition(name, prev);
      auto h = prev->get("routerHealth");
      if (h && h->is_object() && !health_[name].ever_probed) {
        HealthState& hs = health_[name];
        hs.ever_probed = true;
        hs.healthy = h->get_bool("healthy");
        hs.consecutive_successes =
            int(h->get_number("consecutiveSuccesses"));
        hs.consecutive_failures =
            int(h->get_number("consecutiveFailures"));
        hs.last_probe_time = h->get_string("lastProbeTime");
        hs.last_detail = h->get_string("detail");
        std::time_t t = parse_iso8601(hs.last_probe_time);
        if (t > 0) last_probe_[name] = t;
      }
    } catch (const cpjson::ParseError&) {
    }
  }

  static std::time_t parse_iso8601(const std::string& s) {
    struct tm tm;
    std::memset(&tm, 0, sizeof(tm));
    if (s.empty() || !strptime(s.c_str(), "%Y-%m-%dT%H:%M:%SZ", &tm))
      return 0;
    return timegm(&tm);
  }

  // Error-path status write: a transient failure must not erase the
  // persisted lastAppliedTime/routerHealth/transition of a previously
  // healthy route (the status file is the file-mode store of record).
  void finish_error_status(const std::string& out_dir, RouteStatus* st) {
    recover_state(out_dir, st->name);
    st->last_applied_time = applied_time_[st->name];
    st->health = health_[st->name];
    if (st->config_ref.empty()) {
      // Keep the configMapRef pointer so GC can still find the rendered
      // config if the spec is deleted while in this error state.
      std::string text;
      if (read_file(out_dir + "/status/" + st->name + ".json", &text)) {
        try {
          st->config_ref = cpjson::parse(text)->get_string("configMapRef");
        } catch (const cpjson::ParseError&) {
        }
      }
    }
    stamp_transition(st->name, st);
    finish_file_status(out_dir, *st);
  }

  void finish_file_status(const std::string& out_dir, const RouteStatus& st) {
    write_file_atomic(out_dir + "/status/" + st.name + ".json",
                      cpjson::dump(st.to_json()));
  }

  // Deleting a spec must take its rendered config out of service — the
  // file-mode analogue of the reference's ownerReference-based GC.
  void collect_garbage(const std::string& out_dir,
                       const std::set<std::string>& seen) {
    std::string status_dir = out_dir + "/status";
    for (const std::string& fname : list_json_files(status_dir)) {
      std::string name = fname.substr(0, fname.size() - 5);
      if (seen.count(name)) continue;
      std::string text;
      std::string config_ref;
      if (read_file(status_dir + "/" + fname, &text)) {
        try {
          config_ref = cpjson::parse(text)->get_string("configMapRef");
        } catch (const cpjson::ParseError&) {
        }
      }
      // is_safe_name (not just a '/'-check) so a corrupted status file
      // can never aim the delete at e.g. ".." and escape out_dir.
      if (is_safe_name(config_ref)) {
        std::string cfg_dir = out_dir + "/" + config_ref;
        ::remove((cfg_dir + "/dynamic_config.json").c_str());
        ::rmdir(cfg_dir.c_str());
      }
      if (is_safe_name(name))
        ::remove((status_dir + "/" + fname).c_str());
      health_.erase(name);
      applied_time_.erase(name);
      last_probe_.erase(name);
      transition_.erase(name);
    }
  }

  bool upsert_configmap(const std::string& api_base,
                        const cpjson::ValuePtr& owner,
                        const StaticRouteSpec& spec,
                        const std::string& key, RouteStatus* st) {
    std::string rendered = render_dynamic_config(spec);
    std::string cm_url = api_base + "/api/v1/namespaces/" + spec.namespace_ +
                         "/configmaps/" + spec.config_name();
    cphttp::Response existing = cphttp::get(cm_url, 10);
    if (existing.ok && existing.status == 200) {
      try {
        auto cm = cpjson::parse(existing.body);
        auto data = cm->get("data");
        if (data && data->is_object() &&
            data->get_string("dynamic_config.json") == rendered)
          return true;  // up to date
      } catch (const cpjson::ParseError&) {
        // fall through to rewrite
      }
    }
    auto cm = cpjson::Value::make_object();
    cm->set_string("apiVersion", "v1");
    cm->set_string("kind", "ConfigMap");
    auto meta = cpjson::Value::make_object();
    meta->set_string("name", spec.config_name());
    meta->set_string("namespace", spec.namespace_);
    // ownerReference -> kube GC deletes the ConfigMap with its CR, like
    // the reference's controllerutil.SetControllerReference.
    auto owner_meta = owner->get("metadata");
    std::string uid = owner_meta && owner_meta->is_object()
                          ? owner_meta->get_string("uid")
                          : "";
    if (!uid.empty()) {
      auto refs = cpjson::Value::make_array();
      auto ref = cpjson::Value::make_object();
      ref->set_string("apiVersion",
                      std::string(kGroup) + "/" + kVersion);
      ref->set_string("kind", "StaticRoute");
      ref->set_string("name", spec.name);
      ref->set_string("uid", uid);
      ref->set_bool("controller", true);
      ref->set_bool("blockOwnerDeletion", true);
      refs->arr.push_back(ref);
      meta->set("ownerReferences", refs);
    }
    cm->set("metadata", meta);
    auto data = cpjson::Value::make_object();
    data->set_string("dynamic_config.json", rendered);
    cm->set("data", data);

    cphttp::Response put;
    if (existing.ok && existing.status == 200) {
      put = cphttp::request("PUT", cm_url, cpjson::dump(cm));
    } else {
      std::string create_url = api_base + "/api/v1/namespaces/" +
                               spec.namespace_ + "/configmaps";
      put = cphttp::request("POST", create_url, cpjson::dump(cm));
    }
    if (!put.ok || put.status >= 300) {
      st->reason = "ConfigMapError";
      st->message = put.ok ? "HTTP " + std::to_string(put.status) : put.error;
      return false;
    }
    applied_time_[key] = now_iso8601();
    return true;
  }

  void update_cr_status(const std::string& api_base,
                        const cpjson::ValuePtr& item,
                        const StaticRouteSpec& spec,
                        const RouteStatus& st) {
    // PUT the fetched object back with .status set (needs resourceVersion,
    // which the fetched item carries). Skip when the CR's live status
    // already matches — an unconditional PUT every tick would bump
    // resourceVersion forever and wake every watcher of the CRD.
    // Comparing against the *fetched* status (not a local cache) also
    // repairs external edits; cpjson objects are sorted maps, so dumps
    // are order-normalized on both sides.
    auto status_json = st.to_json();
    auto live = item->get("status");
    if (live && cpjson::dump(live) == cpjson::dump(status_json)) return;
    auto obj = item;  // shared structure; we only mutate .status
    obj->set("status", status_json);
    std::string url = api_base + "/apis/" + std::string(kGroup) + "/" +
                      kVersion + "/namespaces/" + spec.namespace_ +
                      "/staticroutes/" + spec.name + "/status";
    cphttp::request("PUT", url, cpjson::dump(obj));
  }
};

}  // namespace cpagent
