// tpu-stack-controlplane: native control-plane agent for the TPU serving
// stack.
//
// The reference implements this layer as a Go/kubebuilder operator
// (src/router-controller/cmd/main.go). This agent provides the same
// contract — StaticRoute spec -> dynamic_config.json -> router
// DynamicConfigWatcher, plus router health probing — as a single static
// C++ binary with no library dependencies, so it can run as a plain
// sidecar, a systemd unit on bare metal, or a Deployment next to a
// kubectl-proxy container.
#include <csignal>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>

#include "reconciler.hpp"

namespace {

volatile std::sig_atomic_t g_stop = 0;
void handle_signal(int) { g_stop = 1; }

void usage(const char* prog) {
  std::fprintf(stderr,
               "usage: %s [--spec-dir DIR --out-dir DIR | --kube-api URL "
               "[--namespace NS]]\n"
               "          [--period SECONDS] [--once]\n"
               "\n"
               "File mode (default): reconcile *.json StaticRoute specs in\n"
               "--spec-dir into <out-dir>/<configName>/dynamic_config.json\n"
               "and statuses into <out-dir>/status/.\n"
               "\n"
               "K8s mode: reconcile StaticRoute custom resources\n"
               "(apis/%s/%s) via a kubectl-proxy base URL into ConfigMaps\n"
               "and CR status subresources.\n",
               prog, cpagent::Reconciler::kGroup,
               cpagent::Reconciler::kVersion);
}

}  // namespace

int main(int argc, char** argv) {
  std::string spec_dir;
  std::string out_dir;
  std::string kube_api;
  std::string ns;
  int period_s = 10;
  bool once = false;

  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    auto need_value = [&](const char* flag) -> const char* {
      if (i + 1 >= argc) {
        std::fprintf(stderr, "%s requires a value\n", flag);
        std::exit(2);
      }
      return argv[++i];
    };
    if (arg == "--spec-dir") {
      spec_dir = need_value("--spec-dir");
    } else if (arg == "--out-dir") {
      out_dir = need_value("--out-dir");
    } else if (arg == "--kube-api") {
      kube_api = need_value("--kube-api");
    } else if (arg == "--namespace") {
      ns = need_value("--namespace");
    } else if (arg == "--period") {
      period_s = std::atoi(need_value("--period"));
      if (period_s < 1) period_s = 1;
    } else if (arg == "--once") {
      once = true;
    } else if (arg == "--help" || arg == "-h") {
      usage(argv[0]);
      return 0;
    } else {
      std::fprintf(stderr, "unknown flag: %s\n", arg.c_str());
      usage(argv[0]);
      return 2;
    }
  }

  bool file_mode = !spec_dir.empty();
  bool k8s_mode = !kube_api.empty();
  if (file_mode == k8s_mode) {  // neither or both
    usage(argv[0]);
    return 2;
  }
  if (file_mode && out_dir.empty()) {
    std::fprintf(stderr, "--spec-dir requires --out-dir\n");
    return 2;
  }

  std::signal(SIGINT, handle_signal);
  std::signal(SIGTERM, handle_signal);

  cpagent::Reconciler reconciler;
  std::fprintf(stderr, "[controlplane] starting in %s mode (period %ds)\n",
               file_mode ? "file" : "k8s", period_s);

  while (!g_stop) {
    std::vector<cpagent::RouteStatus> statuses =
        file_mode ? reconciler.reconcile_dir(spec_dir, out_dir)
                  : reconciler.reconcile_k8s(kube_api, ns);
    for (const auto& st : statuses) {
      std::fprintf(stderr,
                   "[controlplane] route=%s ready=%s reason=%s%s%s\n",
                   st.name.c_str(), st.ready ? "true" : "false",
                   st.reason.c_str(),
                   st.health.ever_probed ? " routerHealthy=" : "",
                   st.health.ever_probed
                       ? (st.health.healthy ? "true" : "false")
                       : "");
    }
    if (once) break;
    for (int slept = 0; slept < period_s && !g_stop; ++slept)
      std::this_thread::sleep_for(std::chrono::seconds(1));
  }
  std::fprintf(stderr, "[controlplane] exiting\n");
  return 0;
}
