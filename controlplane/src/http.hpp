// Minimal blocking HTTP/1.1 client over POSIX sockets.
//
// Used for (a) router /health probes — parity with the reference operator's
// checkRouterHealth (src/router-controller/internal/controller/
// staticroute_controller.go:186+) — and (b) Kubernetes API calls through a
// kubectl-proxy sidecar (plain HTTP on localhost), which keeps the agent
// free of TLS dependencies. Supports GET/POST/PUT/PATCH with bodies,
// Content-Length and chunked responses, and per-request timeouts.
#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cctype>
#include <cerrno>
#include <cstring>
#include <ctime>
#include <sstream>
#include <string>

namespace cphttp {

struct Url {
  std::string host;
  std::string port = "80";
  std::string path = "/";
  bool valid = false;
};

inline Url parse_url(const std::string& url) {
  Url out;
  std::string rest = url;
  const std::string scheme = "http://";
  if (rest.rfind(scheme, 0) != 0) return out;  // https is not supported
  rest = rest.substr(scheme.size());
  size_t slash = rest.find('/');
  std::string hostport = slash == std::string::npos ? rest
                                                    : rest.substr(0, slash);
  out.path = slash == std::string::npos ? "/" : rest.substr(slash);
  if (!hostport.empty() && hostport[0] == '[') {
    // IPv6 literal: strip the brackets (getaddrinfo wants the bare
    // address) and only treat a colon AFTER ']' as the port separator.
    size_t close = hostport.find(']');
    if (close == std::string::npos || close == 1) return out;
    out.host = hostport.substr(1, close - 1);
    if (close + 1 < hostport.size()) {
      if (hostport[close + 1] != ':') return out;
      out.port = hostport.substr(close + 2);
    }
  } else {
    size_t colon = hostport.rfind(':');
    if (colon != std::string::npos) {
      out.host = hostport.substr(0, colon);
      out.port = hostport.substr(colon + 1);
    } else {
      out.host = hostport;
    }
  }
  out.valid = !out.host.empty() && !out.port.empty();
  return out;
}

struct Response {
  bool ok = false;          // transport-level success
  int status = 0;           // HTTP status code
  std::string body;
  std::string error;        // transport error description when !ok
};

class Connection {
 public:
  ~Connection() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool connect(const Url& url, int timeout_s, std::string* error) {
    struct addrinfo hints;
    std::memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    int rc = ::getaddrinfo(url.host.c_str(), url.port.c_str(), &hints, &res);
    if (rc != 0) {
      *error = std::string("resolve: ") + gai_strerror(rc);
      return false;
    }
    for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
      fd_ = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd_ < 0) continue;
      set_timeouts(timeout_s);
      if (::connect(fd_, ai->ai_addr, ai->ai_addrlen) == 0) {
        ::freeaddrinfo(res);
        return true;
      }
      ::close(fd_);
      fd_ = -1;
    }
    ::freeaddrinfo(res);
    *error = "connect: " + std::string(std::strerror(errno));
    return false;
  }

  bool send_all(const std::string& data, std::string* error) {
    size_t off = 0;
    while (off < data.size()) {
      ssize_t n = ::send(fd_, data.data() + off, data.size() - off, 0);
      if (n <= 0) {
        *error = "send: " + std::string(std::strerror(errno));
        return false;
      }
      off += size_t(n);
    }
    return true;
  }

  // Reads until EOF (responses use Connection: close), bounded by an
  // overall deadline: SO_RCVTIMEO alone is per-recv(), so a peer dripping
  // bytes slower than the timeout would otherwise stall the reconcile
  // loop forever.
  bool recv_all(std::string* out, int timeout_s, std::string* error) {
    char buf[8192];
    std::time_t deadline = std::time(nullptr) + timeout_s;
    while (true) {
      if (std::time(nullptr) >= deadline) {
        *error = "recv: overall deadline exceeded";
        return false;
      }
      ssize_t n = ::recv(fd_, buf, sizeof(buf), 0);
      if (n < 0) {
        *error = "recv: " + std::string(std::strerror(errno));
        return false;
      }
      if (n == 0) return true;
      out->append(buf, size_t(n));
    }
  }

 private:
  int fd_ = -1;

  void set_timeouts(int timeout_s) {
    struct timeval tv;
    tv.tv_sec = timeout_s;
    tv.tv_usec = 0;
    ::setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    ::setsockopt(fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  }
};

inline std::string dechunk(const std::string& body) {
  std::string out;
  size_t pos = 0;
  while (pos < body.size()) {
    size_t eol = body.find("\r\n", pos);
    if (eol == std::string::npos) break;
    unsigned long len = 0;
    try {
      len = std::stoul(body.substr(pos, eol - pos), nullptr, 16);
    } catch (const std::exception&) {
      break;
    }
    if (len == 0) break;
    out.append(body, eol + 2, len);
    pos = eol + 2 + len + 2;  // skip chunk + trailing CRLF
  }
  return out;
}

inline Response request(const std::string& method, const std::string& url_str,
                        const std::string& body = "",
                        const std::string& content_type = "application/json",
                        int timeout_s = 5) {
  Response resp;
  Url url = parse_url(url_str);
  if (!url.valid) {
    resp.error = "bad url (only http:// is supported): " + url_str;
    return resp;
  }

  // IPv6 literals must be re-bracketed in the Host header.
  bool v6 = url.host.find(':') != std::string::npos;
  std::string host_hdr =
      (v6 ? "[" + url.host + "]" : url.host) + ":" + url.port;
  std::ostringstream req;
  req << method << ' ' << url.path << " HTTP/1.1\r\n"
      << "Host: " << host_hdr << "\r\n"
      << "Connection: close\r\n"
      << "Accept: application/json\r\n";
  if (!body.empty() || method == "POST" || method == "PUT" ||
      method == "PATCH") {
    req << "Content-Type: " << content_type << "\r\n"
        << "Content-Length: " << body.size() << "\r\n";
  }
  req << "\r\n" << body;

  Connection conn;
  if (!conn.connect(url, timeout_s, &resp.error)) return resp;
  if (!conn.send_all(req.str(), &resp.error)) return resp;
  std::string raw;
  if (!conn.recv_all(&raw, timeout_s, &resp.error)) return resp;

  size_t header_end = raw.find("\r\n\r\n");
  if (header_end == std::string::npos) {
    resp.error = "malformed response";
    return resp;
  }
  std::string headers = raw.substr(0, header_end);
  resp.body = raw.substr(header_end + 4);

  size_t sp = headers.find(' ');
  if (sp == std::string::npos) {
    resp.error = "malformed status line";
    return resp;
  }
  try {
    resp.status = std::stoi(headers.substr(sp + 1, 3));
  } catch (const std::exception&) {
    resp.error = "malformed status code";
    return resp;
  }

  // Lower-case the header block once for case-insensitive matching.
  std::string lower = headers;
  for (char& c : lower) c = char(tolower((unsigned char)c));
  if (lower.find("transfer-encoding: chunked") != std::string::npos)
    resp.body = dechunk(resp.body);

  resp.ok = true;
  return resp;
}

inline Response get(const std::string& url, int timeout_s = 5) {
  return request("GET", url, "", "", timeout_s);
}

}  // namespace cphttp
