// StaticRoute spec model: parse, validate, render dynamic config.
//
// Capability parity with the reference CRD
// (src/router-controller/api/v1alpha1/staticroute_types.go:28-107):
// serviceDiscovery, routingLogic, staticBackends/staticModels, routerRef
// (flattened to routerUrl — the agent probes an URL, not a k8s object),
// healthCheck{timeout,period,successThreshold,failureThreshold}, and
// configMapName. Rendering matches the Go reconcileConfigMap output
// (staticroute_controller.go:134-184) and the router's
// DynamicRouterConfig.from_json contract
// (production_stack_tpu/router/dynamic_config.py).
#pragma once

#include <cctype>
#include <set>
#include <string>

#include "json.hpp"

namespace cpagent {

struct HealthCheckConfig {
  int timeout_s = 5;
  int period_s = 10;
  int success_threshold = 1;
  int failure_threshold = 3;
};

struct StaticRouteSpec {
  std::string name;                    // resource name (from file or CR)
  std::string namespace_ = "default";  // k8s namespace (k8s mode)
  std::string service_discovery = "static";
  std::string routing_logic = "roundrobin";
  std::string static_backends;  // comma-separated URLs
  std::string static_models;    // comma-separated model names
  std::string session_key;      // optional, for session routing
  std::string router_url;       // optional; enables health probing
  std::string config_map_name;  // output name; default <name>-config
  HealthCheckConfig health;

  std::string config_name() const {
    return config_map_name.empty() ? name + "-config" : config_map_name;
  }
};

// The routing algorithms our router actually implements
// (production_stack_tpu/router/routing/logic.py RoutingLogic enum).
inline const std::set<std::string>& valid_routing_logics() {
  static const std::set<std::string> kValid = {
      "roundrobin", "session", "llq", "hra", "custom"};
  return kValid;
}

// Mirrors the router's _URL_RE (production_stack_tpu/utils/__init__.py:17):
// ^(https?)://([a-zA-Z0-9.\-_]+|\[ipv6\])(:\d{1,5})?(/.*)?$ — the agent
// must reject anything the router's parser would, or Ready=True lies.
inline bool is_valid_backend_url(const std::string& url) {
  size_t pos;
  if (url.rfind("http://", 0) == 0)
    pos = 7;
  else if (url.rfind("https://", 0) == 0)
    pos = 8;
  else
    return false;

  size_t host_start = pos;
  if (pos < url.size() && url[pos] == '[') {  // ipv6 literal
    ++pos;
    while (pos < url.size() &&
           (isxdigit((unsigned char)url[pos]) || url[pos] == ':'))
      ++pos;
    if (pos >= url.size() || url[pos] != ']' || pos == host_start + 1)
      return false;
    ++pos;
  } else {
    while (pos < url.size()) {
      char c = url[pos];
      if (isalnum((unsigned char)c) || c == '.' || c == '-' || c == '_')
        ++pos;
      else
        break;
    }
    if (pos == host_start) return false;
  }
  if (pos < url.size() && url[pos] == ':') {  // optional port
    ++pos;
    size_t digits = 0;
    while (pos < url.size() && isdigit((unsigned char)url[pos])) {
      ++pos;
      ++digits;
    }
    if (digits < 1 || digits > 5) return false;
  }
  return pos == url.size() || url[pos] == '/';
}

// Resource/ConfigMap names become path components (file mode) and URL
// segments (k8s mode); restrict to k8s-object-name characters so a
// malicious or mistyped name like "../.." can never escape the output
// dir (written AND deleted by the reconciler) or splice the API path.
inline bool is_safe_name(const std::string& n) {
  if (n.empty() || n == "." || n == "..") return false;
  for (char c : n) {
    if (!(isalnum((unsigned char)c) || c == '.' || c == '-' || c == '_'))
      return false;
  }
  return true;
}

struct ParseResult {
  bool ok = false;
  std::string error;
  StaticRouteSpec spec;
};

inline ParseResult parse_spec(const std::string& name,
                              const cpjson::ValuePtr& root) {
  ParseResult out;
  if (!root || !root->is_object()) {
    out.error = "spec must be a JSON object";
    return out;
  }
  // Accept both a bare spec and a CR-shaped {metadata:..., spec:...}.
  cpjson::ValuePtr spec = root->get("spec");
  if (!spec || !spec->is_object()) spec = root;

  StaticRouteSpec& s = out.spec;
  s.name = name;
  auto meta = root->get("metadata");
  if (meta && meta->is_object()) {
    std::string n = meta->get_string("name");
    if (!n.empty()) s.name = n;
    std::string ns = meta->get_string("namespace");
    if (!ns.empty()) s.namespace_ = ns;
  }
  if (s.name.empty()) {
    out.error = "spec has no name";
    return out;
  }
  if (!is_safe_name(s.name)) {
    out.error = "invalid resource name '" + s.name + "'";
    return out;
  }
  if (!is_safe_name(s.namespace_)) {
    out.error = "invalid namespace '" + s.namespace_ + "'";
    return out;
  }

  s.service_discovery = spec->get_string("serviceDiscovery", "static");
  if (s.service_discovery != "static") {
    out.error = "serviceDiscovery must be 'static', got '" +
                s.service_discovery + "'";
    return out;
  }
  s.routing_logic = spec->get_string("routingLogic", "roundrobin");
  // The reference CRD enum says least_loaded; our router calls it llq.
  if (s.routing_logic == "least_loaded") s.routing_logic = "llq";
  if (!valid_routing_logics().count(s.routing_logic)) {
    out.error = "unknown routingLogic '" + s.routing_logic + "'";
    return out;
  }

  // staticBackends / staticModels: comma-separated string or JSON array.
  auto join = [](const cpjson::ValuePtr& v) {
    std::string joined;
    for (const auto& e : v->arr) {
      if (!e->is_string()) continue;
      if (!joined.empty()) joined += ',';
      joined += e->str;
    }
    return joined;
  };
  auto backends = spec->get("staticBackends");
  if (backends && backends->is_array())
    s.static_backends = join(backends);
  else
    s.static_backends = spec->get_string("staticBackends");
  // Validate each backend the way the router's
  // parse_comma_separated_urls will (production_stack_tpu/utils): a
  // Ready=True status must imply the router can actually apply the
  // config, not silently reject and pin the bad digest.
  {
    std::istringstream ss(s.static_backends);
    std::string url;
    while (std::getline(ss, url, ',')) {
      size_t a = url.find_first_not_of(" \t");
      size_t b = url.find_last_not_of(" \t");
      if (a == std::string::npos) continue;
      url = url.substr(a, b - a + 1);
      if (!is_valid_backend_url(url)) {
        out.error = "invalid backend URL '" + url + "'";
        return out;
      }
    }
  }
  auto models = spec->get("staticModels");
  if (models && models->is_array())
    s.static_models = join(models);
  else
    s.static_models = spec->get_string("staticModels");
  if (s.static_backends.empty()) {
    out.error = "staticBackends is required";
    return out;
  }
  if (s.static_models.empty()) {
    out.error = "staticModels is required";
    return out;
  }

  s.session_key = spec->get_string("sessionKey");
  if (s.routing_logic == "session" && s.session_key.empty()) {
    out.error = "routingLogic 'session' requires sessionKey";
    return out;
  }
  s.router_url = spec->get_string("routerUrl");
  s.config_map_name = spec->get_string("configMapName");
  if (!s.config_map_name.empty() && !is_safe_name(s.config_map_name)) {
    out.error = "invalid configMapName '" + s.config_map_name + "'";
    return out;
  }

  auto hc = spec->get("healthCheck");
  if (hc && hc->is_object()) {
    auto clamp_pos = [](double v, int dflt) {
      int i = int(v);
      return i >= 1 ? i : dflt;
    };
    s.health.timeout_s = clamp_pos(hc->get_number("timeoutSeconds", 5), 5);
    s.health.period_s = clamp_pos(hc->get_number("periodSeconds", 10), 10);
    s.health.success_threshold =
        clamp_pos(hc->get_number("successThreshold", 1), 1);
    s.health.failure_threshold =
        clamp_pos(hc->get_number("failureThreshold", 3), 3);
  }
  out.ok = true;
  return out;
}

// Renders the dynamic_config.json payload the router's
// DynamicConfigWatcher consumes.
inline std::string render_dynamic_config(const StaticRouteSpec& s) {
  auto v = cpjson::Value::make_object();
  v->set_string("service_discovery", s.service_discovery);
  v->set_string("routing_logic", s.routing_logic);
  v->set_string("static_backends", s.static_backends);
  v->set_string("static_models", s.static_models);
  if (!s.session_key.empty()) v->set_string("session_key", s.session_key);
  return cpjson::dump(v);
}

}  // namespace cpagent
